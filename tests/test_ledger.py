"""The signed contribution ledger (receipt-backed swarm accounting).

Acceptance (all virtual-time, deterministic): the ``ledger`` simulator
scenario — 12 peers, one inflating its cumulative claim 10x, one serving
most checkpoint bytes — credits every honest peer within 5% of scripted
ground truth, caps the inflator at its receipt-supported total (x slack)
with a named ``overclaim`` discrepancy, renders both on the volunteer
leaderboard (``runlog_summary --contributions`` and the ``swarm_watch
--brief`` one-liner), and the fold replays BIT-IDENTICALLY from the
dumped ledger JSONL and from per-peer event logs. Hostile inputs (jammed
/ truncated JSONL, pre-ledger fleets, empty swarms) degrade with named
coverage notes, never false discrepancies or crashes.
"""
import copy
import importlib.util
import json
from pathlib import Path

import pytest
from pydantic import ValidationError

from dedloc_tpu.averaging.matchmaking import Member
from dedloc_tpu.simulator.scenarios import run_scenario
from dedloc_tpu.telemetry.ledger import (
    DEFAULT_SLACK,
    MAX_WITNESS,
    ContributionClaim,
    RoundReceipt,
    WitnessEntry,
    fold_ledger,
    leaderboard,
    ledger_key,
    parse_claims,
    parse_receipts,
    parse_round_step,
    receipt_from_group,
    receipts_key,
    subkey_owner_id,
    update_witness,
)

pytestmark = pytest.mark.simulator

_TOOLS = Path(__file__).resolve().parent.parent / "tools"


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(name, _TOOLS / f"{name}.py")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


# order matters: swarm_watch resolves `runlog_summary` via sys.modules
runlog_summary = _load_tool("runlog_summary")
import sys  # noqa: E402

sys.modules.setdefault("runlog_summary", runlog_summary)
swarm_watch = _load_tool("swarm_watch")


# ------------------------------------------------------------- unit: schema


def _claim(**over):
    base = dict(peer="aa" * 16, samples=100, rounds=5, train_seconds=60.0,
                bytes_served=0, time=1000.0)
    base.update(over)
    return ContributionClaim.model_validate(base)


def _receipt(**over):
    base = dict(signer="aa", round_id="step7", step=7, leg="flat",
                members=["aa", "bb"], weights=[32.0, 32.0],
                witness={"bb": {"samples": 32.0, "rounds": 1}}, time=1000.0)
    base.update(over)
    return RoundReceipt.model_validate(base)


def test_claim_schema_accepts_and_rejects():
    claim = _claim()
    assert claim.samples == 100
    for bad in (
        {"samples": -1},
        {"rounds": -2},
        {"bytes_served": -5},
        {"train_seconds": float("nan")},
        {"train_seconds": -1.0},
        {"time": float("inf")},
        {"peer": ""},
        {"peer": "x" * 200},
        {"samples": 1.5},  # StrictInt: a float smuggled in is rejected
    ):
        with pytest.raises(ValidationError):
            _claim(**bad)


def test_receipt_schema_accepts_and_rejects():
    receipt = _receipt()
    assert receipt.witness["bb"].samples == 32.0
    for bad in (
        {"leg": "wan"},  # only flat/gossip/clique legs exist
        {"members": ["bb", "aa"]},  # must be strictly sorted
        {"members": ["aa", "aa"]},  # and unique
        {"members": ["aa"]},  # a receipt needs a counterparty
        {"weights": [32.0]},  # alignment
        {"weights": [-1.0, 2.0]},
        {"signer": "zz"},  # signer must be a member
        {"step": -2},
        {"witness": {f"p{i}": {"samples": 1.0, "rounds": 1}
                     for i in range(MAX_WITNESS + 1)}},
    ):
        with pytest.raises(ValidationError):
            _receipt(**bad)


def test_parse_drops_malformed_keeps_valid():
    good = _claim().model_dump()
    claims = parse_claims([
        (bytes.fromhex(good["peer"]), good),
        (b"\xbb", {"peer": "bb", "samples": -3}),  # malformed
        (b"\xcc", "not a dict"),
    ])
    assert [c.peer for c in claims] == [good["peer"]]
    receipts = parse_receipts([
        (b"\xaa", _receipt().model_dump()),  # signer "aa" under its slot
        (b"\xbb", {"signer": "bb"}),
    ])
    assert len(receipts) == 1


# ------------------------------------------------- unit: identity binding


def test_subkey_owner_id_binds_rsa_tag_and_raw_bytes():
    from dedloc_tpu.core.auth import peer_id_from_public_key
    from dedloc_tpu.dht.crypto import RSAPrivateKey
    from dedloc_tpu.dht.validation import OWNER_PREFIX

    key = RSAPrivateKey()
    tag = OWNER_PREFIX + key.public_bytes()
    assert subkey_owner_id(tag) == (
        peer_id_from_public_key(key.public_bytes()).hex()
    )
    assert subkey_owner_id(b"\xaa\xbb") == "aabb"
    assert subkey_owner_id(12345) is None  # unbindable shape


def test_parse_claims_rejects_spoofed_peer():
    """A claim naming a victim, published under the attacker's own slot,
    never reaches the fold — the victim's totals cannot be overridden."""
    victim = "aa" * 16
    forged = _claim(samples=0, time=9999.0).model_dump()  # peer = victim
    assert parse_claims([(b"\xee" * 16, forged)]) == []
    # and the rsa owner tag binds through the key digest, both ways
    from dedloc_tpu.core.auth import peer_id_from_public_key
    from dedloc_tpu.dht.crypto import RSAPrivateKey
    from dedloc_tpu.dht.validation import OWNER_PREFIX

    key = RSAPrivateKey()
    tag = OWNER_PREFIX + key.public_bytes()
    me = peer_id_from_public_key(key.public_bytes()).hex()
    ok = _claim(peer=me).model_dump()
    assert [c.peer for c in parse_claims([(tag, ok)])] == [me]
    assert parse_claims([(tag, _claim(peer=victim).model_dump())]) == []


def test_parse_receipts_rejects_laundered_witness():
    """The attack the binding exists for: a receipt published under the
    attacker's OWN valid slot whose ``signer`` is a fabricated id and
    whose witness table credits the attacker — without the binding, the
    fold's self-witness skip (peer == signer) is bypassed and the
    attacker's inflated claim becomes fully receipt-supported."""
    attacker = "ee" * 16
    fabricated = "ff" * 16
    members = sorted([attacker, fabricated])
    forged = RoundReceipt(
        signer=fabricated, round_id="r0", step=-1, leg="flat",
        members=members, weights=[1e9, 1e9],
        witness={attacker: {"samples": 1e9, "rounds": 1}},
        time=1000.0,
    ).model_dump()
    assert parse_receipts([(bytes.fromhex(attacker), forged)]) == []
    # and folding what parse admits credits the attacker NOTHING
    folded = fold_ledger(
        None,
        [_claim(peer=attacker, samples=10**9)],
        parse_receipts([(bytes.fromhex(attacker), forged)]),
        now=2000.0,
    )
    assert folded["peers"][attacker]["credited_samples"] == 10**9  # pre-
    # ledger only because NO receipt survived; with any honest receipt
    # present the attacker is unwitnessed:
    honest = _receipt(signer="aa", members=["aa", "bb"],
                      witness={"bb": {"samples": 32.0, "rounds": 1}})
    folded = fold_ledger(
        None, [_claim(peer=attacker, samples=10**9)], [honest], now=2000.0,
    )
    assert folded["peers"][attacker]["credited_samples"] == 0
    assert folded["peers"][attacker]["discrepancy"]["kind"] == "unwitnessed"


def test_parse_round_step():
    assert parse_round_step("step42") == 42
    assert parse_round_step("step_7") == 7
    assert parse_round_step("avground-0003") == -1
    assert parse_round_step("") == -1


def test_keys():
    assert ledger_key("exp") == "exp_contribution_ledger"
    assert receipts_key("exp") == "exp_round_receipts"


# ---------------------------------------------------------- unit: witness


def test_update_witness_accumulates_and_bounds():
    witness = {}
    update_witness(witness, [("bb", 32.0), ("cc", 16.0)])
    update_witness(witness, [("bb", 32.0)])
    assert witness["bb"] == {"samples": 64.0, "rounds": 2}
    assert witness["cc"] == {"samples": 16.0, "rounds": 1}
    # bound: the smallest-sample tail is dropped, top entries kept
    update_witness(
        witness,
        [(f"p{i:04d}", 1000.0 + i) for i in range(MAX_WITNESS + 10)],
    )
    assert len(witness) == MAX_WITNESS
    assert "p0009" not in witness  # smallest of the big batch, evicted
    assert "cc" not in witness  # tiny witness total, evicted first


def test_receipt_from_group_excludes_self_from_witness():
    witness = {}
    receipt = receipt_from_group(
        "bb", "step3", 3, "flat",
        [("bb", 32.0), ("aa", 16.0), ("cc", 8.0)], witness,
    )
    assert receipt.members == ["aa", "bb", "cc"]  # sorted
    assert receipt.weights == [16.0, 32.0, 8.0]  # aligned to members
    assert set(receipt.witness) == {"aa", "cc"}  # never the signer
    assert witness["aa"] == {"samples": 16.0, "rounds": 1}


# ------------------------------------------------------------- unit: fold


def _w(samples, rounds=1):
    return WitnessEntry(samples=float(samples), rounds=int(rounds))


def test_fold_pre_ledger_credits_as_claimed():
    folded = fold_ledger(None, [_claim(samples=500)], [], now=2000.0)
    entry = folded["peers"]["aa" * 16]
    assert entry["coverage"] == "pre-ledger"
    assert entry["credited_samples"] == 500
    assert entry["discrepancy"] is None
    assert folded["discrepancies"] == 0


def test_fold_overclaim_capped_and_named():
    receipt = _receipt(signer="bb", members=["aa" * 16, "bb"],
                       weights=[100.0, 100.0],
                       witness={"aa" * 16: {"samples": 100.0, "rounds": 5}})
    folded = fold_ledger(
        None, [_claim(samples=1000, rounds=5)], [receipt], now=2000.0,
    )
    entry = folded["peers"]["aa" * 16]
    assert entry["coverage"] == "receipts"
    assert entry["credited_samples"] == int(100 * DEFAULT_SLACK)
    assert entry["discrepancy"]["kind"] == "overclaim"
    assert entry["discrepancy"]["ratio"] == 10.0


def test_fold_supported_is_max_not_sum():
    """Two signers witnessing the same cumulative total must not add up —
    witness tables are cumulative maxima over shared rounds."""
    mk = lambda signer: _receipt(  # noqa: E731
        signer=signer, members=[signer, "pp"], weights=[10.0, 10.0],
        witness={"pp": {"samples": 60.0, "rounds": 3}},
    )
    folded = fold_ledger(
        None, [_claim(peer="pp", samples=120, rounds=3)],
        [mk("aa"), mk("bb")], now=2000.0,
    )
    entry = folded["peers"]["pp"]
    assert entry["supported_samples"] == 60.0  # max, not 120
    assert entry["credited_samples"] == int(60 * DEFAULT_SLACK)


def test_fold_self_witness_does_not_support():
    receipt = _receipt(
        signer="aa" * 16, members=["aa" * 16, "bb"], weights=[9.0, 9.0],
        witness={"bb": {"samples": 9.0, "rounds": 1}},
    )
    folded = fold_ledger(None, [_claim(samples=90)], [receipt], now=2000.0)
    entry = folded["peers"]["aa" * 16]
    # receipts exist, but only the peer's OWN — it stays unwitnessed
    assert entry["coverage"] == "unwitnessed"
    assert entry["credited_samples"] == 0
    assert entry["discrepancy"]["kind"] == "unwitnessed"


def test_fold_receipts_only_credits_witnessed_total():
    receipt = _receipt(signer="bb", members=["bb", "cc"],
                       weights=[5.0, 5.0],
                       witness={"cc": {"samples": 40.0, "rounds": 4}})
    folded = fold_ledger(None, [], [receipt], now=2000.0)
    entry = folded["peers"]["cc"]
    assert entry["coverage"] == "receipts-only"
    assert entry["credited_samples"] == 40
    assert entry["credited_rounds"] == 4
    assert entry["discrepancy"] is None


def test_fold_prev_carryover_marked_stale():
    prev = fold_ledger(None, [_claim(samples=500)], [], now=2000.0)
    folded = fold_ledger(prev, [], [], now=3000.0)
    entry = folded["peers"]["aa" * 16]
    assert entry["coverage"] == "stale"
    assert entry["credited_samples"] == 500
    # and a returning live record supersedes the stale carry-over
    folded2 = fold_ledger(folded, [_claim(samples=600)], [], now=4000.0)
    assert folded2["peers"]["aa" * 16]["credited_samples"] == 600
    assert folded2["peers"]["aa" * 16]["coverage"] == "pre-ledger"


def test_fold_receipt_expiry_carries_support_for_present_peers():
    """Receipts expire (~300s) long before a long-running peer's claims
    stop refreshing: the prev fold's supported totals floor the current
    ones, so credit stays monotone — no flip to 0, no false
    'unwitnessed' flag — while the cap still holds against inflation."""
    receipt = _receipt(
        signer="bb", members=["aa" * 16, "bb"], weights=[100.0, 100.0],
        witness={"aa" * 16: {"samples": 100.0, "rounds": 5}},
    )
    first = fold_ledger(
        None, [_claim(samples=100, rounds=5)], [receipt], now=2000.0,
    )
    assert first["peers"]["aa" * 16]["coverage"] == "receipts"
    # all receipts expired; the peer is still present and claims on
    second = fold_ledger(
        first, [_claim(samples=110, rounds=5, time=2500.0)], [], now=3000.0,
    )
    entry = second["peers"]["aa" * 16]
    assert entry["coverage"] == "carried"
    assert entry["credited_samples"] == 110  # within slack of the floor
    assert entry["supported_samples"] == 100.0
    assert entry["discrepancy"] is None
    # the carried floor still CAPS: inflation cannot ride the expiry
    third = fold_ledger(
        second, [_claim(samples=100000, time=2600.0)], [], now=4000.0,
    )
    entry = third["peers"]["aa" * 16]
    assert entry["credited_samples"] == int(100 * DEFAULT_SLACK)
    assert entry["discrepancy"]["kind"] == "overclaim"


def test_fold_latest_claim_per_peer_wins():
    folded = fold_ledger(
        None,
        [_claim(samples=100, time=1000.0), _claim(samples=200, time=1500.0)],
        [], now=2000.0,
    )
    assert folded["peers"]["aa" * 16]["claimed_samples"] == 200


def test_leaderboard_ranking_and_share():
    folded = fold_ledger(
        None,
        [_claim(peer="aa", samples=300), _claim(peer="bb", samples=100),
         _claim(peer="cc", samples=100, bytes_served=999)],
        [], now=2000.0,
    )
    board = leaderboard(folded)
    assert [e["peer"] for e in board] == ["aa", "cc", "bb"]  # bytes break tie
    assert board[0]["share"] == 0.6
    assert sum(e["share"] for e in board) == pytest.approx(1.0)


# -------------------------------------------------- member wire back-compat


def test_member_weight_rides_envelope_and_defaults():
    m = Member(peer_id=b"p1", endpoint=("h", 1), bandwidth=10.0,
               weight=32.0)
    assert Member.unpack(m.pack()).weight == 32.0
    # a pre-ledger peer's 6-field envelope unpacks with weight 0.0
    legacy = Member.unpack(m.pack()[:6])
    assert legacy.weight == 0.0 and legacy.peer_id == b"p1"


# ------------------------------------------------------ scenario acceptance


LEDGER_SPEC = {
    "scenario": "ledger", "peers": 12, "avg_rounds": 6, "seed": 0,
    "boundaries": 2, "samples_per_boundary": 16, "window_s": 5.0,
}


@pytest.fixture(scope="module")
def ledger_run(tmp_path_factory):
    out = tmp_path_factory.mktemp("ledger_scenario")
    report = run_scenario(copy.deepcopy(LEDGER_SPEC), out_dir=str(out))
    return report, out


def test_scenario_honest_peers_within_5pct(ledger_run):
    report, _out = ledger_run
    inflator = report["inflate"]["peer"]
    for label, tr in report["truth"].items():
        if tr["peer"] == inflator:
            continue
        entry = report["ledger"]["peers"][tr["peer"]]
        assert entry["credited_samples"] == pytest.approx(
            tr["samples"], rel=0.05
        ), label
        assert entry["discrepancy"] is None, label


def test_scenario_inflator_capped_with_named_discrepancy(ledger_run):
    report, _out = ledger_run
    inflator = report["inflate"]["peer"]
    truth = next(
        tr for tr in report["truth"].values() if tr["peer"] == inflator
    )
    entry = report["ledger"]["peers"][inflator]
    slack = report["ledger"]["slack"]
    assert entry["claimed_samples"] == truth["samples"] * 10
    # capped at the receipt-supported total x slack, nothing more
    assert entry["credited_samples"] <= int(truth["samples"] * slack) + 1
    assert entry["discrepancy"]["kind"] == "overclaim"
    assert entry["discrepancy"]["ratio"] == pytest.approx(10.0, rel=0.05)
    assert report["ledger"]["discrepancies"] == 1


def test_scenario_leaderboard_renders_both(ledger_run):
    report, _out = ledger_run
    board = report["leaderboard"]
    flagged = [e for e in board if e["discrepancy"]]
    assert [e["peer"] for e in flagged] == [report["inflate"]["peer"]]
    served = max(board, key=lambda e: e["bytes_served"])
    assert served["peer"] == report["serve"]["peer"]
    assert served["bytes_served"] == report["serve"]["bytes"]


def test_scenario_replay_bit_identical(ledger_run):
    """The dumped ledger JSONL replays to the identical state, and an
    identical re-run of the spec reproduces the dump byte for byte."""
    report, out = ledger_run
    rows = runlog_summary.load_jsonl_rows([str(out / "ledger.jsonl")])
    assert json.dumps(rows[-1]["ledger"], sort_keys=True) == json.dumps(
        report["ledger"], sort_keys=True
    )
    rerun = run_scenario(copy.deepcopy(LEDGER_SPEC))
    assert json.dumps(rerun["ledger_rows"], sort_keys=True) == json.dumps(
        report["ledger_rows"], sort_keys=True
    )


def test_contributions_recorded_vs_replayed_agree(ledger_run):
    """--contributions over the coordinator-shaped ledger JSONL (recorded)
    and over per-peer event logs (refolded from ledger.claim/ledger.receipt
    events) must produce the same leaderboard."""
    report, out = ledger_run
    recorded = runlog_summary.contributions_data(
        runlog_summary.load_jsonl_rows([str(out / "ledger.jsonl")])
    )
    event_logs = sorted(str(p) for p in out.glob("peer-*.jsonl"))
    replayed = runlog_summary.contributions_data(
        runlog_summary.load_jsonl_rows(event_logs)
    )
    assert recorded["source"] == "recorded"
    assert replayed["source"] == "replayed"
    assert recorded["leaderboard"] == replayed["leaderboard"]
    assert recorded["discrepancies"] == replayed["discrepancies"] == 1


def test_contributions_text_rendering(ledger_run, capsys):
    report, out = ledger_run
    rows = runlog_summary.load_jsonl_rows([str(out / "ledger.jsonl")])
    runlog_summary.print_contributions(rows)
    text = capsys.readouterr().out
    assert "volunteer leaderboard" in text
    assert "OVERCLAIM" in text
    assert report["inflate"]["peer"][:12] in text
    assert report["serve"]["peer"][:12] in text


def test_swarm_watch_brief_ledger_line(ledger_run, capsys):
    report, out = ledger_run
    swarm_watch.ledger_brief(
        runlog_summary.load_jsonl_rows([str(out / "ledger.jsonl")])
    )
    line = capsys.readouterr().out.strip()
    assert line.startswith("ledger: top ")
    assert "1 discrepancy(ies)" in line
    assert report["inflate"]["peer"][:12] in line


@pytest.mark.slow
def test_scenario_multi_seed_sweep():
    """Heavyweight cross-seed invariants: the credit formula's guarantees
    hold under different matchmaking timings, not just seed 0."""
    for seed in (1, 2, 3):
        spec = {**copy.deepcopy(LEDGER_SPEC), "seed": seed}
        report = run_scenario(spec)
        ledger = report["ledger"]
        assert ledger["discrepancies"] == 1
        inflator = report["inflate"]["peer"]
        assert ledger["peers"][inflator]["discrepancy"]["kind"] == "overclaim"
        slack = ledger["slack"]
        for tr in report["truth"].values():
            entry = ledger["peers"][tr["peer"]]
            # NOBODY is ever credited above slack x their true work
            assert entry["credited_samples"] <= tr["samples"] * slack + 1


# ------------------------------------------------ receipts over the sim wire


def test_weight_rides_real_matchmaking_envelope(sim_swarm):
    """The declared weight survives the REAL matchmaking wire (pack →
    sim DHT RPC → unpack): every member of a formed group reads every
    other member's declared weight off the verified join envelope."""
    engine, swarm = sim_swarm(4)
    weights = {}
    for i, peer in enumerate(swarm.alive_peers()):
        mm = peer.attach_matchmaking(
            "wiretest", target_group_size=4, averaging_expiration=5.0
        )
        mm.declared_weight = 10.0 * (i + 1)
        weights[peer.node.node_id.to_bytes().hex()] = mm.declared_weight

    async def _form():
        import asyncio

        async def one(p):
            try:
                return await p.matchmaking.form_group("wt-round-0")
            except Exception:  # noqa: BLE001 — asserted below via None
                return None

        return await asyncio.gather(
            *(one(p) for p in swarm.alive_peers())
        )

    groups = [g for g in engine.run(_form()) if g is not None]
    assert groups, "no group formed"
    full = max(groups, key=lambda g: len(g.members))
    assert len(full.members) >= 2
    for m in full.members:
        assert m.weight == weights[m.peer_id.hex()]
    # and the receipt built from that envelope carries the declarations
    signer = full.members[0].peer_id.hex()
    receipt = receipt_from_group(
        signer, "wt-round-0", -1, "flat",
        [(m.peer_id.hex(), float(m.weight)) for m in full.members], {},
    )
    for m in full.members:
        if m.peer_id.hex() != signer:
            assert receipt.witness[m.peer_id.hex()].samples == m.weight


# ------------------------------------------------------- hostile inputs


def _ledger_row(t, step, peers):
    folded = fold_ledger(None, peers, [], now=t)
    return {"t": folded["t"], "step": step, "ledger": folded}


def test_contributions_jammed_and_truncated_jsonl(tmp_path):
    """Two writer-jammed rows on one line are salvaged object-by-object;
    a torn final line yields the last COMPLETE fold."""
    row1 = _ledger_row(1000.0, 0, [_claim(samples=100, time=999.0)])
    row2 = _ledger_row(2000.0, 1, [_claim(samples=250, time=1999.0)])
    path = tmp_path / "ledger.jsonl"
    torn = json.dumps(_ledger_row(3000.0, 2, [_claim(samples=999)]))
    path.write_text(
        json.dumps(row1) + json.dumps(row2) + "\n" + torn[: len(torn) // 2]
    )
    doc = runlog_summary.contributions_data(
        runlog_summary.load_jsonl_rows([str(path)])
    )
    assert doc["source"] == "recorded"
    # last COMPLETE state wins: the torn 999-sample row never surfaces
    assert doc["leaderboard"][0]["claimed_samples"] == 250
    assert doc["discrepancies"] == 0


def test_contributions_pre_ledger_peers_kept_no_false_flags(tmp_path):
    """A fleet with claims but NO receipts anywhere (pre-ledger builds):
    every row is kept, credited as claimed, flagged by a coverage note —
    and there are ZERO false discrepancies."""
    path = tmp_path / "events.jsonl"
    with path.open("w") as f:
        for i in range(3):
            f.write(json.dumps({
                "t": 1000.0 + i, "event": "ledger.claim",
                "peer": f"p{i:02d}", "samples": 64 * (i + 1), "rounds": 2,
                "train_seconds": 30.0, "bytes_served": 0,
            }) + "\n")
    doc = runlog_summary.contributions_data(
        runlog_summary.load_jsonl_rows([str(path)])
    )
    assert doc["source"] == "replayed"
    assert len(doc["leaderboard"]) == 3
    assert all(e["coverage"] == "pre-ledger" for e in doc["leaderboard"])
    assert all(e["discrepancy"] is None for e in doc["leaderboard"])
    assert doc["discrepancies"] == 0
    assert any("predate receipts" in n for n in doc["notes"])


def test_contributions_malformed_events_dropped_with_note(tmp_path):
    path = tmp_path / "events.jsonl"
    with path.open("w") as f:
        f.write(json.dumps({
            "t": 1000.0, "event": "ledger.claim", "peer": "good",
            "samples": 64, "rounds": 2, "train_seconds": 30.0,
            "bytes_served": 0,
        }) + "\n")
        f.write(json.dumps({
            "t": 1001.0, "event": "ledger.claim", "peer": "evil",
            "samples": -5, "rounds": 2, "train_seconds": 30.0,
            "bytes_served": 0,
        }) + "\n")
        f.write(json.dumps({
            "t": 1002.0, "event": "ledger.receipt", "signer": "x",
            "members": ["x"], "weights": [], "witness": {},
        }) + "\n")
    doc = runlog_summary.contributions_data(
        runlog_summary.load_jsonl_rows([str(path)])
    )
    assert [e["peer"] for e in doc["leaderboard"]] == ["good"]
    assert any("malformed" in n for n in doc["notes"])


def test_contributions_empty_swarm_exits_helpfully(tmp_path):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(SystemExit) as exc:
        runlog_summary.contributions_data(
            runlog_summary.load_jsonl_rows([str(empty)])
        )
    assert "no contribution-ledger records" in str(exc.value)
    # metrics-era rows (no ledger anything) get the same guidance
    metrics = tmp_path / "metrics.jsonl"
    metrics.write_text(json.dumps({"step": 1, "loss": 2.0}) + "\n")
    with pytest.raises(SystemExit) as exc:
        runlog_summary.contributions_data(
            runlog_summary.load_jsonl_rows([str(metrics)])
        )
    assert "pre-ledger" in str(exc.value)


def test_ledger_brief_quiet_without_ledger_rows(capsys):
    swarm_watch.ledger_brief([{"step": 1, "loss": 2.0}])
    assert capsys.readouterr().out == ""


# ----------------------------------------------- coordinator fold wiring


def test_coordinator_idle_claim_refresh_does_not_grow_log(
    tmp_path, monkeypatch
):
    """A live-but-idle swarm re-publishes claims every ~30s with only the
    timestamps moving: those folds must NOT append new ledger rows — only
    a change of substance (totals, coverage, discrepancies) does."""
    import types

    from dedloc_tpu.roles import coordinator as co

    feeds = iter([
        ([_claim(samples=100, time=1000.0, train_seconds=60.0)], []),
        # refresh tick: same totals, newer stamps only
        ([_claim(samples=100, time=1030.0, train_seconds=90.0)], []),
        # real progress: a new row is due
        ([_claim(samples=200, time=1060.0, train_seconds=120.0)], []),
    ])
    monkeypatch.setattr(
        co, "_fetch_ledger_records", lambda dht, prefix: next(feeds)
    )
    extra = types.SimpleNamespace(
        ledger_slack=DEFAULT_SLACK,
        ledger_log_path=str(tmp_path / "coordinator_ledger.jsonl"),
    )
    state = {"prev": None, "flagged": {}}
    for i, t in enumerate((1000.0, 1030.0, 1060.0)):
        co._ledger_fold(None, "exp", extra, state, t, i)
    rows = [
        json.loads(line)
        for line in Path(extra.ledger_log_path).read_text().splitlines()
    ]
    assert len(rows) == 2  # the timestamp-only refresh appended nothing
    assert rows[-1]["ledger"]["peers"]["aa" * 16]["claimed_samples"] == 200
    # the in-memory prev still advanced to the freshest stamps
    assert state["prev"]["peers"]["aa" * 16]["last_claim_t"] == 1060.0


def test_coordinator_prev_ledger_restart_safe(tmp_path):
    from dedloc_tpu.roles.coordinator import _prev_ledger

    path = tmp_path / "coordinator_ledger.jsonl"
    assert _prev_ledger(str(path)) is None  # not-yet-created log
    row = _ledger_row(1000.0, 3, [_claim(samples=100)])
    torn = json.dumps(_ledger_row(2000.0, 4, [_claim(samples=500)]))
    path.write_text(json.dumps(row) + "\n" + torn[: len(torn) // 2])
    prev = _prev_ledger(str(path))
    assert prev is not None
    assert prev["peers"]["aa" * 16]["claimed_samples"] == 100
