"""ZeRO-1 optimizer-state sharding: layout, memory math, and numerical
equivalence of sharded vs replicated updates."""
import jax
import jax.numpy as jnp
import numpy as np

from dedloc_tpu.optim import lamb
from dedloc_tpu.parallel.mesh import make_mesh
from dedloc_tpu.parallel.train_step import TrainState, make_apply_step
from dedloc_tpu.parallel.zero import (
    _spec_for_leaf,
    opt_state_bytes_per_device,
    opt_state_shardings,
    shard_opt_state,
)


def _params(rng):
    return {
        "dense": {"kernel": jnp.asarray(rng.standard_normal((64, 128)),
                                        jnp.float32),
                  "bias": jnp.asarray(rng.standard_normal(128), jnp.float32)},
        "emb": jnp.asarray(rng.standard_normal((80, 32)), jnp.float32),
    }


def test_spec_shards_largest_divisible_dim():
    mesh = make_mesh(8)
    assert _spec_for_leaf(jnp.zeros((64, 128)), mesh, "data") == \
        jax.sharding.PartitionSpec(None, "data")
    assert _spec_for_leaf(jnp.zeros((80, 32)), mesh, "data") == \
        jax.sharding.PartitionSpec("data", None)
    # indivisible and scalar leaves replicate
    assert _spec_for_leaf(jnp.zeros((7, 3)), mesh, "data") == \
        jax.sharding.PartitionSpec()
    assert _spec_for_leaf(jnp.zeros([]), mesh, "data") == \
        jax.sharding.PartitionSpec()


def test_sharded_update_matches_replicated(rng):
    mesh = make_mesh(8)
    params = _params(rng)
    tx = lamb(learning_rate=1e-2, weight_decay=0.01)
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
        params,
    )

    # replicated baseline (fresh buffers: apply donates its input state)
    state_r = TrainState.create(jax.tree.map(jnp.array, params), tx)
    new_r = make_apply_step(tx)(state_r, grads)

    # ZeRO-sharded state
    state_z = TrainState.create(jax.tree.map(jnp.array, params), tx)
    opt_sh = opt_state_shardings(state_z.opt_state, mesh)
    state_z = state_z.replace(
        opt_state=shard_opt_state(state_z.opt_state, mesh)
    )
    apply_z = make_apply_step(tx, mesh=mesh, opt_state_sharding=opt_sh)
    new_z = apply_z(state_z, grads)

    for a, b in zip(jax.tree.leaves(jax.device_get(new_r.params)),
                    jax.tree.leaves(jax.device_get(new_z.params))):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)
    # the new opt state keeps the sharded layout
    for leaf, sh in zip(jax.tree.leaves(new_z.opt_state),
                        jax.tree.leaves(opt_sh)):
        assert leaf.sharding.is_equivalent_to(sh, leaf.ndim)


def test_opt_state_bytes_per_device(rng):
    mesh = make_mesh(8)
    params = _params(rng)
    tx = lamb(learning_rate=1e-2)
    opt_state = tx.init(params)
    full = sum(
        int(np.prod(l.shape or (1,))) * l.dtype.itemsize
        for l in jax.tree.leaves(opt_state)
    )
    per_dev = opt_state_bytes_per_device(opt_state, mesh)
    # moments dominate and divide by 8; scalars replicate
    assert per_dev < full / 4
