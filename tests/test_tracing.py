"""Cross-peer distributed tracing + per-link network telemetry.

Tentpole acceptance (ISSUE 7): a 3-peer loopback all-reduce under an
injected asymmetric-latency link must produce (1) ONE stitched cross-peer
trace whose ``runlog_summary --trace`` critical path names the slow LINK
(not just the slow peer), and (2) a ``--topology`` link matrix whose
RTT/goodput estimates rank that link worst — while telemetry disabled adds
ZERO bytes to the wire framing.

Satellite: a leader-death + slow-link replay on FakeClock/FaultSchedule
whose stitched trace attributes the stall to the injected link and REPORTS
the orphaned child spans (a parent whose peer died / whose log was never
collected) instead of silently dropping them.

Everything here is loopback with tiny vectors; injected delays are ~0.1s
and overlap, per memory/tier1-timing-budget.md.
"""
import asyncio
import contextlib
import importlib.util
import io
import json
from pathlib import Path

import numpy as np
import pytest

from dedloc_tpu.averaging.allreduce import GroupAllReduce
from dedloc_tpu.averaging.matchmaking import Matchmaking
from dedloc_tpu.dht import protocol
from dedloc_tpu.dht.node import DHTNode
from dedloc_tpu.dht.protocol import RPCClient, RPCServer
from dedloc_tpu.telemetry import Telemetry, registry
from dedloc_tpu.telemetry.links import LinkTable, endpoint_key
from dedloc_tpu.testing.faults import FakeClock, FaultSchedule

from tests.test_averaging import _allreduce_swarm

pytestmark = pytest.mark.telemetry

_spec = importlib.util.spec_from_file_location(
    "runlog_summary_for_tracing",
    Path(__file__).resolve().parent.parent / "tools" / "runlog_summary.py",
)
runlog_summary = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(runlog_summary)


def _render(fn, *args):
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        fn(*args)
    return out.getvalue()


# ------------------------------------------------------- linkage unit tests


def test_span_linkage_and_deterministic_trace_seed():
    """Nested spans share one trace and chain parent ids; two peers seeding
    from the same round_id derive the SAME trace id with no handshake."""
    a, b = Telemetry(peer="a"), Telemetry(peer="b")
    with a.span("avg.round", trace_seed="step7") as _:
        a.event("point", k=1)
        with a.span("mm.form_group"):
            pass
    with b.span("avg.round", trace_seed="step7"):
        pass
    ev_a = {e["event"]: e for e in a.events}
    ev_b = {e["event"]: e for e in b.events}
    tid = registry.trace_id_for("step7")
    assert ev_a["avg.round"]["trace"] == tid
    assert ev_b["avg.round"]["trace"] == tid
    assert ev_a["avg.round"]["span"] != ev_b["avg.round"]["span"]
    # nesting: inner span and point event parent on the outer span
    assert ev_a["mm.form_group"]["parent"] == ev_a["avg.round"]["span"]
    assert ev_a["point"]["parent"] == ev_a["avg.round"]["span"]
    assert "parent" not in ev_a["avg.round"]  # root
    assert registry.current_trace() is None  # context restored


def test_adopt_trace_records_remote_parent_and_caller():
    t = Telemetry(peer="server")
    with registry.adopt_trace(["cafe" * 4, "beef" * 4, "client-peer"]):
        with t.span("mm.join.serve") as ctx:
            ctx["ok"] = True
    (event,) = list(t.events)
    assert event["trace"] == "cafe" * 4
    assert event["parent"] == "beef" * 4
    assert event["caller"] == "client-peer"
    # malformed tc must be ignored, never raise
    with registry.adopt_trace(None):
        pass
    with registry.adopt_trace(42):
        pass


def test_link_table_estimates_and_eviction():
    lt = LinkTable(alpha=0.5, max_links=2)
    lt.observe_rtt(("10.0.0.1", 1), 0.010)
    lt.observe_rtt(("10.0.0.1", 1), 0.030)
    link = lt.top()[0]
    assert link.dst == "10.0.0.1:1"
    assert abs(link.rtt_s - 0.020) < 1e-9  # EWMA alpha=0.5
    lt.observe_transfer(("10.0.0.2", 2), 10, 1.0)  # slow thin link
    lt.observe_transfer(("10.0.0.1", 1), 1000, 0.001)
    flat = lt.flat(top_k=8)
    assert flat["link.10.0.0.1:1.goodput_bps"] == pytest.approx(1e6)
    assert flat["link.10.0.0.2:2.goodput_bps"] == pytest.approx(10.0)
    assert flat["link.10.0.0.1:1.rtt_s"] == pytest.approx(0.020)
    # bounded by EVICTION, not refusal: a new destination displaces the
    # least-recently-OBSERVED link (.2), never the one still in use — on a
    # churning swarm the live partners stay tracked and departed peers age
    # out instead of squatting the table forever
    lt.observe_transfer(("10.0.0.3", 3), 99, 1.0)
    assert {l.dst for l in lt.top()} == {"10.0.0.1:1", "10.0.0.3:3"}
    # top_k truncation keeps the busiest link
    only = lt.flat(top_k=1)
    assert only and all(k.startswith("link.10.0.0.1:1.") for k in only)
    assert endpoint_key("already:formed") == "already:formed"


# -------------------------------------- wire framing: the zero-byte contract


def test_frames_carry_tc_only_when_telemetry_traces(monkeypatch):
    """Request frames carry the compact trace context ONLY when telemetry is
    enabled and a span is live — disabled telemetry leaves the framing
    byte-identical (no ``tc`` key at all). The server side adopts the
    context so its serve span records the remote parent + caller."""
    captured = []
    orig = protocol.write_frame

    def spy(writer, obj):
        captured.append(obj)
        orig(writer, obj)

    monkeypatch.setattr(protocol, "write_frame", spy)

    tele_srv = Telemetry(peer="srv")
    tele_cli = Telemetry(peer="cli")

    async def run():
        server = RPCServer("127.0.0.1", 0, telemetry_registry=tele_srv)

        async def echo(peer, args):
            with tele_srv.span("echo.serve") as ctx:
                ctx["ok"] = True
            return {}

        server.register("echo", echo)
        await server.start()
        endpoint = ("127.0.0.1", server.port)

        # 1) telemetry fully disabled: no tc on any frame
        bare = RPCClient(request_timeout=5.0)
        await bare.call(endpoint, "echo", {})
        assert captured, "spy saw no frames"
        assert all("tc" not in m for m in captured if isinstance(m, dict))
        await bare.close()

        # 2) enabled but NO live span: still no tc (nothing to link to)
        cli = RPCClient(request_timeout=5.0, telemetry_registry=tele_cli)
        await cli.call(endpoint, "echo", {})
        assert all("tc" not in m for m in captured if isinstance(m, dict))

        # 3) enabled inside a span: tc = [trace, parent span, caller peer]
        with tele_cli.span("avg.round", trace_seed="r9"):
            await cli.call(endpoint, "echo", {})
        tagged = [m for m in captured if isinstance(m, dict) and "tc" in m]
        assert len(tagged) == 1
        await cli.close()
        await server.stop()
        return tagged[0]["tc"]

    tc = asyncio.run(run())
    outer = [e for e in tele_cli.events if e["event"] == "avg.round"][-1]
    assert tc == [registry.trace_id_for("r9"), outer["span"], "cli"]
    # the server-side serve span recorded the REMOTE parent
    serves = [e for e in tele_srv.events if e["event"] == "echo.serve"]
    adopted = [e for e in serves if e.get("parent") == outer["span"]]
    assert len(adopted) == 1
    assert adopted[0]["trace"] == registry.trace_id_for("r9")
    assert adopted[0]["caller"] == "cli"
    # the un-traced serves (cases 1 and 2) carry no remote linkage
    assert all("caller" not in e for e in serves if e is not adopted[0])


# ---------------------------------------------- tentpole acceptance scenario


def _asymmetric_round(tmp_path, round_id="round1", delay=0.12):
    """3-peer loopback all-reduce with one injected slow directed link
    (p0 -> p2). Returns (event log paths, endpoints, telemetries)."""
    teles = [
        Telemetry(peer=f"p{i}", event_log_path=str(tmp_path / f"p{i}.jsonl"))
        for i in range(3)
    ]
    n, dim = 3, 240
    vectors = [np.full(dim, float(i + 1), np.float32) for i in range(n)]
    captured_eps = {}

    def fault_setup(clients, endpoints):
        captured_eps["eps"] = list(endpoints)
        schedule.inject(
            "rpc.client.call", "delay", times=-1, delay=delay,
            match=lambda ctx: ctx["client"] is clients[0]
            and tuple(ctx["endpoint"]) == tuple(endpoints[2]),
        )

    with FaultSchedule(seed=0) as schedule:
        results = asyncio.run(
            _allreduce_swarm(
                vectors, [1.0] * n, [1.0] * n, chunk_size=40,
                telemetries=teles, round_id=round_id,
                fault_setup=fault_setup,
            )
        )
        assert schedule.fired, "the slow-link fault never triggered"
    expected = sum(vectors) / n
    for r in results:
        np.testing.assert_allclose(r, expected, atol=1e-5)
    for t in teles:
        t.close()  # flush link.stats events
    paths = [str(tmp_path / f"p{i}.jsonl") for i in range(3)]
    return paths, captured_eps["eps"], teles


def test_acceptance_slow_link_trace_and_topology(tmp_path):
    """The ISSUE 7 acceptance criterion end to end."""
    paths, endpoints, teles = _asymmetric_round(tmp_path)
    slow_dst = endpoint_key(endpoints[2])

    rows = runlog_summary.load_events(paths)
    # ONE stitched trace: every peer's allreduce.round span derived the
    # same trace id from the shared round_id
    trace_rows, traces = runlog_summary.select_trace(rows, "round1")
    assert len(traces) == 1
    assert {r.get("peer") for r in trace_rows} >= {"p0", "p1", "p2"}

    out = _render(runlog_summary.print_trace, rows, "round1")
    # the critical path names the slow LINK: p0 waited on p0 -> p2
    critical = [l for l in out.splitlines() if l.startswith("critical path")]
    assert len(critical) == 1
    assert "p0 waited" in critical[0]
    assert f"p0 -> p2 ({slow_dst})" in critical[0]

    # --topology ranks that link worst by its RTT/goodput estimates
    topo = _render(
        runlog_summary.print_topology, runlog_summary.load_jsonl_rows(paths)
    )
    worst = [l for l in topo.splitlines() if l.startswith("worst link")]
    assert len(worst) == 1
    assert "p0 -> p2" in worst[0]
    # and the per-peer snapshot that would ride the metrics bus carries the
    # same estimate (flat link.* keys, bounded top-K)
    snap = teles[0].snapshot()
    slow_key = f"link.{slow_dst}.goodput_bps"
    assert slow_key in snap
    other = [
        v for k, v in snap.items()
        if k.startswith("link.") and k.endswith(".goodput_bps")
        and k != slow_key
    ]
    assert other and all(snap[slow_key] < v for v in other)


# ------------------------- satellite: leader death + slow link, with orphans


def test_trace_stitching_under_leader_death_and_slow_link(tmp_path):
    """FakeClock/FaultSchedule replay: the declared leader dies
    mid-matchmaking (joins dropped with process-death semantics), the
    survivors regroup and run the round over a slow link. The stitched
    trace must attribute the stall to the injected link; re-stitching
    WITHOUT the joiner's log must REPORT its spans as orphaned."""
    teles = [
        Telemetry(peer=f"m{i}", event_log_path=str(tmp_path / f"m{i}.jsonl"))
        for i in range(3)
    ]

    state = {}

    async def scenario(clock, schedule):
        first = await DHTNode.create(listen_host="127.0.0.1")
        nodes = [first] + [
            await DHTNode.create(listen_host="127.0.0.1",
                                 initial_peers=[first.endpoint])
            for _ in range(2)
        ]
        servers, clients, mms = [], [], []
        for node, tele in zip(nodes, teles):
            client = RPCClient(request_timeout=10.0, telemetry_registry=tele)
            server = RPCServer("127.0.0.1", 0, telemetry_registry=tele)
            await server.start()
            tele.event(
                "peer.endpoint", endpoint=f"127.0.0.1:{server.port}"
            )
            clients.append(client)
            servers.append(server)
            mms.append(
                Matchmaking(
                    node, client, server, "tracemm",
                    node.node_id.to_bytes(), ("127.0.0.1", server.port),
                    bandwidth=1.0, averaging_expiration=30.0,
                    telemetry_registry=tele,
                )
            )
        try:
            lead_task = asyncio.ensure_future(mms[0].form_group("r1"))
            for _ in range(400):
                if any(
                    lid == mms[0].peer_id
                    for lid, _ep in await mms[1]._live_leaders("r1")
                ):
                    break
                await asyncio.sleep(0.02)
            else:
                raise AssertionError("leader record never appeared")
            # process-death semantics for the leader, both directions
            schedule.inject(
                "rpc.server.dispatch", "drop", times=-1,
                match=lambda ctx: ctx["server"] is servers[0]
                and ctx["method"] == "mm.join",
            )
            schedule.inject(
                "rpc.client.call", "drop", times=-1,
                match=lambda ctx: ctx["client"] is clients[0]
                and ctx["method"] == "mm.join",
            )
            g1, g2 = await asyncio.gather(
                mms[1].form_group("r1", expected_size=2),
                mms[2].form_group("r1", expected_size=2),
            )
            assert {m.peer_id for m in g1.members} == {
                mms[1].peer_id, mms[2].peer_id
            }

            # the surviving pair now runs the round over one slow link
            # (survivor1 -> survivor2), same round id => same trace
            reducers = [
                GroupAllReduce(clients[i], servers[i], timeout=10.0,
                               straggler_timeout=5.0, chunk_size=20,
                               telemetry_registry=teles[i])
                for i in (1, 2)
            ]
            endpoints = [
                ("127.0.0.1", servers[1].port), ("127.0.0.1", servers[2].port)
            ]
            state["slow_dst"] = endpoint_key(endpoints[1])
            schedule.inject(
                "rpc.client.call", "delay", times=-1, delay=0.1,
                match=lambda ctx: ctx["client"] is clients[1]
                and tuple(ctx["endpoint"]) == endpoints[1]
                and ctx["method"].startswith("avg."),
            )
            vec = [np.full(60, float(i), np.float32) for i in range(2)]
            r1, r2 = await asyncio.gather(
                reducers[0].run("r1", 0, vec[0], 1.0, endpoints, [1.0, 1.0]),
                reducers[1].run("r1", 1, vec[1], 1.0, endpoints, [1.0, 1.0]),
            )
            np.testing.assert_allclose(r1, (vec[0] + vec[1]) / 2, atol=1e-5)

            clock.advance(120.0)  # expire the dead leader's window
            with contextlib.suppress(Exception):
                await asyncio.wait_for(lead_task, timeout=30)
        finally:
            for c in clients:
                await c.close()
            for s in servers:
                await s.stop()
            for node in nodes:
                await node.shutdown()

    with FakeClock(start=50_000.0) as clock, \
            FaultSchedule(seed=0) as schedule:
        asyncio.run(scenario(clock, schedule))
    for t in teles:
        t.close()
    paths = [str(tmp_path / f"m{i}.jsonl") for i in range(3)]

    # (1) full stitch: one trace, stall attributed to the injected link
    rows = runlog_summary.load_events(paths)
    _, traces = runlog_summary.select_trace(rows, "r1")
    assert len(traces) == 1
    out = _render(runlog_summary.print_trace, rows, "r1")
    critical = [l for l in out.splitlines() if l.startswith("critical path")]
    assert len(critical) == 1
    assert "m1 waited" in critical[0]
    assert state["slow_dst"] in critical[0]

    # (2) the surviving leader's serve span names the joiner's span as its
    # remote parent; stitching WITHOUT the joiner's log must report it as
    # orphaned, not silently drop it
    by_path = {p: runlog_summary.load_events([p]) for p in paths}
    serve_logs = [
        p for p, rs in by_path.items()
        if any(r.get("event") == "mm.join.serve" and r.get("ok")
               for r in rs)
    ]
    assert len(serve_logs) == 1, "exactly one survivor led the regroup"
    serve = next(
        r for r in by_path[serve_logs[0]]
        if r.get("event") == "mm.join.serve" and r.get("ok")
    )
    assert serve.get("parent"), "serve span must carry the remote parent"
    assert serve.get("caller") in {"m1", "m2"}
    joiner_log = next(
        p for p, rs in by_path.items()
        if any(r.get("span") == serve["parent"] for r in rs)
    )
    partial = [p for p in paths if p != joiner_log]
    out2 = _render(
        runlog_summary.print_trace, runlog_summary.load_events(partial), "r1"
    )
    assert "orphaned spans" in out2
    assert "mm.join.serve" in out2.split("orphaned spans")[1]


# --------------------------- satellite: provider goodput in the shard fetch


def test_fetcher_records_provider_goodput_and_bytes(tmp_path):
    from dedloc_tpu.checkpointing import build_manifest
    from dedloc_tpu.checkpointing.fetcher import fetch_shards
    from dedloc_tpu.core.serialization import CompressionType, serialize_array

    tree = {"w": np.arange(64, dtype=np.float32)}
    manifest, flat = build_manifest(tree, step=3, shard_size=16)
    tele = Telemetry(peer="joiner")

    class FakeClient:
        async def call(self, ep, method, args, timeout=None):
            assert method == "ckpt.shard"
            lo = args["index"] * 16
            return {
                "data": serialize_array(
                    flat[lo: lo + 16], CompressionType.NONE
                )
            }

    async def run():
        providers = [(("10.0.0.9", 1), None), (("10.0.0.8", 2), None)]
        pb = {}
        shards = await fetch_shards(
            FakeClient(), manifest, providers, parallelism=2,
            telemetry_registry=tele, provider_bytes=pb,
        )
        assert len(shards) == manifest.num_shards
        return pb

    provider_bytes = asyncio.run(run())
    snap = tele.snapshot()
    assert snap["ckpt.provider_goodput.count"] == manifest.num_shards
    assert snap["ckpt.provider_goodput.mean"] > 0
    # bytes attributed per provider endpoint, and the link estimator fed
    assert sum(provider_bytes.values()) == manifest.total_bytes
    assert set(provider_bytes) == {"10.0.0.9:1", "10.0.0.8:2"}
    assert any(k.startswith("link.10.0.0.") for k in snap)


# ------------------------ satellite: health fold tolerates old-schema peers


def test_swarm_health_topology_and_old_schema_tolerance():
    from dedloc_tpu.collaborative.metrics import LocalMetrics
    from dedloc_tpu.telemetry import build_swarm_health

    def rec(step, peer, tail=None, endpoint=None):
        return LocalMetrics(
            step=step, samples_per_second=1.0, samples_accumulated=8,
            loss=1.0, mini_steps=1, peer=peer, telemetry=tail,
            endpoint=endpoint,
        )

    new_peer = rec(
        5, "aa",
        tail={
            "rpc.client.calls": 3.0,
            "link.10.0.0.2:7000.rtt_s": 0.002,
            "link.10.0.0.2:7000.goodput_bps": 5e6,
            "link.10.0.0.3:7000.rtt_s": 0.150,
            "link.10.0.0.3:7000.goodput_bps": 1e4,
        },
        endpoint="10.0.0.1:7000",
    )
    # pre-link-schema peers: a bare tail, and NO tail at all — both must
    # keep their per-peer row (degrade, don't drop)
    old_peer = rec(5, "bb", tail={"rpc.client.calls": 1.0})
    bare_peer = rec(4, "cc")
    dst_peer = rec(5, "dd", tail={}, endpoint="10.0.0.2:7000")

    health = build_swarm_health([new_peer, old_peer, bare_peer, dst_peer])
    assert {p["peer"] for p in health["peers"]} == {"aa", "bb", "cc", "dd"}
    topo = health["topology"]
    # only the new-schema peer contributes links; dst resolves to a peer
    # label when some record advertises that endpoint
    assert {l["src"] for l in topo["links"]} == {"aa"}
    by_dst = {l["dst"]: l for l in topo["links"]}
    assert by_dst["dd"]["dst_endpoint"] == "10.0.0.2:7000"
    assert by_dst["10.0.0.3:7000"]["rtt_s"] == pytest.approx(0.150)
    assert topo["peers"]["bb"] is None

    # an all-old swarm simply has no topology — the pre-link health view
    health_old = build_swarm_health([old_peer, bare_peer])
    assert "topology" not in health_old
    assert {p["peer"] for p in health_old["peers"]} == {"bb", "cc"}


def test_trace_view_exits_cleanly_on_unknown_round(tmp_path):
    p = tmp_path / "e.jsonl"
    p.write_text(json.dumps({"t": 1.0, "peer": "x", "event": "noop"}) + "\n")
    with pytest.raises(SystemExit):
        runlog_summary.print_trace(
            runlog_summary.load_events([str(p)]), "missing-round"
        )
