"""Pipeline parallelism: GPipe microbatch pipelining must be EXACT — same
outputs and gradients as running the stage stack sequentially (it is a
schedule, not an approximation)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dedloc_tpu.parallel.mesh import make_mesh
from dedloc_tpu.parallel.pipeline import (
    pipeline_apply,
    shared_stage_fn,
    stage_param_sharding,
)

STAGES = 4
WIDTH = 16


def _stage_fn(params, x):
    # one dense + nonlinearity block, activation-shape preserving
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(rng):
    return {
        "w": jnp.asarray(rng.normal(0, 0.5, (STAGES, WIDTH, WIDTH)), jnp.float32),
        "b": jnp.asarray(rng.normal(0, 0.1, (STAGES, WIDTH)), jnp.float32),
    }


def _sequential(params, micro):
    def run_one(x):
        for s in range(STAGES):
            x = _stage_fn(jax.tree_util.tree_map(lambda p: p[s], params), x)
        return x

    return jax.vmap(run_one)(micro)


def test_pipeline_matches_sequential(rng):
    mesh = make_mesh(4, axis_names=("pipe",))
    params = _stacked_params(rng)
    micro = jnp.asarray(rng.normal(0, 1, (6, 8, WIDTH)), jnp.float32)

    out = jax.jit(
        lambda p, m: pipeline_apply(_stage_fn, p, m, mesh, axis="pipe")
    )(params, micro)
    ref = _sequential(params, micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5)


def test_pipeline_gradients_match_sequential(rng):
    """The backward pipeline (autodiff through scan+ppermute) must produce
    the sequential stack's gradients — GPipe's defining property."""
    mesh = make_mesh(4, axis_names=("pipe",))
    params = _stacked_params(rng)
    micro = jnp.asarray(rng.normal(0, 1, (5, 4, WIDTH)), jnp.float32)
    tgt = jnp.asarray(rng.normal(0, 1, (5, 4, WIDTH)), jnp.float32)

    def pipe_loss(p):
        out = pipeline_apply(_stage_fn, p, micro, mesh, axis="pipe")
        return jnp.mean((out - tgt) ** 2)

    def seq_loss(p):
        return jnp.mean((_sequential(p, micro) - tgt) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(params)
    g_seq = jax.grad(seq_loss)(params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]), rtol=1e-4, atol=1e-6
        )


def test_pipeline_stage_params_actually_sharded(rng):
    """Placing stacked stage params with stage_param_sharding must keep each
    device holding 1/S of every leaf — the memory property PP exists for."""
    mesh = make_mesh(4, axis_names=("pipe",))
    params = jax.device_put(_stacked_params(rng), stage_param_sharding(mesh))
    shard = params["w"].addressable_shards[0]
    assert shard.data.shape == (1, WIDTH, WIDTH)

    micro = jnp.asarray(rng.normal(0, 1, (4, 2, WIDTH)), jnp.float32)
    out = jax.jit(
        lambda p, m: pipeline_apply(_stage_fn, p, m, mesh, axis="pipe")
    )(params, micro)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, micro)), rtol=2e-5
    )


def test_pipeline_composes_with_data_parallelism(rng):
    """dp2 x pp4: the microbatch batch dim sharded over data, activations
    hopping over pipe — one SPMD program, both axes live."""
    mesh = make_mesh(8, axis_names=("data", "pipe"), shape=(2, 4))
    params = _stacked_params(rng)
    micro = jax.device_put(
        jnp.asarray(rng.normal(0, 1, (3, 4, WIDTH)), jnp.float32),
        NamedSharding(mesh, P(None, "data")),
    )
    out = jax.jit(
        lambda p, m: pipeline_apply(
            _stage_fn, p, m, mesh, axis="pipe", micro_spec=P(None, "data")
        )
    )(params, micro)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, micro)), rtol=2e-5
    )


def test_pipeline_rejects_wrong_stage_count(rng):
    """8 stacked stages on a 4-device pipe axis would legally split under
    P(axis) and silently drop half the stages — must raise instead."""
    mesh = make_mesh(4, axis_names=("pipe",))
    params = {
        "w": jnp.zeros((8, WIDTH, WIDTH)),
        "b": jnp.zeros((8, WIDTH)),
    }
    with pytest.raises(ValueError, match="leading dim 4"):
        pipeline_apply(_stage_fn, params, jnp.zeros((2, 2, WIDTH)), mesh)


def test_pipeline_rejects_pipe_axis_in_micro_spec(rng):
    mesh = make_mesh(4, axis_names=("pipe",))
    with pytest.raises(ValueError, match="pipe"):
        pipeline_apply(
            _stage_fn,
            _stacked_params(rng),
            jnp.zeros((2, 2, WIDTH)),
            mesh,
            axis="pipe",
            micro_spec=P("pipe"),
        )


def test_albert_shared_layer_pipelined(rng):
    """ALBERT-style pipelining: the ONE shared transformer block applied
    24/S iterations per stage (cross-layer weight sharing — stages differ
    only in position), pipelined == the encoder's sequential scan."""
    from dedloc_tpu.models.albert import AlbertConfig, AlbertLayer

    cfg = AlbertConfig.tiny()
    layer = AlbertLayer(cfg, deterministic=True)
    B, S = 2, 16
    hidden = jnp.asarray(
        rng.normal(0, 1, (B, S, cfg.hidden_size)), jnp.float32
    ).astype(cfg.dtype)
    attn_bias = jnp.zeros((B, 1, 1, S), cfg.dtype)
    lparams = layer.init(jax.random.PRNGKey(0), hidden, attn_bias)["params"]

    def block_fn(p, x):
        return layer.apply({"params": p}, x, attn_bias)[0]

    total_iters = 8
    mesh = make_mesh(4, axis_names=("pipe",))
    stage = shared_stage_fn(block_fn, total_iters // 4)

    micro = hidden[None]  # [M=1, B, S, H]
    out = jax.jit(
        lambda p, m: pipeline_apply(
            stage, p, m, mesh, axis="pipe", stacked_params=False
        )
    )(lparams, micro)[0]

    ref = hidden
    for _ in range(total_iters):
        ref = block_fn(lparams, ref)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2,  # bf16 accumulation across 8 blocks
    )
