"""Zero-egress natural-language corpus extraction.

The reference trains on downloaded corpora — WikiText-103
(albert/tokenize_wikitext103.py:90-104) and streaming wiki+OSCAR
(sahajbert/dataset_streaming.py:116-139). The bench/dev environment for this
framework has no network egress, so this module harvests the human-written
English prose that is already on the machine: module/class/function
docstrings of every installed distribution and the stdlib, plus the .md/.rst
documentation files that ship inside site-packages. The output layout is the
one-document-per-line format that ``data/prepare.py`` and the streaming
pipeline consume, so the rest of the data path is identical to a real
downloaded corpus.

Run:
    python -m dedloc_tpu.data.corpus \\
        --output data/corpus/train.txt \\
        --holdout_output data/corpus/holdout.txt --holdout_fraction 0.02

Deduplication is exact (hash of the normalized document); filtering keeps
multi-sentence prose (word count + letter-ratio heuristics) and drops
code-dominated docstrings so the MLM task sees natural language.
"""
from __future__ import annotations

import ast
import hashlib
import os
import re
import sys
import sysconfig
from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional

from dedloc_tpu.core.config import parse_config
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_WS = re.compile(r"\s+")
_WORD = re.compile(r"[A-Za-z]{2,}")
# reST/markdown markup that would otherwise leak into the corpus
_MARKUP = re.compile(
    r"(:param[^:]*:|:return[^:]*:|:rtype:|:raises[^:]*:|:type[^:]*:"
    r"|``+|\*\*+|^#+\s|^\.\. [a-z-]+::.*$|^={3,}$|^-{3,}$|^~{3,}$)",
    re.MULTILINE,
)


def default_roots() -> List[str]:
    """Stdlib + every site/dist-packages dir on this interpreter's path."""
    roots = [sysconfig.get_paths()["stdlib"]]
    try:
        import site

        roots.extend(site.getsitepackages())
    except Exception:  # noqa: BLE001 — site may be absent in embedded builds
        pass
    for p in sys.path:
        if p and os.path.isdir(p) and ("site-packages" in p or "dist-packages" in p):
            roots.append(p)
    seen, out = set(), []
    for r in roots:
        r = os.path.realpath(r)
        if r not in seen and os.path.isdir(r):
            seen.add(r)
            out.append(r)
    return out


def iter_source_files(roots: Iterable[str]) -> Iterator[str]:
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            # tests and vendored test data are noise-heavy; node_modules can
            # be enormous inside jupyter-adjacent wheels
            dirnames[:] = [
                d
                for d in dirnames
                if d not in ("node_modules", "__pycache__", ".git")
            ]
            for name in filenames:
                if name.endswith((".py", ".md", ".rst", ".txt")):
                    yield os.path.join(dirpath, name)


def _clean(text: str) -> str:
    """Markup-strip + whitespace-normalize into a single corpus line."""
    text = _MARKUP.sub(" ", text)
    return _WS.sub(" ", text).strip()


def _is_prose(doc: str, min_words: int) -> bool:
    words = _WORD.findall(doc)
    if len(words) < min_words:
        return False
    letters = sum(c.isalpha() or c == " " for c in doc)
    if letters / max(len(doc), 1) < 0.72:  # code/tables are symbol-dense
        return False
    # sentence-ish: at least two terminators, so segment-pair/SOP packing
    # (data/mlm.py) gets a usable A/B split downstream
    return doc.count(". ") + doc.count("? ") + doc.count("! ") >= 2


def docstrings_from_source(source: str) -> Iterator[str]:
    """Every module/class/function docstring in a Python source blob."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            doc = ast.get_docstring(node, clean=True)
            if doc:
                # cut doctest blocks: everything from the first >>> onward
                cut = doc.find(">>>")
                yield doc[:cut] if cut >= 0 else doc


def documents_from_file(path: str) -> Iterator[str]:
    try:
        with open(path, encoding="utf-8", errors="ignore") as f:
            blob = f.read(4 << 20)
    except OSError:
        return
    if path.endswith(".py"):
        yield from docstrings_from_source(blob)
    else:
        # doc files: paragraphs (blank-line separated) as documents, so one
        # README becomes several coherent multi-sentence docs
        for para in re.split(r"\n\s*\n", blob):
            if not para.lstrip().startswith((">>>", "    ", "\t", "|", "+--")):
                yield para


def harvest(
    roots: Optional[List[str]] = None,
    min_words: int = 40,
    max_docs: int = 0,
) -> Iterator[str]:
    """Deduplicated prose documents, one string per document."""
    seen = set()
    count = 0
    for path in iter_source_files(roots or default_roots()):
        for raw in documents_from_file(path):
            doc = _clean(raw)
            if not _is_prose(doc, min_words):
                continue
            key = hashlib.md5(doc.lower().encode()).digest()
            if key in seen:
                continue
            seen.add(key)
            yield doc
            count += 1
            if max_docs and count >= max_docs:
                return


@dataclass
class CorpusArguments:
    output: str = "data/corpus/train.txt"
    holdout_output: str = ""  # optional eval split path
    holdout_fraction: float = 0.0
    min_words: int = 40
    max_docs: int = 0  # 0 = everything
    roots: List[str] = field(default_factory=list)  # empty = auto-discover
    seed: int = 0


def run_corpus(args: CorpusArguments) -> int:
    import random

    rng = random.Random(args.seed)
    os.makedirs(os.path.dirname(args.output) or ".", exist_ok=True)
    hold = None
    if args.holdout_output and args.holdout_fraction > 0:
        os.makedirs(os.path.dirname(args.holdout_output) or ".", exist_ok=True)
        hold = open(args.holdout_output, "w", encoding="utf-8")
    n = n_hold = chars = 0
    with open(args.output, "w", encoding="utf-8") as out:
        for doc in harvest(args.roots or None, args.min_words, args.max_docs):
            if hold is not None and rng.random() < args.holdout_fraction:
                hold.write(doc + "\n")
                n_hold += 1
            else:
                out.write(doc + "\n")
                n += 1
                chars += len(doc)
    if hold is not None:
        hold.close()
    logger.info(
        f"corpus: {n} train docs ({chars / 1e6:.1f} MB), {n_hold} holdout"
    )
    return n


def main(argv=None) -> None:
    run_corpus(parse_config(CorpusArguments, argv))


if __name__ == "__main__":
    main()
