"""On-disk tokenized dataset reader for the trainer role.

The reference tokenizes WikiText-103 once and caches it with
``datasets.save_to_disk`` (albert/tokenize_wikitext103.py:90-104); trainers
then memory-map it. Here the cached layout is the framework's own wire
format: a directory of ``shard-*.bin`` files, each a serialized tree of
column arrays (see ``write_shards``) — mmap-friendly, tokenizer-agnostic,
and with no dependency on the `datasets` wheel at train time.
"""
from __future__ import annotations

import os
from typing import Dict, Iterator, List

import numpy as np

from dedloc_tpu.core.serialization import (
    CompressionType,
    deserialize_tree,
    serialize_tree,
)
from dedloc_tpu.data.mlm import SpecialTokens, mask_tokens, max_predictions_for

COLUMNS = ("input_ids", "token_type_ids", "special_tokens_mask", "sop_labels")


def write_shards(
    path: str,
    batches: Iterator[Dict[str, np.ndarray]],
    examples_per_shard: int = 8192,
) -> int:
    """Write batched instances into shard files; returns total examples."""
    os.makedirs(path, exist_ok=True)
    buf: List[Dict[str, np.ndarray]] = []
    count = n_shards = 0

    def flush() -> None:
        nonlocal buf, n_shards
        if not buf:
            return
        merged = {
            k: np.concatenate([b[k] for b in buf], axis=0) for k in COLUMNS
        }
        blob = serialize_tree(merged, CompressionType.NONE)
        with open(os.path.join(path, f"shard-{n_shards:05d}.bin"), "wb") as f:
            f.write(blob)
        n_shards += 1
        buf = []

    pending = 0
    for batch in batches:
        buf.append({k: np.asarray(batch[k]) for k in COLUMNS})
        pending += len(batch["input_ids"])
        count += len(batch["input_ids"])
        if pending >= examples_per_shard:
            flush()
            pending = 0
    flush()
    return count


def tokenized_dataset_batches(
    path: str,
    cfg,
    batch_size: int,
    seq_length: int,
    seed: int,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite shuffled batch stream over the cached shards, with fresh MLM
    masking each epoch (per-peer seed ⇒ independent shuffling,
    run_trainer.py:266-270 capability)."""
    shards = sorted(
        os.path.join(path, f) for f in os.listdir(path) if f.endswith(".bin")
    )
    if not shards:
        raise FileNotFoundError(f"no shard-*.bin files under {path}")
    meta_path = os.path.join(path, "meta.json")
    if os.path.exists(meta_path):
        import json

        with open(meta_path) as f:
            meta = json.load(f)
        if meta.get("vocab_size", 0) > cfg.vocab_size:
            raise ValueError(
                f"dataset at {path} was tokenized with vocab "
                f"{meta['vocab_size']} but the model's vocab_size is only "
                f"{cfg.vocab_size}; out-of-range ids would corrupt the "
                f"embedding lookup. Use --training.vocab_size "
                f"{meta['vocab_size']} or retokenize."
            )
    rng = np.random.default_rng(seed)
    tokens = SpecialTokens(vocab_size=cfg.vocab_size)
    seq_length = min(seq_length, cfg.max_position_embeddings)
    # gathered label layout: the model projects to the vocab only at masked
    # positions (~15%), not all seq_length of them — on ALBERT-large this is
    # the difference between a 512x30k and an 81x30k logits tensor per row
    max_predictions = max_predictions_for(seq_length)
    while True:
        for shard_path in rng.permutation(shards):
            with open(shard_path, "rb") as f:
                cols = deserialize_tree(f.read())
            n = len(cols["input_ids"])
            order = rng.permutation(n)
            for start in range(0, n - batch_size + 1, batch_size):
                idx = order[start : start + batch_size]
                ids = cols["input_ids"][idx, :seq_length].astype(np.int32)
                batch = {
                    "input_ids": ids,
                    "token_type_ids": cols["token_type_ids"][idx, :seq_length].astype(
                        np.int32
                    ),
                    "special_tokens_mask": cols["special_tokens_mask"][
                        idx, :seq_length
                    ].astype(np.int32),
                    "attention_mask": (ids != tokens.pad_id).astype(np.int32),
                    "sop_labels": cols["sop_labels"][idx].astype(np.int32),
                }
                yield mask_tokens(
                    batch, rng, tokens, max_predictions=max_predictions
                )
