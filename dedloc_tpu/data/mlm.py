"""MLM + sentence-order-prediction instance construction and masking.

Capability parity with the reference's data prep
(albert/tokenize_wikitext103.py:13-72 ``create_instances_from_document``:
segment-pair packing with a random A/B split point and a 50% swap that
defines the SOP label; and transformers' ``DataCollatorForLanguageModeling``
masking: 15% of non-special positions get a label, of which 80% → [MASK],
10% → random token, 10% → unchanged).

Tokenizer-agnostic and TPU-first: everything operates on integer numpy
arrays (the tokenizer itself stays an external wheel — SURVEY.md §2.7), and
masking is vectorized over the whole batch so the host never loops per
token. All outputs are fixed-shape, jit-ready arrays.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class SpecialTokens:
    cls_id: int = 2
    sep_id: int = 3
    pad_id: int = 0
    mask_id: int = 4
    vocab_size: int = 30000
    # ids < num_reserved are never used as random replacements
    num_reserved: int = 5


def create_instances_from_document(
    sentences: Sequence[Sequence[int]],
    max_seq_length: int,
    rng: np.random.Generator,
    tokens: SpecialTokens,
) -> List[Dict[str, np.ndarray]]:
    """Pack one document's tokenized sentences into MLM+SOP instances.

    Mirrors tokenize_wikitext103.py:13-72: greedily fill ``current_chunk`` to
    ``max_seq_length - 3`` (CLS + 2×SEP), choose a random sentence boundary
    ``a_end`` to split segments A|B, swap A and B with probability 0.5
    (``sentence_order_label`` 1 when swapped), emit
    ``[CLS] A [SEP] B [SEP]`` with token-type ids 0…0 1…1.
    """
    target_len = max_seq_length - 3
    instances: List[Dict[str, np.ndarray]] = []
    current: List[Sequence[int]] = []
    current_len = 0

    def flush() -> None:
        nonlocal current, current_len
        if not current:
            return
        if len(current) == 1:
            segment_a, segment_b = list(current[0]), []
        else:
            a_end = int(rng.integers(1, len(current)))
            segment_a = [t for s in current[:a_end] for t in s]
            segment_b = [t for s in current[a_end:] for t in s]
        label = 0
        if segment_b and rng.random() < 0.5:
            segment_a, segment_b = segment_b, segment_a
            label = 1
        # truncate the pair to fit (front-biased like the reference's
        # truncate_seq_pair capability: drop from the longer segment)
        while len(segment_a) + len(segment_b) > target_len:
            longer = segment_a if len(segment_a) >= len(segment_b) else segment_b
            longer.pop()
        ids = (
            [tokens.cls_id]
            + segment_a
            + [tokens.sep_id]
            + segment_b
            + [tokens.sep_id]
        )
        type_ids = [0] * (len(segment_a) + 2) + [1] * (len(segment_b) + 1)
        special = (
            [1] + [0] * len(segment_a) + [1] + [0] * len(segment_b) + [1]
        )
        instances.append(
            {
                "input_ids": np.asarray(ids, np.int32),
                "token_type_ids": np.asarray(type_ids, np.int32),
                "special_tokens_mask": np.asarray(special, np.int32),
                "sop_label": np.asarray(label, np.int32),
            }
        )
        current, current_len = [], 0

    for sentence in sentences:
        if not len(sentence):
            continue
        current.append(sentence)
        current_len += len(sentence)
        if current_len >= target_len:
            flush()
    flush()
    return instances


def pad_and_batch(
    instances: Sequence[Dict[str, np.ndarray]],
    max_seq_length: int,
    tokens: SpecialTokens,
) -> Dict[str, np.ndarray]:
    """Stack variable-length instances into fixed [B, S] arrays (+mask)."""
    b = len(instances)
    out = {
        "input_ids": np.full((b, max_seq_length), tokens.pad_id, np.int32),
        "token_type_ids": np.zeros((b, max_seq_length), np.int32),
        "special_tokens_mask": np.ones((b, max_seq_length), np.int32),
        "attention_mask": np.zeros((b, max_seq_length), np.int32),
        "sop_labels": np.zeros((b,), np.int32),
    }
    for i, inst in enumerate(instances):
        n = min(len(inst["input_ids"]), max_seq_length)
        out["input_ids"][i, :n] = inst["input_ids"][:n]
        out["token_type_ids"][i, :n] = inst["token_type_ids"][:n]
        out["special_tokens_mask"][i, :n] = inst["special_tokens_mask"][:n]
        out["attention_mask"][i, :n] = 1
        out["sop_labels"][i] = inst["sop_label"]
    return out


def max_predictions_for(seq_length: int, mlm_probability: float = 0.15) -> int:
    """Gathered-label capacity for a sequence length: the expected masked
    count plus slack so sampling jitter never truncates labels. The single
    source of truth — every producer of ``mlm_positions`` and every consumer
    sizing the gathered head must agree on this width or shapes recompile."""
    return int(seq_length * mlm_probability) + 4


def mask_tokens(
    batch: Dict[str, np.ndarray],
    rng: np.random.Generator,
    tokens: SpecialTokens,
    mlm_probability: float = 0.15,
    ignore_index: int = -100,
    max_predictions: int = 0,
) -> Dict[str, np.ndarray]:
    """Whole-batch vectorized MLM masking (DataCollatorForLanguageModeling
    semantics): 15% of maskable positions become labels; 80% of those are
    replaced by [MASK], 10% by a random non-special token, 10% kept.

    With ``max_predictions > 0`` the batch additionally carries the gathered
    TPU-native label layout — ``mlm_positions``/``mlm_label_ids``/
    ``mlm_weights`` [B, max_predictions] — so the model can run the vocab
    projection on prediction positions only. Labelled positions beyond
    ``max_predictions`` are demoted back to unlabelled (and unmasked), so
    the two layouts stay consistent.
    """
    input_ids = batch["input_ids"]
    maskable = (batch["special_tokens_mask"] == 0) & (batch["attention_mask"] == 1)
    probs = rng.random(input_ids.shape)
    labelled = (probs < mlm_probability) & maskable

    if max_predictions:
        # keep at most max_predictions labels per row (drop the excess)
        cum = np.cumsum(labelled, axis=1)
        labelled &= cum <= max_predictions

    mlm_labels = np.where(labelled, input_ids, ignore_index).astype(np.int32)

    action = rng.random(input_ids.shape)
    masked = labelled & (action < 0.8)
    randomized = labelled & (action >= 0.8) & (action < 0.9)
    random_ids = rng.integers(
        tokens.num_reserved, tokens.vocab_size, input_ids.shape
    ).astype(np.int32)

    new_ids = np.where(masked, tokens.mask_id, input_ids)
    new_ids = np.where(randomized, random_ids, new_ids).astype(np.int32)

    out = dict(batch)
    out["input_ids"] = new_ids
    out["mlm_labels"] = mlm_labels
    if max_predictions:
        b, s = input_ids.shape
        positions = np.zeros((b, max_predictions), np.int32)
        label_ids = np.zeros((b, max_predictions), np.int32)
        weights = np.zeros((b, max_predictions), np.float32)
        for i in range(b):
            idx = np.flatnonzero(labelled[i])
            n = len(idx)
            positions[i, :n] = idx
            label_ids[i, :n] = input_ids[i, idx]
            weights[i, :n] = 1.0
        out["mlm_positions"] = positions
        out["mlm_label_ids"] = label_ids
        out["mlm_weights"] = weights
    return out
