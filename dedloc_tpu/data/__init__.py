from dedloc_tpu.data.mlm import (
    SpecialTokens,
    create_instances_from_document,
    mask_tokens,
    pad_and_batch,
)
from dedloc_tpu.data.streaming import (
    ShuffleBuffer,
    interleave_weighted,
    repeat_forever,
)
