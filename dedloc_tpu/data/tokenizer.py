"""SentencePiece-Unigram tokenizer pipeline + fast wrapper.

Capability parity with the reference's custom Bengali tokenizer
(sahajbert/tokenizer/tokenizer_model.py:9-87 — Unigram model with NMT/NFKC
normalization, Bengali danda/viserga unicode repairs, Metaspace+Digits+
Punctuation pre-tokenization, ``[CLS] $A [SEP] $B:1 [SEP]:1`` template — and
sahajbert/tokenization_albert_bengali_fast.py — the PreTrainedTokenizerFast
wrapper) built on the ``tokenizers`` wheel. The framework-side API is the
small ``FastTokenizer`` facade the data pipelines and fine-tune drivers
consume; transformers interop is one adapter call away.
"""
from __future__ import annotations

import logging
from typing import Dict, Iterable, List, Optional, Sequence

logger = logging.getLogger(__name__)

SPECIAL_TOKENS = ["<pad>", "<unk>", "[CLS]", "[SEP]", "[MASK]"]
PAD_ID, UNK_ID, CLS_ID, SEP_ID, MASK_ID = range(5)


def build_unigram_tokenizer(replacement: str = "▁", add_prefix_space: bool = True):
    """Untrained Unigram tokenizer with the Bengali-aware normalizer stack.

    Normalization repairs common Bengali unicode confusions before
    lowercasing (reference tokenizer_model.py:17-29): deprecated
    danda/double-danda codepoints and the ASCII pipe to U+0964, the Assamese
    riha to danda, and a colon following a Bengali char to the viserga.
    """
    from tokenizers import Regex, Tokenizer, decoders, normalizers, pre_tokenizers
    from tokenizers.models import Unigram
    from tokenizers.processors import TemplateProcessing

    tok = Tokenizer(Unigram())
    tok.normalizer = normalizers.Sequence(
        [
            normalizers.Nmt(),
            normalizers.NFKC(),
            normalizers.Replace(Regex(" {2,}"), " "),
            normalizers.Replace("৤", "।"),
            normalizers.Replace("৥", "॥"),
            normalizers.Replace("|", "।"),
            normalizers.Replace("৷", "।"),
            normalizers.Replace(Regex(r"(?<=[ঀ-৿]):"), "ঃ"),
            normalizers.Lowercase(),
        ]
    )
    tok.pre_tokenizer = pre_tokenizers.Sequence(
        [
            pre_tokenizers.Metaspace(
                replacement=replacement, prepend_scheme="always" if add_prefix_space else "never"
            ),
            pre_tokenizers.Digits(individual_digits=True),
            pre_tokenizers.Punctuation(),
        ]
    )
    tok.decoder = decoders.Metaspace(
        replacement=replacement, prepend_scheme="always" if add_prefix_space else "never"
    )
    tok.post_processor = TemplateProcessing(
        single="[CLS] $A [SEP]",
        pair="[CLS] $A [SEP] $B:1 [SEP]:1",
        special_tokens=[("[CLS]", CLS_ID), ("[SEP]", SEP_ID)],
    )
    return tok


def train_unigram_tokenizer(
    texts: Iterable[str],
    vocab_size: int = 8000,
    special_tokens: Sequence[str] = tuple(SPECIAL_TOKENS),
    show_progress: bool = False,
):
    """Train from any text iterator (the reference trains on OSCAR-bn with
    vocab 31,995, tokenizer_training_custom.py:1-31)."""
    from tokenizers import trainers
    from tokenizers.processors import TemplateProcessing

    if "[CLS]" not in special_tokens or "[SEP]" not in special_tokens:
        raise ValueError(
            "special_tokens must include [CLS] and [SEP] (required by the "
            f"post-processing template); got {list(special_tokens)}"
        )
    tok = build_unigram_tokenizer()
    trainer = trainers.UnigramTrainer(
        vocab_size=vocab_size,
        special_tokens=list(special_tokens),
        unk_token="<unk>",
        show_progress=show_progress,
    )
    tok.train_from_iterator(texts, trainer=trainer)
    # Rebuild the template with the ids the trainer actually assigned — a
    # caller-supplied special_tokens order must not silently desync the
    # [CLS]/[SEP] ids the post-processor emits.
    vocab = tok.get_vocab()
    tok.post_processor = TemplateProcessing(
        single="[CLS] $A [SEP]",
        pair="[CLS] $A [SEP] $B:1 [SEP]:1",
        special_tokens=[("[CLS]", vocab["[CLS]"]), ("[SEP]", vocab["[SEP]"])],
    )
    return tok


class FastTokenizer:
    """Thin facade over a trained ``tokenizers.Tokenizer``.

    The three call patterns the framework needs: plain text -> ids
    (streaming MLM pipeline), segment pairs (SOP instances), and
    pre-split words with word_ids (NER label alignment,
    train_ner.py:184-212).
    """

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer
        vocab = tokenizer.get_vocab()
        self.pad_id = vocab.get("<pad>", PAD_ID)
        self.unk_id = vocab.get("<unk>", UNK_ID)
        self.cls_id = vocab.get("[CLS]", CLS_ID)
        self.sep_id = vocab.get("[SEP]", SEP_ID)
        self.mask_id = vocab.get("[MASK]", MASK_ID)

    @property
    def vocab_size(self) -> int:
        return self.tokenizer.get_vocab_size()

    def encode_ids(self, text: str, add_special_tokens: bool = True) -> List[int]:
        return self.tokenizer.encode(text, add_special_tokens=add_special_tokens).ids

    def encode_pair(self, a: str, b: str) -> Dict[str, List[int]]:
        enc = self.tokenizer.encode(a, b)
        return {"input_ids": enc.ids, "token_type_ids": enc.type_ids}

    def tokenize_words(self, words: List[str]) -> Dict[str, List]:
        enc = self.tokenizer.encode(words, is_pretokenized=True)
        return {"input_ids": enc.ids, "word_ids": enc.word_ids}

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self.tokenizer.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def save(self, path: str) -> None:
        self.tokenizer.save(path)

    @classmethod
    def load(cls, path: str) -> "FastTokenizer":
        from tokenizers import Tokenizer

        return cls(Tokenizer.from_file(path))

    def to_transformers(self):
        """PreTrainedTokenizerFast adapter (the AlbertBengaliTokenizerFast
        capability, tokenization_albert_bengali_fast.py:19-103)."""
        from transformers import PreTrainedTokenizerFast

        return PreTrainedTokenizerFast(
            tokenizer_object=self.tokenizer,
            pad_token="<pad>",
            unk_token="<unk>",
            cls_token="[CLS]",
            sep_token="[SEP]",
            mask_token="[MASK]",
            model_max_length=512,
        )


def load_fast_tokenizer(path_or_dir: str) -> FastTokenizer:
    """Load tokenizer.json from a file path or a checkpoint directory."""
    import os

    path = path_or_dir
    if os.path.isdir(path_or_dir):
        path = os.path.join(path_or_dir, "tokenizer.json")
    return FastTokenizer.load(path)
