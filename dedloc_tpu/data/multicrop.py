"""Multicrop pipeline: SSL augmentations + crop-group batching + fixtures.

Capability parity with the reference's SwAV data path: ``ImgPilToMultiCrop``
generates 2 global 224² + 6 local 96² views per image via RandomResizedCrop
(swav/vissl/vissl/data/ssl_transforms/img_pil_to_multicrop.py:11-74), the
SimCLR augmentation stack — RandomHorizontalFlip, ImgPilColorDistortion
(strength 1.0: jitter 0.8/0.8/0.8/0.2 applied with p=0.8 + grayscale p=0.2,
img_pil_color_distortion.py:11-54), ImgPilGaussianBlur (p=0.5, radius
U(0.1, 2.0), img_pil_gaussian_blur.py:12-41) and ImageNet normalization
(swav_1node_resnet_submit.yaml:32-49) — the multicrop collator groups
same-resolution crops so the trunk runs once per resolution
(data/collators/multicrop_collator.py:7-55 + base_ssl_model.py:76), and
SyntheticImageDataset provides the test fixture (synthetic_dataset.py:7-53).

Implemented on PIL + numpy (no torchvision): decode, geometric ops and blur
ride PIL; photometric ops are vectorized numpy. Every sampler draws from a
caller-owned ``np.random.Generator`` so augmentation streams are exactly
reproducible per peer seed.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


@dataclasses.dataclass(frozen=True)
class MultiCropSpec:
    """2×224 + 6×96 by default (swav_1node_resnet_submit.yaml:32-37)."""

    sizes: Sequence[int] = (224, 96)
    counts: Sequence[int] = (2, 6)
    channels: int = 3

    @property
    def num_crops(self) -> int:
        return sum(self.counts)

    @staticmethod
    def tiny(**overrides) -> "MultiCropSpec":
        base = dict(sizes=(32, 16), counts=(2, 2))
        base.update(overrides)
        return MultiCropSpec(**base)


def crop_groups(spec: MultiCropSpec, batch_size: int) -> List[Tuple[int, int]]:
    """(count, size) per resolution group — the static shape contract between
    the data pipeline and the jitted SwAV step."""
    return [(c * batch_size, s) for s, c in zip(spec.sizes, spec.counts)]


def synthetic_multicrop_batches(
    spec: MultiCropSpec,
    batch_size: int,
    seed: int = 0,
    num_classes: int = 8,
) -> Iterator[List[np.ndarray]]:
    """Synthetic multicrop stream (SyntheticImageDataset capability): each
    "image" is a class-dependent mean plus noise; crops of one image share
    its mean, so crops agree like real augmented views do. Yields one
    [count*B, S, S, C] float32 array per resolution group, in crop order."""
    rng = np.random.default_rng(seed)
    while True:
        means = rng.standard_normal((batch_size, 1, 1, spec.channels)) * 0.5
        groups: List[np.ndarray] = []
        for size, count in zip(spec.sizes, spec.counts):
            views = []
            for _ in range(count):
                noise = rng.standard_normal(
                    (batch_size, size, size, spec.channels)
                ).astype(np.float32) * 0.1
                views.append((means + noise).astype(np.float32))
            groups.append(np.concatenate(views, axis=0))
        yield groups


def _random_resized_crop(
    img, size: int, scale: Tuple[float, float], rng: np.random.Generator
):
    """torchvision RandomResizedCrop semantics: 10 attempts at a random area
    in ``scale``×orig_area with log-uniform aspect in (3/4, 4/3), then a
    center-crop fallback; bicubic resize to size×size."""
    from PIL import Image

    w, h = img.size
    area = w * h
    for _ in range(10):
        target_area = area * rng.uniform(*scale)
        log_ratio = (np.log(3 / 4), np.log(4 / 3))
        ratio = np.exp(rng.uniform(*log_ratio))
        cw = int(round(np.sqrt(target_area * ratio)))
        ch = int(round(np.sqrt(target_area / ratio)))
        if 0 < cw <= w and 0 < ch <= h:
            x = int(rng.integers(0, w - cw + 1))
            y = int(rng.integers(0, h - ch + 1))
            box = (x, y, x + cw, y + ch)
            break
    else:
        side = min(w, h)  # fallback: center crop
        x, y = (w - side) // 2, (h - side) // 2
        box = (x, y, x + side, y + side)
    return img.resize((size, size), Image.BICUBIC, box=box)


def _color_jitter(arr: np.ndarray, strength: float, rng) -> np.ndarray:
    """SimCLR jitter on a float [0,1] HWC array: brightness/contrast/
    saturation factors U(1±0.8s) and hue shift U(±0.2s), applied in a random
    order (torchvision ColorJitter semantics)."""
    s = 0.8 * strength

    def brightness(a):
        return a * rng.uniform(max(0.0, 1 - s), 1 + s)

    def contrast(a):
        m = _grayscale(a).mean()
        return (a - m) * rng.uniform(max(0.0, 1 - s), 1 + s) + m

    def saturation(a):
        g = _grayscale(a)[..., None]
        return (a - g) * rng.uniform(max(0.0, 1 - s), 1 + s) + g

    def hue(a):
        shift = rng.uniform(-0.2 * strength, 0.2 * strength)
        hsv = _rgb_to_hsv(a)
        hsv[..., 0] = (hsv[..., 0] + shift) % 1.0
        return _hsv_to_rgb(hsv)

    ops = [brightness, contrast, saturation, hue]
    for i in rng.permutation(4):
        arr = np.clip(ops[i](arr), 0.0, 1.0)
    return arr


def _grayscale(a: np.ndarray) -> np.ndarray:
    return a[..., 0] * 0.299 + a[..., 1] * 0.587 + a[..., 2] * 0.114


def _rgb_to_hsv(a: np.ndarray) -> np.ndarray:
    mx, mn = a.max(-1), a.min(-1)
    diff = mx - mn
    safe = np.where(diff == 0, 1.0, diff)
    r, g, b = a[..., 0], a[..., 1], a[..., 2]
    h = np.where(
        mx == r, (g - b) / safe % 6, np.where(mx == g, (b - r) / safe + 2, (r - g) / safe + 4)
    ) / 6.0
    h = np.where(diff == 0, 0.0, h)
    s = np.where(mx == 0, 0.0, diff / np.where(mx == 0, 1.0, mx))
    return np.stack([h, s, mx], axis=-1)


def _hsv_to_rgb(hsv: np.ndarray) -> np.ndarray:
    h, s, v = hsv[..., 0] * 6.0, hsv[..., 1], hsv[..., 2]
    i = np.floor(h).astype(np.int32) % 6
    f = h - np.floor(h)
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    table = np.stack(
        [
            np.stack([v, t, p], -1), np.stack([q, v, p], -1),
            np.stack([p, v, t], -1), np.stack([p, q, v], -1),
            np.stack([t, p, v], -1), np.stack([v, p, q], -1),
        ],
        axis=0,
    )
    return np.take_along_axis(table, i[None, ..., None], axis=0)[0]


@dataclasses.dataclass
class AugmentSpec:
    """SwAV recipe knobs (swav_1node_resnet_submit.yaml:32-49)."""

    crop_scales: Sequence[Tuple[float, float]] = ((0.14, 1.0), (0.05, 0.14))
    flip_p: float = 0.5
    color_strength: float = 1.0  # 0 disables color distortion entirely
    color_p: float = 0.8
    grayscale_p: float = 0.2
    blur_p: float = 0.5
    blur_radius: Tuple[float, float] = (0.1, 2.0)
    normalize: bool = True


def augment_multicrop(
    img,
    spec: MultiCropSpec,
    aug: AugmentSpec,
    rng: np.random.Generator,
) -> List[np.ndarray]:
    """One image -> ``spec.num_crops`` augmented float32 HWC views, in crop
    order (globals first). The full reference stack per crop:
    RandomResizedCrop -> flip -> color distortion -> blur -> normalize."""
    from PIL import Image, ImageFilter

    if not isinstance(img, Image.Image):
        img = Image.fromarray(np.asarray(img).astype(np.uint8))
    if img.mode != "RGB":
        img = img.convert("RGB")
    if len(aug.crop_scales) != len(spec.sizes):
        # zip would silently truncate resolution groups, breaking the
        # spec.num_crops contract the batch grouping relies on
        raise ValueError(
            f"aug.crop_scales has {len(aug.crop_scales)} entries but the "
            f"crop spec has {len(spec.sizes)} resolution groups"
        )
    crops = []
    for size, count, scale in zip(spec.sizes, spec.counts, aug.crop_scales):
        for _ in range(count):
            view = _random_resized_crop(img, size, scale, rng)
            if rng.random() < aug.flip_p:
                view = view.transpose(Image.FLIP_LEFT_RIGHT)
            arr = np.asarray(view, np.float32) / 255.0
            if aug.color_strength:
                if rng.random() < aug.color_p:
                    arr = _color_jitter(arr, aug.color_strength, rng)
                if rng.random() < aug.grayscale_p:
                    arr = np.repeat(_grayscale(arr)[..., None], 3, axis=-1)
            if aug.blur_p and rng.random() < aug.blur_p:
                radius = rng.uniform(*aug.blur_radius)
                blurred = Image.fromarray(
                    (np.clip(arr, 0, 1) * 255).astype(np.uint8)
                ).filter(ImageFilter.GaussianBlur(radius=radius))
                arr = np.asarray(blurred, np.float32) / 255.0
            if aug.normalize:
                arr = (arr - IMAGENET_MEAN) / IMAGENET_STD
            crops.append(arr.astype(np.float32))
    return crops


def iter_image_files(path: str) -> List[str]:
    """Sorted image files under ``path`` (flat dir or one subdir per class —
    the disk_folder layout vissl's GenericSSLDataset reads)."""
    exts = (".jpg", ".jpeg", ".png", ".bmp")
    out = []
    for dirpath, _dirnames, filenames in os.walk(path):
        for name in filenames:
            if name.lower().endswith(exts):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def image_folder_multicrop_batches(
    path: str,
    spec: MultiCropSpec,
    batch_size: int,
    seed: int = 0,
    aug: Optional[AugmentSpec] = None,
) -> Iterator[List[np.ndarray]]:
    """Infinite augmented multicrop stream over a real image folder; same
    crop-group layout as ``synthetic_multicrop_batches`` ([count*B, S, S, C]
    per resolution group, views concatenated in crop order)."""
    from PIL import Image

    aug = aug or AugmentSpec()
    files = iter_image_files(path)
    if not files:
        raise FileNotFoundError(f"no image files under {path}")
    rng = np.random.default_rng(seed)
    while True:
        chosen = rng.choice(len(files), size=batch_size, replace=len(files) < batch_size)
        per_image = []
        for idx in chosen:
            with Image.open(files[int(idx)]) as im:
                per_image.append(augment_multicrop(im, spec, aug, rng))
        groups: List[np.ndarray] = []
        crop_idx = 0
        for size, count in zip(spec.sizes, spec.counts):
            views = [
                np.stack([img_crops[crop_idx + v] for img_crops in per_image])
                for v in range(count)
            ]
            crop_idx += count
            groups.append(np.concatenate(views, axis=0))
        yield groups


def synthetic_labeled_images(
    num_images: int,
    size: int = 32,
    num_classes: int = 8,
    channels: int = 3,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Labeled single-crop fixture for linear-probe evaluation
    (SyntheticImageDataset capability, sized test-small): each class has a
    fixed mean color, so even a random frozen trunk yields linearly
    separable pooled features. Returns (images [N,S,S,C] f32, labels [N])."""
    rng = np.random.default_rng(seed)
    class_means = rng.standard_normal((num_classes, 1, 1, channels)) * 1.5
    labels = rng.integers(0, num_classes, num_images)
    noise = rng.standard_normal(
        (num_images, size, size, channels)
    ).astype(np.float32) * 0.1
    images = (class_means[labels] + noise).astype(np.float32)
    return images, labels.astype(np.int32)
