"""Multicrop pipeline: crop-group batching + synthetic image fixture.

Capability parity with the reference's SwAV data path: ``ImgPilToMultiCrop``
generates 2 global 224² + 6 local 96² views per image
(swav/vissl/vissl/data/ssl_transforms/img_pil_to_multicrop.py:11-74), the
multicrop collator groups same-resolution crops so the trunk runs once per
resolution (data/collators/multicrop_collator.py:7-55 +
base_ssl_model.py:76), and SyntheticImageDataset provides the test fixture
(data/synthetic_dataset.py:7-53).

Real image decoding/augmentation stays outside the framework (a data-side
wheel concern, SURVEY.md §2.7); this module defines the crop-group batch
STRUCTURE the jitted SwAV step consumes: a list of [N, H_i, W_i, C] arrays,
one per resolution group, in crop order.
"""
from __future__ import annotations

import dataclasses
from typing import Iterator, List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class MultiCropSpec:
    """2×224 + 6×96 by default (swav_1node_resnet_submit.yaml:32-37)."""

    sizes: Sequence[int] = (224, 96)
    counts: Sequence[int] = (2, 6)
    channels: int = 3

    @property
    def num_crops(self) -> int:
        return sum(self.counts)

    @staticmethod
    def tiny(**overrides) -> "MultiCropSpec":
        base = dict(sizes=(32, 16), counts=(2, 2))
        base.update(overrides)
        return MultiCropSpec(**base)


def crop_groups(spec: MultiCropSpec, batch_size: int) -> List[Tuple[int, int]]:
    """(count, size) per resolution group — the static shape contract between
    the data pipeline and the jitted SwAV step."""
    return [(c * batch_size, s) for s, c in zip(spec.sizes, spec.counts)]


def synthetic_multicrop_batches(
    spec: MultiCropSpec,
    batch_size: int,
    seed: int = 0,
    num_classes: int = 8,
) -> Iterator[List[np.ndarray]]:
    """Synthetic multicrop stream (SyntheticImageDataset capability): each
    "image" is a class-dependent mean plus noise; crops of one image share
    its mean, so crops agree like real augmented views do. Yields one
    [count*B, S, S, C] float32 array per resolution group, in crop order."""
    rng = np.random.default_rng(seed)
    while True:
        means = rng.standard_normal((batch_size, 1, 1, spec.channels)) * 0.5
        groups: List[np.ndarray] = []
        for size, count in zip(spec.sizes, spec.counts):
            views = []
            for _ in range(count):
                noise = rng.standard_normal(
                    (batch_size, size, size, spec.channels)
                ).astype(np.float32) * 0.1
                views.append((means + noise).astype(np.float32))
            groups.append(np.concatenate(views, axis=0))
        yield groups


def synthetic_labeled_images(
    num_images: int,
    size: int = 32,
    num_classes: int = 8,
    channels: int = 3,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Labeled single-crop fixture for linear-probe evaluation
    (SyntheticImageDataset capability, sized test-small): each class has a
    fixed mean color, so even a random frozen trunk yields linearly
    separable pooled features. Returns (images [N,S,S,C] f32, labels [N])."""
    rng = np.random.default_rng(seed)
    class_means = rng.standard_normal((num_classes, 1, 1, channels)) * 1.5
    labels = rng.integers(0, num_classes, num_images)
    noise = rng.standard_normal(
        (num_images, size, size, channels)
    ).astype(np.float32) * 0.1
    images = (class_means[labels] + noise).astype(np.float32)
    return images, labels.astype(np.int32)
