"""Corpus preparation CLI: text -> tokenized MLM+SOP instance shards.

Capability parity with the reference's standalone data-prep script
(albert/tokenize_wikitext103.py): sentence-split raw documents, tokenize,
pack into segment-pair MLM+SOP instances (random A/B swap for the
sentence-order label), and cache to disk for the trainer role's
``--training.dataset_path``.

Run:
    python -m dedloc_tpu.data.prepare \\
        --input corpus1.txt corpus2.txt \\
        --tokenizer_path tokenizer.json \\
        --output_dir data/tokenized \\
        --max_seq_length 512

Input files are one DOCUMENT per line (the streaming pipeline's layout);
blank lines are skipped. Masking is NOT applied here — it happens on the
fly at train time so every epoch sees fresh masks (mask_tokens in
data/disk.py), matching the reference's collator-side masking.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, Iterator, List

import numpy as np

from dedloc_tpu.core.config import parse_config
from dedloc_tpu.data.mlm import (
    SpecialTokens,
    create_instances_from_document,
    pad_and_batch,
)
from dedloc_tpu.data.streaming import split_sentences
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class PrepareArguments:
    input: List[str] = field(default_factory=list)  # one document per line
    tokenizer_path: str = ""  # trained tokenizer.json
    output_dir: str = "data/tokenized"
    max_seq_length: int = 512
    examples_per_shard: int = 8192
    batch_size: int = 256  # instance-packing granularity
    seed: int = 0


def instance_batches(
    documents: Iterator[str],
    tokenize_sentences,
    tokens: SpecialTokens,
    max_seq_length: int,
    batch_size: int,
    seed: int,
) -> Iterator[Dict[str, np.ndarray]]:
    """Documents -> padded instance batches ready for ``write_shards``."""
    rng = np.random.default_rng(seed)
    pending: List[Dict[str, np.ndarray]] = []
    for doc in documents:
        sentences = tokenize_sentences(doc)
        pending.extend(
            create_instances_from_document(
                sentences, max_seq_length, rng, tokens
            )
        )
        while len(pending) >= batch_size:
            group, pending = pending[:batch_size], pending[batch_size:]
            yield pad_and_batch(group, max_seq_length, tokens)
    if pending:
        yield pad_and_batch(pending, max_seq_length, tokens)


def run_prepare(args: PrepareArguments) -> int:
    from dedloc_tpu.data.disk import write_shards
    from dedloc_tpu.data.tokenizer import load_fast_tokenizer

    if not args.input:
        raise ValueError("--input: at least one document file is required")
    tok = load_fast_tokenizer(args.tokenizer_path)
    tokens = SpecialTokens(
        cls_id=tok.cls_id, sep_id=tok.sep_id, pad_id=tok.pad_id,
        mask_id=tok.mask_id, vocab_size=tok.vocab_size,
    )

    def documents() -> Iterator[str]:
        for path in args.input:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield line

    def tokenize_sentences(doc: str) -> List[List[int]]:
        return [
            tok.encode_ids(s, add_special_tokens=False)
            for s in split_sentences(doc)
        ]

    total = write_shards(
        args.output_dir,
        instance_batches(
            documents(), tokenize_sentences, tokens,
            args.max_seq_length, args.batch_size, args.seed,
        ),
        examples_per_shard=args.examples_per_shard,
    )
    # dataset metadata: lets the trainer fail fast when the model's vocab is
    # smaller than the tokenizer's (out-of-range embedding lookups otherwise
    # surface as NaN params a full global step later)
    import json
    import os

    with open(os.path.join(args.output_dir, "meta.json"), "w") as f:
        json.dump(
            {
                "vocab_size": tok.vocab_size,
                "max_seq_length": args.max_seq_length,
                "num_instances": total,
                "tokenizer_path": args.tokenizer_path,
            },
            f,
        )
    logger.info(
        f"wrote {total} instances to {args.output_dir} "
        f"(max_seq_length={args.max_seq_length})"
    )
    return total


def main(argv=None) -> None:
    run_prepare(parse_config(PrepareArguments, argv))


if __name__ == "__main__":
    main()
