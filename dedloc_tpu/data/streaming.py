"""Streaming dataset combinators: weighted interleave + seeded shuffle buffer.

Capability parity with sahajbert/dataset_streaming.py:98-139: a lazy mix of
two text sources with probabilities (wiki 23% / oscar 77%), a shuffle buffer
of 10^4 examples seeded PER PEER (``shuffle_seed = hash(local_public_key) %
2**31``, sahajbert/run_trainer.py:268-270 — peers must not see identical
batches), and an infinite wrapper that restarts exhausted sources.

Source-agnostic: combinators take any iterables/factories, so they work over
HF streaming datasets, local files, or synthetic generators (the §4 fixture
pattern) without importing `datasets` here.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def peer_shuffle_seed(peer_public_key: bytes) -> int:
    """Deterministic per-peer seed (run_trainer.py:268-270 capability —
    stable across runs, unlike Python's salted hash())."""
    import hashlib

    return int.from_bytes(
        hashlib.sha256(peer_public_key).digest()[:4], "little"
    ) % (2**31)


def interleave_weighted(
    sources: Sequence[Iterable[Any]],
    probabilities: Sequence[float],
    seed: int = 0,
) -> Iterator[Any]:
    """Sample the next example from source i with probability p_i
    (merge_datasets(probabilities=...) capability, dataset_streaming.py:127).
    An exhausted source's probability is redistributed to the others."""
    assert len(sources) == len(probabilities) > 0
    rng = np.random.default_rng(seed)
    iters: List[Optional[Iterator[Any]]] = [iter(s) for s in sources]
    probs = np.asarray(probabilities, np.float64)
    probs = probs / probs.sum()
    while any(it is not None for it in iters):
        live = [i for i, it in enumerate(iters) if it is not None]
        p = probs[live] / probs[live].sum()
        choice = int(rng.choice(live, p=p))
        try:
            yield next(iters[choice])  # type: ignore[arg-type]
        except StopIteration:
            iters[choice] = None


class ShuffleBuffer:
    """Reservoir-style shuffle buffer (buffer_size 10^4 in the reference,
    dataset_streaming.py:131): fill the buffer, then yield a random slot and
    replace it with the next upstream example."""

    def __init__(self, buffer_size: int = 10_000, seed: int = 0):
        self.buffer_size = buffer_size
        self.seed = seed

    def __call__(self, source: Iterable[Any]) -> Iterator[Any]:
        rng = np.random.default_rng(self.seed)
        buf: List[Any] = []
        for item in source:
            if len(buf) < self.buffer_size:
                buf.append(item)
                continue
            idx = int(rng.integers(0, len(buf)))
            yield buf[idx]
            buf[idx] = item
        rng.shuffle(buf)
        yield from buf


def repeat_forever(factory: Callable[[], Iterable[Any]]) -> Iterator[Any]:
    """Infinite stream over a restartable source (WrappedIterableDataset
    capability, dataset_streaming.py:105-113: training never stops at epoch
    boundaries; a crashed/exhausted source is simply reopened)."""
    while True:
        produced = False
        try:
            for item in factory():
                produced = True
                yield item
        except Exception as e:  # noqa: BLE001 — streaming sources flake
            logger.warning(f"stream source failed ({e!r}); reopening")
        if not produced:
            # avoid a hot loop on a permanently-empty source
            raise RuntimeError("stream source yielded no examples")


def batched(source: Iterable[Any], batch_size: int) -> Iterator[List[Any]]:
    """Group a stream into fixed-size lists (drops the trailing partial)."""
    it = iter(source)
    while True:
        chunk = list(itertools.islice(it, batch_size))
        if len(chunk) < batch_size:
            return
        yield chunk


def split_sentences(text: str, delimiters: str = ".!?।") -> List[str]:
    """Delimiter-based sentence splitting (the Bengali danda ``।`` included —
    dataset_streaming.py:33 handles it via bnlp; this is the dependency-free
    equivalent). Keeps the delimiter attached to its sentence."""
    sentences: List[str] = []
    current: List[str] = []
    for ch in text:
        current.append(ch)
        if ch in delimiters:
            s = "".join(current).strip()
            if s:
                sentences.append(s)
            current = []
    tail = "".join(current).strip()
    if tail:
        sentences.append(tail)
    return sentences


def text_file_source(path: str) -> Callable[[], Iterable[str]]:
    """Restartable one-document-per-line reader for ``repeat_forever``."""

    def factory() -> Iterator[str]:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line

    return factory


def http_text_source(
    url: str,
    *,
    timeout: float = 30.0,
    max_retries: int = 5,
    backoff: float = 1.0,
    chunk_size: int = 64 * 1024,
) -> Callable[[], Iterable[str]]:
    """Restartable one-document-per-line reader over HTTP(S) — the remote
    streaming capability of the reference's wiki+oscar mix
    (sahajbert/dataset_streaming.py:116-139 streams both over HTTP).

    Mid-stream failures RESUME: the reader tracks the byte offset of fully
    consumed lines and reconnects with a ``Range`` request after an
    exponentially backed-off retry; a server without Range support is
    re-read from the start with the consumed prefix skipped. Lines are
    yielded exactly once either way."""

    def factory() -> Iterator[str]:
        import http.client
        import time as _time
        import urllib.error
        import urllib.request

        offset = 0  # bytes of COMPLETE lines already yielded
        retries = 0
        while True:
            req = urllib.request.Request(url)
            if offset:
                req.add_header("Range", f"bytes={offset}-")
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    skip = offset if (offset and resp.status != 206) else 0
                    expected = int(resp.headers.get("Content-Length") or -1)
                    received = 0
                    buf = b""
                    while True:
                        chunk = resp.read(chunk_size)
                        if not chunk:
                            if 0 <= received < expected:
                                # server closed early (advertised more):
                                # NOT end-of-stream — resume from offset
                                raise ConnectionError(
                                    f"short read: {received}/{expected}"
                                )
                            tail = buf.decode("utf-8", "replace").strip()
                            if tail:
                                yield tail
                            return
                        received += len(chunk)
                        if skip:
                            drop = min(skip, len(chunk))
                            chunk = chunk[drop:]
                            skip -= drop
                            if not chunk:
                                continue
                        buf += chunk
                        while b"\n" in buf:
                            raw, buf = buf.split(b"\n", 1)
                            offset += len(raw) + 1
                            retries = 0  # progress => reset the budget
                            line = raw.decode("utf-8", "replace").strip()
                            if line:
                                yield line
            except (urllib.error.URLError, ConnectionError, TimeoutError,
                    OSError, http.client.HTTPException) as e:
                retries += 1
                if retries > max_retries:
                    raise
                logger.warning(
                    f"http stream {url} failed ({e!r}); "
                    f"resuming at byte {offset} (retry {retries})"
                )
                _time.sleep(backoff * retries)

    return factory


def make_text_source(spec: str) -> Callable[[], Iterable[str]]:
    """Source from a spec string: ``http(s)://`` URLs stream remotely with
    retry/resume; anything else is a local one-document-per-line file."""
    if spec.startswith(("http://", "https://")):
        return http_text_source(spec)
    return text_file_source(spec)


def prefetch(source: Iterable[Any], size: int = 64) -> Iterator[Any]:
    """Bounded background prefetch: a daemon thread pulls up to ``size``
    items ahead so network/tokenization latency overlaps the consumer
    (the accelerator step). Exceptions re-raise at the consumption point."""
    import queue
    import threading

    q: "queue.Queue[Any]" = queue.Queue(maxsize=size)
    _END = object()

    def worker() -> None:
        try:
            for item in source:
                q.put(item)
            q.put(_END)
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            q.put(e)

    threading.Thread(target=worker, daemon=True).start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


def streaming_mlm_batches(
    text_sources: Sequence[Callable[[], Iterable[str]]],
    weights: Sequence[float],
    tokenize_sentences: Callable[[str], List[List[int]]],
    tokens,
    batch_size: int,
    max_seq_length: int,
    seed: int,
    buffer_size: int = 10_000,
    max_predictions: int = 0,
) -> Iterator[dict]:
    """The full streaming pipeline (make_lazy_wikioscar_dataset capability,
    dataset_streaming.py:116-139): weighted lazy mix of restartable document
    sources -> per-peer-seeded shuffle buffer -> on-the-fly MLM+SOP instance
    building -> fixed-shape masked batches. Infinite; never epoch-bounded."""
    from dedloc_tpu.data.mlm import (
        create_instances_from_document,
        mask_tokens,
        pad_and_batch,
    )

    rng = np.random.default_rng(seed)

    def instance_stream() -> Iterator[dict]:
        sources = [repeat_forever(f) for f in text_sources]
        for doc in interleave_weighted(sources, weights, seed=seed):
            sentences = tokenize_sentences(doc)
            yield from create_instances_from_document(
                sentences, max_seq_length, rng, tokens
            )

    shuffled = ShuffleBuffer(buffer_size, seed=seed)(instance_stream())
    for group in batched(shuffled, batch_size):
        batch = pad_and_batch(group, max_seq_length, tokens)
        yield mask_tokens(
            batch, rng, tokens, max_predictions=max_predictions
        )
