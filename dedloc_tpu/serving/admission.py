"""Per-peer rate admission: token buckets keyed by sender identity.

ROADMAP item 3's remainder: ``core/auth.py`` gates WHO may join a run, but
nothing bounded HOW FAST an authorized (or open-swarm) peer may hit the
write paths. One ``Admission`` instance fronts both rate-controlled RPC
surfaces — the DHT ``dht.store`` handler and the serving plane's
``expert.dispatch`` handler — refusing over-rate requests with a NAMED
reason the caller can distinguish from a dead peer (a refusal must steer
the router to another replica, not trigger a retry storm at the same one).

Clocks ride ``timeutils.monotonic`` so refill happens on the virtual
timeline under the simulator (dedlint clock discipline)."""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

from dedloc_tpu.core import timeutils

# refusal reasons (the named contract: serve.reject events carry one)
REASON_OVER_RATE = "over-rate"
REASON_OVER_CAPACITY = "over-capacity"
REASON_WRONG_VERSION = "wrong-version"
REASON_UNKNOWN_EXPERT = "unknown-expert"


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill toward ``burst``.

    Lazy refill against the injected clock — no background task, safe in
    virtual time."""

    def __init__(
        self,
        rate: float,
        burst: float,
        clock: Callable[[], float] = timeutils.monotonic,
    ):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t = float(clock())

    def _refill(self, now: float) -> None:
        dt = max(0.0, now - self._t)
        if dt > 0:
            self._tokens = min(self.burst, self._tokens + dt * self.rate)
            self._t = now

    def allow(self, n: float = 1.0) -> bool:
        self._refill(float(self._clock()))
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def available(self) -> float:
        self._refill(float(self._clock()))
        return self._tokens


class Admission:
    """Per-identity token buckets with a bounded table.

    ``check(identity)`` returns ``None`` to admit or a named reason to
    refuse. Identities are whatever the transport can attribute — the
    sender's node-id hex on DHT RPCs, the caller label on dispatch RPCs,
    falling back to the source host. The table is LRU-bounded so a sybil
    flood of fresh identities cannot grow it without bound (each eviction
    hands the evicted identity a FULL bucket again, which is acceptable:
    the flood itself is rate-limited per identity, and the table bound
    caps total admitted rate at ``max_peers * rate``)."""

    def __init__(
        self,
        rate: float = 50.0,
        burst: float = 100.0,
        clock: Callable[[], float] = timeutils.monotonic,
        max_peers: int = 4096,
    ):
        self.rate = float(rate)
        self.burst = float(burst)
        self.max_peers = int(max_peers)
        self._clock = clock
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()

    def check(self, identity: str, cost: float = 1.0) -> Optional[str]:
        identity = str(identity)
        bucket = self._buckets.get(identity)
        if bucket is None:
            bucket = TokenBucket(self.rate, self.burst, clock=self._clock)
            self._buckets[identity] = bucket
            while len(self._buckets) > self.max_peers:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(identity)
        if not bucket.allow(cost):
            return REASON_OVER_RATE
        return None
