"""Expert discovery: who serves which expert shard, at what load.

One dictionary record per collaboration at ``{prefix}_experts``, one subkey
per hosting peer — the same signed-record machinery as the checkpoint
catalog and the contribution ledger (collaborative/metrics.py
``make_validators``): the ``ExpertRecord`` schema is validated at every
storing node, and when the subkey is a peer's RSA owner tag the record is
signature-bound to that peer. A record says: "at ``endpoint`` I serve these
expert shards (id, weight version, per-window token capacity, recent load
EWMA)". Because a peer owns exactly ONE subkey slot, hosting several
experts means one record listing several ``ExpertEntry`` rows; every store
is a last-write-wins refresh carrying the live load numbers, so discovery
and load reporting are the same write.

Identity binding mirrors the ledger (telemetry/ledger.subkey_owner_id): the
``peer`` field inside a record is only trusted when it matches the identity
its storage slot speaks for; ``parse_expert_records`` DROPS any record that
fails the binding, so a peer cannot advertise endpoints under a victim's
identity from its own valid slot.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional, Tuple

from pydantic import BaseModel, StrictInt, StrictStr, model_validator

from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.telemetry.ledger import subkey_owner_id
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# a hosting peer keeps its slot refreshed well inside this window; a
# crashed host's record ages out in one discovery refresh period
DEFAULT_EXPERT_TTL = 30.0

# bound on experts one record may list: the DHT record must stay small
# (the catalog's sizing discipline) even for a fat peer hosting many shards
MAX_EXPERTS_PER_RECORD = 256


def experts_key(prefix: str) -> str:
    return f"{prefix}_experts"


def _finite(x: Any) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(float(x))


class ExpertEntry(BaseModel):
    """One hosted expert shard inside a peer's ``ExpertRecord``."""

    expert_id: StrictInt  # index into the MoE expert axis
    version: StrictInt  # checkpoint step the expert weights came from
    capacity: StrictInt  # max tokens admitted per dispatch window
    load_ewma: float  # recent tokens/s EWMA (the router's load signal)

    @model_validator(mode="after")
    def _check(self) -> "ExpertEntry":
        if self.expert_id < 0:
            raise ValueError(f"negative expert_id {self.expert_id}")
        if self.version < 0:
            raise ValueError(f"negative version {self.version}")
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")
        if not _finite(self.load_ewma) or self.load_ewma < 0:
            raise ValueError(f"bad load_ewma {self.load_ewma!r}")
        return self


class ExpertRecord(BaseModel):
    """One hosting peer's subkey slot (validated by the DHT's
    SchemaValidator chain — see collaborative/metrics.py)."""

    peer: StrictStr  # peer id, hex — must match the slot's bound identity
    endpoint: List  # [host, port] — the peer's RPC endpoint
    experts: List[ExpertEntry]
    time: float  # publication stamp (DHT clock)

    @model_validator(mode="after")
    def _check(self) -> "ExpertRecord":
        if not self.peer or len(self.peer) > 128:
            raise ValueError(f"bad peer id {self.peer!r}")
        if (
            len(self.endpoint) != 2
            or not isinstance(self.endpoint[0], str)
            or not isinstance(self.endpoint[1], int)
        ):
            raise ValueError(f"endpoint must be [host, port]: {self.endpoint}")
        if not self.experts:
            raise ValueError("record must list at least one expert")
        if len(self.experts) > MAX_EXPERTS_PER_RECORD:
            raise ValueError(
                f"record lists {len(self.experts)} experts "
                f"(bound {MAX_EXPERTS_PER_RECORD})"
            )
        seen = set()
        for e in self.experts:
            if e.expert_id in seen:
                raise ValueError(f"duplicate expert_id {e.expert_id}")
            seen.add(e.expert_id)
        if not _finite(self.time):
            raise ValueError(f"bad time {self.time!r}")
        return self


def parse_expert_records(
    items: Iterable[Tuple[Any, Any]],
) -> List[ExpertRecord]:
    """(subkey, value) pairs from the ``{prefix}_experts`` dictionary entry
    -> identity-bound ``ExpertRecord`` list. A record whose ``peer`` does
    not match the identity its subkey speaks for is DROPPED (same rule as
    ledger claims), as is anything structurally invalid — a validating
    storing node already rejected those, but a reader must not trust that
    every replica validated."""
    out: List[ExpertRecord] = []
    for subkey, value in items:
        owner = subkey_owner_id(subkey)
        if owner is None:
            continue
        try:
            record = ExpertRecord.model_validate(value)
        except Exception:  # noqa: BLE001 — malformed record, drop
            logger.debug(f"dropping malformed expert record under {owner}")
            continue
        if record.peer != owner:
            logger.warning(
                f"dropping expert record naming {record.peer} stored under "
                f"slot bound to {owner}"
            )
            continue
        out.append(record)
    return out


def expert_directory(
    records: Iterable[ExpertRecord],
) -> Dict[int, List[Tuple[ExpertRecord, ExpertEntry]]]:
    """expert_id -> hosting (record, entry) pairs, one per peer (the
    latest record per peer wins), deterministically ordered by peer id so
    every reader of the same DHT view ranks candidates identically."""
    latest: Dict[str, ExpertRecord] = {}
    for record in records:
        held = latest.get(record.peer)
        if held is None or record.time >= held.time:
            latest[record.peer] = record
    directory: Dict[int, List[Tuple[ExpertRecord, ExpertEntry]]] = {}
    for peer in sorted(latest):
        record = latest[peer]
        for entry in record.experts:
            directory.setdefault(entry.expert_id, []).append((record, entry))
    return directory


async def publish_expert_record(
    node,
    prefix: str,
    record: ExpertRecord,
    subkey: bytes,
    expiration: float = DEFAULT_EXPERT_TTL,
) -> bool:
    """Store this peer's expert slot on a ``DHTNode`` (async path — the
    simulator and any in-loop host). Role code holding the threaded ``DHT``
    wrapper uses ``dht.store`` with the same arguments instead."""
    return await node.store(
        experts_key(prefix).encode(),
        record.model_dump(),
        get_dht_time() + expiration,
        subkey=subkey,
    )


class LoadEWMA:
    """Tokens-per-second load estimate with exponential decay — the
    ``load_ewma`` field a host publishes and the router ranks by.

    Decay is applied lazily on read/update against the virtual-time-safe
    clock the caller supplies (``timeutils.monotonic`` in production, the
    engine clock under the simulator), so an idle host's advertised load
    drains toward zero without a background task."""

    def __init__(self, clock, half_life_s: float = 10.0):
        self._clock = clock
        self._half_life = max(1e-6, float(half_life_s))
        self._value = 0.0
        self._t = float(clock())

    def _decay(self, now: float) -> None:
        dt = max(0.0, now - self._t)
        if dt > 0:
            self._value *= 0.5 ** (dt / self._half_life)
            self._t = now

    def observe(self, tokens: float) -> float:
        """Record ``tokens`` worth of work arriving now; returns the
        updated rate estimate."""
        now = float(self._clock())
        self._decay(now)
        # a burst of T tokens spread over one half-life
        self._value += float(tokens) / self._half_life
        return self._value

    def value(self) -> float:
        self._decay(float(self._clock()))
        return self._value
