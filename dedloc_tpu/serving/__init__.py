"""Swarm-sharded MoE serving: the training swarm doubling as an
inference fleet (ROADMAP item 1, the million-user workload).

Three layers, each riding machinery that already exists:

- ``records``  — signed ``ExpertRecord`` discovery under the
  ``{prefix}_experts`` DHT namespace (the checkpoint-catalog /
  contribution-ledger record pattern: one schema-validated, identity-bound
  subkey slot per hosting peer, last-write-wins refresh).
- ``host``     — the expert side: registers the ``expert.dispatch`` RPC on
  a peer's existing server, computes the Switch FFN for its hosted expert
  shards, tracks a load EWMA, and re-announces.
- ``router``   — the gateway side: resolves a gating network's top-1
  choice to a live hosting peer (link-table RTT + fat/thin uplink
  classification + load), dispatches token batches with per-request
  deadlines, bounded retries with backoff and a hedged fallback, and
  degrades to the Switch residual path when every candidate is dead or
  over capacity — a request can fall through, never wedge.
- ``admission``— per-peer token buckets shared by the DHT store path and
  the expert-dispatch path (public-run rate control, ROADMAP item 3).
"""
from dedloc_tpu.serving.admission import Admission, TokenBucket
from dedloc_tpu.serving.host import ExpertHost, ffn_compute_fn
from dedloc_tpu.serving.records import (
    ExpertEntry,
    ExpertRecord,
    expert_directory,
    experts_key,
    parse_expert_records,
    publish_expert_record,
)
from dedloc_tpu.serving.router import ExpertRouter, RouterPolicy

__all__ = [
    "Admission",
    "TokenBucket",
    "ExpertHost",
    "ffn_compute_fn",
    "ExpertEntry",
    "ExpertRecord",
    "expert_directory",
    "experts_key",
    "parse_expert_records",
    "publish_expert_record",
    "ExpertRouter",
    "RouterPolicy",
]
