"""Latency/load-aware expert routing: top-1 choice -> a live hosting peer.

The ``ExpertRouter`` is the embeddable gateway core (``roles/gateway.py``
wraps it in a role). Per dispatch:

1. **Resolve** — the expert directory is a cached parse of the
   ``{prefix}_experts`` DHT entry, refreshed every ``refresh_period_s`` of
   virtual/monotonic time (one discovery refresh is the re-route bound the
   serving scenario asserts).
2. **Rank** — candidates are scored ``effective_rtt * (1 + load/capacity)``
   from the peer's OWN link table (PR 6: RTT EWMAs observed on every RPC
   connect) plus the record's published load EWMA; candidates whose
   observed ``peak_bps`` clears ``FAT_UPLINK_FACTOR`` x the candidate
   median get the fat-peer discount (PR 15's fat/thin classification,
   reused as a serving prior: a fat uplink absorbs a token burst a thin
   one chokes on). Unknown links fall back to a flat RTT prior, so ranking
   is deterministic for a fixed DHT view.
3. **Dispatch** — per-request deadline, bounded retries with exponential
   backoff ACROSS candidates (a structured refusal — over-rate,
   over-capacity, wrong-version — reroutes immediately without backoff;
   only transport failures back off), plus a hedged fallback: when the
   best candidate has not answered after ``hedge_after_s`` the runner-up
   is fired concurrently and the first acceptance wins.
4. **Degrade** — when every candidate is dead or refusing, the dispatch
   returns ``None`` and the caller takes the Switch residual path
   (parallel/moe.py's over-capacity fall-through semantics: the token
   rides the residual connection, the request NEVER wedges).

``serve.*`` spans ride the PR 6 trace propagation: the gateway seeds the
trace from the request id and the host's ``expert.compute`` span adopts it
off the RPC framing, so ``runlog_summary --trace <request-id>`` stitches
one inference request across peers.
"""
from __future__ import annotations

import asyncio
import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dedloc_tpu.averaging.topology import FAT_UPLINK_FACTOR
from dedloc_tpu.core.serialization import (
    CompressionType,
    deserialize_array,
    serialize_array,
)
from dedloc_tpu.core.timeutils import monotonic
from dedloc_tpu.serving.records import (
    ExpertEntry,
    ExpertRecord,
    expert_directory,
    experts_key,
    parse_expert_records,
)
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.telemetry.links import endpoint_key
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DISPATCH_METHOD = "expert.dispatch"  # host.py registers this handler


@dataclasses.dataclass
class RouterPolicy:
    """Gateway dispatch knobs (--serving.* flags, core/config.py)."""

    deadline_s: float = 2.0  # total per-request budget
    attempt_timeout_s: float = 0.6  # per-attempt RPC timeout
    retries: int = 2  # extra attempts after the first
    backoff_s: float = 0.05  # base backoff, doubled per retry
    hedge_after_s: float = 0.3  # fire the runner-up after this wait
    refresh_period_s: float = 5.0  # expert-directory staleness bound
    default_rtt_s: float = 0.15  # prior for never-observed links
    load_penalty: float = 1.0  # weight of load/capacity in the score
    fat_discount: float = 0.5  # score multiplier for fat-uplink hosts


class ExpertRouter:
    """Resolve expert ids to live hosting peers and dispatch token batches.

    Built over a peer's existing ``DHTNode`` (its RPC client is the
    transport seam, its get path is discovery); embeddable in any role or
    simulator peer."""

    def __init__(
        self,
        node,  # DHTNode
        prefix: str,
        policy: Optional[RouterPolicy] = None,
        telemetry_registry=None,
        caller: str = "",
    ):
        self.node = node
        self.prefix = prefix
        self.policy = policy or RouterPolicy()
        self.telemetry = telemetry_registry
        self.caller = caller or node.node_id.to_bytes().hex()[:16]
        self._directory: Dict[int, List[Tuple[ExpertRecord, ExpertEntry]]] = {}
        self._refreshed_at: Optional[float] = None
        # endpoints that failed a transport attempt THIS directory
        # generation: skipped until the next refresh re-admits whatever
        # the DHT still advertises (re-route within one discovery refresh)
        self._dead: set = set()
        # latest load numbers piggybacked on dispatch replies — fresher
        # than the records' announce-time EWMAs
        self._live_load: Dict[str, float] = {}

    # ---------------------------------------------------------- discovery

    async def refresh(self, force: bool = False) -> None:
        """Re-read the expert directory when stale (or on ``force``)."""
        now = monotonic()
        if (
            not force
            and self._refreshed_at is not None
            and now - self._refreshed_at < self.policy.refresh_period_s
        ):
            return
        entry = await self.node.get(
            experts_key(self.prefix).encode(), latest=True
        )
        items = (
            [(sk, v.value) for sk, v in entry.value.items()]
            if entry is not None and hasattr(entry.value, "items")
            else []
        )
        records = parse_expert_records(items)
        self._directory = expert_directory(records)
        self._refreshed_at = now
        self._dead.clear()
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("serve.refreshes").inc()
            tele.gauge("serve.known_experts").set(float(len(self._directory)))

    def known_experts(self) -> List[int]:
        return sorted(self._directory)

    # ------------------------------------------------------------- ranking

    def _link_stats(self) -> Dict[str, Dict[str, float]]:
        tele = telemetry.resolve(self.telemetry)
        if tele is None or tele._links is None:
            return {}
        return {rec["dst"]: rec for rec in tele.links().records()}

    def candidates(
        self, expert_id: int
    ) -> List[Tuple[Any, ExpertRecord, ExpertEntry, float]]:
        """Live candidates for ``expert_id``, best-scored first:
        ``(endpoint, record, entry, score)``. Deterministic for a fixed
        directory + link table (ties break on peer id)."""
        hosted = self._directory.get(int(expert_id), [])
        links = self._link_stats()
        peaks = []
        for record, _entry in hosted:
            rec = links.get(endpoint_key(record.endpoint))
            if rec and rec.get("peak_bps"):
                peaks.append(float(rec["peak_bps"]))
        median_peak = sorted(peaks)[len(peaks) // 2] if peaks else 0.0
        scored = []
        for record, entry in hosted:
            key = endpoint_key(record.endpoint)
            if key in self._dead:
                continue
            rec = links.get(key, {})
            rtt = float(rec.get("rtt_s") or self.policy.default_rtt_s)
            load = self._live_load.get(record.peer, float(entry.load_ewma))
            score = rtt * (
                1.0
                + self.policy.load_penalty * load / max(1.0, float(entry.capacity))
            )
            peak = float(rec.get("peak_bps") or 0.0)
            if median_peak > 0 and peak >= FAT_UPLINK_FACTOR * median_peak:
                score *= self.policy.fat_discount  # fat-uplink preference
            scored.append((tuple(record.endpoint), record, entry, score))
        scored.sort(key=lambda c: (c[3], c[1].peer))
        return scored

    # ------------------------------------------------------------ dispatch

    async def _attempt(
        self, endpoint, args: Dict[str, Any], timeout: float
    ) -> Dict[str, Any]:
        """One wire attempt; raises on transport error, returns the reply
        dict (which may be a structured refusal) otherwise."""
        return await self.node.client.call(
            tuple(endpoint), DISPATCH_METHOD, args, timeout=timeout
        )

    async def dispatch(
        self,
        expert_id: int,
        tokens: np.ndarray,
        request_id: str,
        version: Optional[int] = None,
    ) -> Optional[np.ndarray]:
        """Route one token batch to a live host of ``expert_id``.

        Returns the expert outputs ``[T, H]`` (gate-weighting is the
        caller's job, as in parallel/moe.py's combine), or ``None`` when
        the request fell through to the residual path. Never raises on
        peer failure and never blocks past the deadline."""
        tele = telemetry.resolve(self.telemetry)
        pol = self.policy
        with telemetry.span(
            "serve.request",
            telemetry=self.telemetry,
            trace_seed=str(request_id),
            round_id=str(request_id),
            expert_id=int(expert_id),
            tokens=int(tokens.shape[0]),
        ) as ctx:
            if tele is not None:
                tele.counter("serve.requests").inc()
            await self.refresh()
            args = {
                "expert_id": int(expert_id),
                "tokens": serialize_array(
                    np.ascontiguousarray(tokens, dtype=np.float32),
                    CompressionType.NONE,
                ),
                "request_id": str(request_id),
                "caller": self.caller,
            }
            if version is not None:
                args["version"] = int(version)
            deadline = monotonic() + pol.deadline_s
            attempts = 0
            refreshed_midflight = False
            while attempts <= pol.retries:
                ranked = self.candidates(expert_id)
                if not ranked and not refreshed_midflight:
                    # maybe the directory is stale (host died with its
                    # record; record expired) — one forced re-resolve
                    refreshed_midflight = True
                    await self.refresh(force=True)
                    ranked = self.candidates(expert_id)
                if not ranked:
                    break
                remaining = deadline - monotonic()
                if remaining <= 0:
                    break
                timeout = min(pol.attempt_timeout_s, remaining)
                primary = ranked[0]
                hedge_target = ranked[1] if len(ranked) > 1 else None
                reply, endpoint = await self._attempt_with_hedge(
                    primary, hedge_target, args, timeout, tele
                )
                attempts += 1
                if reply is None:
                    # transport failure on every path tried this attempt:
                    # back off (unless the deadline says otherwise), then
                    # re-rank — the dead-set now excludes the failed hosts
                    if tele is not None:
                        tele.counter("serve.retries").inc()
                    backoff = pol.backoff_s * (2 ** (attempts - 1))
                    if monotonic() + backoff >= deadline:
                        break
                    await asyncio.sleep(backoff)
                    continue
                if not reply.get("accepted"):
                    # structured refusal: this replica said no (over-rate /
                    # over-capacity / wrong-version) — reroute immediately,
                    # no backoff, and do not blame the transport
                    if tele is not None:
                        tele.counter("serve.rerouted").inc()
                        tele.event(
                            "serve.reroute",
                            expert_id=int(expert_id),
                            reason=str(reply.get("reason")),
                            endpoint=endpoint_key(endpoint),
                        )
                    self._dead.add(endpoint_key(endpoint))
                    continue
                record_peer = next(
                    (r.peer for _ep, r, _e, _s in ranked
                     if endpoint_key(_ep) == endpoint_key(endpoint)),
                    None,
                )
                if record_peer and reply.get("load_ewma") is not None:
                    self._live_load[record_peer] = float(reply["load_ewma"])
                if tele is not None:
                    tele.counter("serve.ok").inc()
                    tele.counter("serve.tokens").inc(int(tokens.shape[0]))
                ctx["ok"] = True
                ctx["endpoint"] = endpoint_key(endpoint)
                return deserialize_array(reply["data"])
            # every path exhausted: Switch residual fall-through
            if tele is not None:
                tele.counter("serve.fall_through").inc()
                tele.event(
                    "serve.fall_through",
                    expert_id=int(expert_id),
                    request_id=str(request_id),
                    attempts=attempts,
                )
            ctx["ok"] = False
            return None

    async def _attempt_with_hedge(
        self, primary, hedge_target, args, timeout: float, tele
    ) -> Tuple[Optional[Dict[str, Any]], Any]:
        """Fire the best candidate; if it has not answered after
        ``hedge_after_s`` and a runner-up exists, fire that too and take
        the first acceptance. Returns ``(reply, endpoint)`` — reply is
        None when every fired attempt failed at the transport.

        Completion checks are by explicit task identity (never set
        iteration over tasks), keeping the path bit-deterministic under
        the simulator engine."""
        p_ep = primary[0]
        p_task = asyncio.ensure_future(self._attempt(p_ep, args, timeout))
        hedge_wait = min(self.policy.hedge_after_s, timeout)
        p_failed = False
        try:
            reply = await asyncio.wait_for(asyncio.shield(p_task), hedge_wait)
            return reply, p_ep
        except asyncio.TimeoutError as e:
            # ambiguous: either the hedge window elapsed (primary still in
            # flight behind the shield) or the RPC's own deadline fired —
            # the task's done flag tells them apart
            if p_task.done():
                self._note_transport_failure(p_ep, e, tele)
                p_failed = True
        except Exception as e:  # noqa: BLE001 — transport failure
            self._note_transport_failure(p_ep, e, tele)
            p_failed = True
        if hedge_target is None:
            if p_failed:
                return None, p_ep
            try:
                return await p_task, p_ep
            except Exception as e:  # noqa: BLE001 — transport failure
                self._note_transport_failure(p_ep, e, tele)
                return None, p_ep
        h_ep = hedge_target[0]
        if tele is not None:
            tele.counter("serve.hedges").inc()
        h_task = asyncio.ensure_future(self._attempt(h_ep, args, timeout))
        if p_failed:
            try:
                return await h_task, h_ep
            except Exception as e:  # noqa: BLE001 — transport failure
                self._note_transport_failure(h_ep, e, tele)
                return None, h_ep
        await asyncio.wait(
            {p_task, h_task}, return_when=asyncio.FIRST_COMPLETED
        )
        # fixed-priority harvest (primary first) — a simultaneous finish
        # resolves the same way every run; never iterate the task set
        for task, ep, other, oep in (
            (p_task, p_ep, h_task, h_ep),
            (h_task, h_ep, p_task, p_ep),
        ):
            if task.done():
                try:
                    reply = task.result()
                except Exception as e:  # noqa: BLE001 — transport failure
                    self._note_transport_failure(ep, e, tele)
                    continue
                other.cancel()
                return reply, ep
        # no completed success yet: one of the two may still be in flight
        # (the other failed) — drain it, bounded by its own RPC deadline
        for task, ep in ((p_task, p_ep), (h_task, h_ep)):
            if not task.done():
                try:
                    return await task, ep
                except Exception as e:  # noqa: BLE001 — transport failure
                    self._note_transport_failure(ep, e, tele)
        return None, p_ep

    def _note_transport_failure(self, endpoint, error, tele) -> None:
        key = endpoint_key(endpoint)
        self._dead.add(key)
        if tele is not None:
            tele.event(
                "serve.host_failure",
                endpoint=key,
                error=type(error).__name__,
            )

    # -------------------------------------------------- collaborative MoE

    def gate_top1(
        self, router_params: np.ndarray, x: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """The gating network's top-1 choice, NumPy mirror of
        parallel/moe.py: softmax over ``x @ router`` -> (expert_idx [T],
        gate [T])."""
        logits = x.astype(np.float32) @ np.asarray(router_params, np.float32)
        z = logits - logits.max(axis=-1, keepdims=True)
        ez = np.exp(z)
        gates = ez / ez.sum(axis=-1, keepdims=True)
        idx = gates.argmax(axis=-1)
        gate = np.take_along_axis(gates, idx[:, None], axis=-1)[:, 0]
        return idx, gate

    async def infer(
        self,
        router_params: np.ndarray,
        x: np.ndarray,
        request_id: str,
        version: Optional[int] = None,
    ) -> Tuple[np.ndarray, Dict[str, int]]:
        """One collaborative MoE layer over the swarm: gate locally, group
        tokens per chosen expert, dispatch the groups concurrently, combine
        gate-weighted — tokens whose expert fell through contribute zeros
        (the Switch residual path, added by the caller exactly as with the
        in-mesh ``moe_ffn``). Returns ``(y [T, H], stats)``."""
        idx, gate = self.gate_top1(router_params, x)
        y = np.zeros_like(x, dtype=np.float32)
        groups: Dict[int, np.ndarray] = {}
        for e in sorted(set(int(v) for v in idx)):
            groups[e] = np.nonzero(idx == e)[0]

        async def one(e: int, rows: np.ndarray):
            return e, rows, await self.dispatch(
                e, x[rows], f"{request_id}/e{e}", version=version
            )

        results = await asyncio.gather(
            *(one(e, rows) for e, rows in groups.items())
        )
        served = fell_through = 0
        for e, rows, out in results:
            if out is None:
                fell_through += len(rows)
                continue
            served += len(rows)
            y[rows] = gate[rows, None].astype(np.float32) * out
        return y, {
            "tokens": int(x.shape[0]),
            "served": served,
            "fall_through": fell_through,
            "experts": len(groups),
        }
