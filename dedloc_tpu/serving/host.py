"""The expert side of the serving plane: host shards, serve dispatches.

An ``ExpertHost`` rides a peer's EXISTING RPC server (the same
``server.register`` seam the checkpoint provider uses), so a training peer
becomes a serving peer by attaching one object — no second listener, no
second port. It:

- registers the ``expert.dispatch`` RPC: admission-check, capacity-check,
  compute the expert FFN on the shipped token batch, return the outputs
  gate-weighting happens at the gateway (``router.py``), mirroring
  ``parallel/moe.py`` where ``combine`` applies the gate after expert_out;
- tracks a per-expert load EWMA and cumulative served counters — the load
  number is republished on every announce, so discovery and load reporting
  are one DHT write;
- accounts bytes/requests served for the contribution ledger
  (``ContributionClaim.bytes_served`` / ``requests_served``).

Expert weights arrive through the content-addressed checkpoint catalog
(the host is handed the already-restored per-expert ``wi``/``wo`` blocks,
or any ``compute_fn`` — the simulator uses a deterministic synthetic one).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from dedloc_tpu.core.serialization import (
    CompressionType,
    deserialize_array,
    serialize_array,
)
from dedloc_tpu.core.timeutils import get_dht_time, monotonic
from dedloc_tpu.serving.admission import (
    Admission,
    REASON_OVER_CAPACITY,
    REASON_UNKNOWN_EXPERT,
    REASON_WRONG_VERSION,
)
from dedloc_tpu.serving.records import (
    DEFAULT_EXPERT_TTL,
    ExpertEntry,
    ExpertRecord,
    LoadEWMA,
    publish_expert_record,
)
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DISPATCH_METHOD = "expert.dispatch"


def ffn_compute_fn(params: Dict[str, np.ndarray]) -> Callable:
    """The real Switch expert computation for restored weights:
    ``gelu(x @ wi[e]) @ wo[e]`` (parallel/moe.py's per-expert math, NumPy
    so a CPU-only serving peer needs no accelerator)."""
    wi, wo = np.asarray(params["wi"]), np.asarray(params["wo"])

    def compute(expert_id: int, x: np.ndarray) -> np.ndarray:
        h = x.astype(np.float32) @ wi[expert_id]
        # tanh-approx GELU matches jax.nn.gelu's default closely enough
        # for serving parity tests (exact equivalence is locked where the
        # weights are, tests/test_moe.py)
        g = 0.5 * h * (1.0 + np.tanh(
            0.7978845608028654 * (h + 0.044715 * h ** 3)
        ))
        return (g @ wo[expert_id]).astype(np.float32)

    return compute


class ExpertHost:
    """Serve a set of expert shards from one peer's RPC server."""

    def __init__(
        self,
        node,  # DHTNode (or any object with .server, .client, .endpoint)
        prefix: str,
        expert_ids: List[int],
        version: int,
        compute_fn: Callable[[int, np.ndarray], np.ndarray],
        capacity: int = 4096,
        admission: Optional[Admission] = None,
        telemetry_registry=None,
        clock: Callable[[], float] = monotonic,
    ):
        self.node = node
        self.prefix = prefix
        self.expert_ids = sorted(int(e) for e in expert_ids)
        self.version = int(version)
        self.compute_fn = compute_fn
        self.capacity = int(capacity)
        self.admission = admission
        self.telemetry = telemetry_registry
        self._clock = clock
        self._load = {e: LoadEWMA(clock) for e in self.expert_ids}
        # cumulative ledger-claim inputs
        self.requests_served = 0
        self.tokens_served = 0
        self.bytes_served = 0
        node.server.register(DISPATCH_METHOD, self._rpc_dispatch)

    # ------------------------------------------------------------ serving

    def _refuse(self, reason: str, expert_id: Any) -> Dict[str, Any]:
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("serve.rejected").inc()
            tele.event("serve.reject", reason=reason, expert_id=expert_id)
        return {"accepted": False, "reason": reason}

    async def _rpc_dispatch(self, peer, args: Dict[str, Any]) -> Dict[str, Any]:
        """One token-batch dispatch. Refusals are STRUCTURED (not raised):
        the router must tell "this replica said no" (reroute, don't retry
        it) apart from "the transport failed" (maybe retry)."""
        expert_id = int(args["expert_id"])
        caller = str(args.get("caller") or peer[0])
        if self.admission is not None:
            reason = self.admission.check(caller)
            if reason is not None:
                return self._refuse(reason, expert_id)
        if expert_id not in self._load:
            return self._refuse(REASON_UNKNOWN_EXPERT, expert_id)
        version = args.get("version")
        if version is not None and int(version) != self.version:
            return self._refuse(REASON_WRONG_VERSION, expert_id)
        x = deserialize_array(args["tokens"])
        if x.ndim != 2:
            raise ValueError(f"tokens must be [T, H], got shape {x.shape}")
        if x.shape[0] > self.capacity:
            # over the per-window token capacity: structured refusal — the
            # gateway falls through to the residual path or another host
            return self._refuse(REASON_OVER_CAPACITY, expert_id)
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            # the span adopts the gateway's trace context off the RPC
            # framing, so one inference request stitches across peers in
            # ``runlog_summary --trace``
            with tele.span(
                "expert.compute", expert_id=expert_id, tokens=int(x.shape[0])
            ):
                y = self.compute_fn(expert_id, x)
        else:
            y = self.compute_fn(expert_id, x)
        load = self._load[expert_id].observe(float(x.shape[0]))
        payload = serialize_array(
            np.ascontiguousarray(y, dtype=np.float32), CompressionType.NONE
        )
        self.requests_served += 1
        self.tokens_served += int(x.shape[0])
        self.bytes_served += len(payload) + len(args["tokens"])
        if tele is not None:
            tele.counter("expert.requests").inc()
            tele.counter("expert.tokens").inc(int(x.shape[0]))
            tele.counter("expert.bytes_served").inc(
                len(payload) + len(args["tokens"])
            )
            tele.gauge("expert.load_ewma").set(round(load, 3))
        return {
            "accepted": True,
            "expert_id": expert_id,
            "data": payload,
            "load_ewma": round(load, 6),
        }

    # ---------------------------------------------------------- discovery

    def record(self) -> ExpertRecord:
        """This host's current ``ExpertRecord`` (live load numbers)."""
        return ExpertRecord(
            peer=self.node.node_id.to_bytes().hex(),
            endpoint=list(self.node.endpoint),
            experts=[
                ExpertEntry(
                    expert_id=e,
                    version=self.version,
                    capacity=self.capacity,
                    load_ewma=round(self._load[e].value(), 6),
                )
                for e in self.expert_ids
            ],
            time=get_dht_time(),
        )

    async def announce(
        self, expiration: float = DEFAULT_EXPERT_TTL
    ) -> bool:
        """Refresh this peer's expert slot in the DHT. Subkey = the node
        id (open-swarm binding); gated runs announce under the RSA owner
        tag via the same helper by passing the signer's subkey through
        ``publish_expert_record`` directly."""
        ok = await publish_expert_record(
            self.node, self.prefix, self.record(),
            subkey=self.node.node_id.to_bytes(), expiration=expiration,
        )
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("expert.announces").inc()
        return ok
