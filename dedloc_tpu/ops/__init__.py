"""Pallas TPU kernels for hot ops."""
from dedloc_tpu.ops.flash_attention import flash_attention  # noqa: F401
