"""Fused flash attention as a Pallas TPU kernel (forward + backward).

The hot op of the ALBERT workload (AlbertSelfAttention, models/albert.py) and
the long-context path. Same exact-softmax math as FlashAttention: the S×S
score matrix never leaves VMEM — logits for one (query-block, kv-block) tile
are computed on the MXU, folded into an online-softmax accumulator, and
discarded. HBM traffic per head drops from O(S²) (XLA's unfused dense path
materializes probs for the backward) to O(S·D + S).

Kernel structure (the canonical Pallas flash shape): the reduction axis is
the INNERMOST GRID DIMENSION, not an in-kernel loop over a resident slab —
TPU grids execute sequentially, so the online-softmax state (acc, m, l) lives
in VMEM scratch across the inner iterations, initialized at the first and
flushed to the output block at the last. VMEM use is O(block), independent of
S: sequence length is bounded by HBM, not VMEM (verified S=16k on a v5e).

Backward follows the standard flash recipe: save only (out, logsumexp) as
residuals, recompute probability tiles on the fly in two kernels (dq over
query blocks, kv innermost; dk/dv over kv blocks, q innermost) using
delta = rowsum(dO ⊙ O).

Layout contract: [B, S, H, D] in/out (the model's layout); internally heads
fold into the grid as [B*H, S, D]. Per-position scalars (bias, lse, delta)
ride as ROW vectors [BH, 1, S]: a [BH, S, 1] column layout would be
128×-padded by the TPU's (8, 128) tiling — 2 GB of HBM for S=16k — so rows
travel packed and are transposed to columns in VMEM where the math needs
them. The additive bias is per KV position (0 keep / -inf drop), broadcast
over heads — exactly the mask bias AlbertModel builds; it is
non-differentiable (it comes from the attention mask).

Off-TPU (CPU tests, CI) the same kernels run under ``interpret=True``.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _pick_block(s: int, preferred: int) -> int:
    block = min(preferred, s)
    while s % block:
        block //= 2
    return max(block, 1)


def _t(x):
    """2D transpose (row [1, N] <-> column [N, 1] relayout in VMEM)."""
    return jnp.swapaxes(x, -1, -2)


# ------------------------------------------------------------------ forward


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, gh, packed):
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # gh heads per program (unrolled): one grid step's DMAs and semaphore
    # work amortise over gh heads' matmuls — at D=64 the per-head dots are
    # too small to hide the per-program overhead (measured on v5e).
    for g in range(gh):
        q = q_ref[g]  # [Bq, D]
        k = k_ref[g]  # [Bk, D]
        v = v_ref[g]
        b = bias_ref[g]  # [1, Bk]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale + b.astype(jnp.float32)

        # softmax state lives as COLUMNS [Bq, 1] in scratch (it never touches
        # HBM) so the running max/denominator broadcast against s with zero
        # cross-lane relayouts; only the lse OUTPUT is a row (HBM tiling).
        m_prev, l_prev = m_ref[g], l_ref[g]  # [Bq, 1] columns
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)  # [Bq, 1]
        l_ref[g] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[g] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[g] = acc_ref[g] * corr + pv

    @pl.when(kb == nk - 1)
    def _flush():
        d = q_ref.shape[-1]
        for g in range(gh):
            safe_l = jnp.maximum(l_ref[g], 1e-30)  # [Bq, 1]
            o = (acc_ref[g] / safe_l).astype(o_ref.dtype)
            if packed:
                # PAIRED output layout: two D=64 heads share one 128-lane
                # tile, so the (remat-saved) output has no lane padding in
                # HBM — half the residual bytes of a [..., 64] layout
                o_ref[g // 2, :, (g % 2) * d:(g % 2 + 1) * d] = o
            else:
                o_ref[g] = o
            lse_ref[g] = _t(m_ref[g] + jnp.log(safe_l))  # -> [1, Bq] row


def _pick_heads(bh: int, block_q: int, block_k: int, budget_mb: float = 6.0):
    """Heads per program: amortise grid-step overhead while keeping the
    per-head transient (fp32 scores + bf16 probs ≈ 6·Bq·Bk bytes) within a
    conservative VMEM budget (~16 MB/core total on v5e)."""
    per_head_mb = 6.0 * block_q * block_k / 2**20
    g = 8
    while g > 1 and (bh % g or g * per_head_mb > budget_mb):
        g //= 2
    return g


def _fwd(q3, k3, v3, bias3, block_q, block_k, interpret):
    """Returns (out, lse). ``out`` is [BH//2, S, 2D] PAIRED when D < 128 and
    the head-group size is even (no lane padding in HBM — matters because
    the remat policy saves this tensor per layer), else [BH, S, D]."""
    bh, s, d = q3.shape
    bq = _pick_block(s, block_q)
    bk = _pick_block(s, block_k)
    gh = _pick_heads(bh, bq, bk)
    packed = d < 128 and gh % 2 == 0
    scale = 1.0 / (d ** 0.5)
    if packed:
        out_spec = pl.BlockSpec((gh // 2, bq, 2 * d),
                                lambda i, j, kb: (i, j, 0))
        out_shape = jax.ShapeDtypeStruct((bh // 2, s, 2 * d), q3.dtype)
    else:
        out_spec = pl.BlockSpec((gh, bq, d), lambda i, j, kb: (i, j, 0))
        out_shape = jax.ShapeDtypeStruct((bh, s, d), q3.dtype)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, gh=gh, packed=packed),
        grid=(bh // gh, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((gh, bq, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((gh, bk, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((gh, bk, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((gh, 1, bk), lambda i, j, kb: (i, 0, kb)),
        ],
        out_specs=[
            out_spec,
            pl.BlockSpec((gh, 1, bq), lambda i, j, kb: (i, 0, j)),
        ],
        out_shape=[
            out_shape,
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((gh, bq, d), jnp.float32),
            pltpu.VMEM((gh, bq, 1), jnp.float32),
            pltpu.VMEM((gh, bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, bias3)
    return out, lse


def _unpack_heads(out, bh: int, d: int):
    """[BH//2, S, 2D] paired -> [BH, S, D] (cheap relayout; inverse pairing
    of the fwd kernel's flush)."""
    if out.shape[0] == bh:
        return out
    half, s, _ = out.shape
    return out.reshape(half, s, 2, d).transpose(0, 2, 1, 3).reshape(bh, s, d)


# ----------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, lse_ref, do_ref, delta_ref,
               dq_ref, dq_acc_ref, *, scale, gh):
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    for g in range(gh):
        q = q_ref[g]
        k = k_ref[g]
        v = v_ref[g]
        b = bias_ref[g]  # [1, Bk]
        do = do_ref[g]  # native (bf16) dtype — MXU runs at full rate
        lse = _t(lse_ref[g])  # [1, Bq] row -> [Bq, 1] column
        delta = _t(delta_ref[g])

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale + b.astype(jnp.float32)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dq_acc_ref[g] = dq_acc_ref[g] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == nk - 1)
    def _flush():
        for g in range(gh):
            dq_ref[g] = dq_acc_ref[g].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, lse_ref, do_ref, delta_ref,
                dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, scale, gh):
    qb = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    for g in range(gh):
        q = q_ref[g]
        k = k_ref[g]
        v = v_ref[g]
        b = bias_ref[g]  # [1, Bk]
        do = do_ref[g]
        lse = _t(lse_ref[g])  # [Bq, 1]
        delta = _t(delta_ref[g])

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale + b.astype(jnp.float32)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        dv_acc_ref[g] = dv_acc_ref[g] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale  # [Bq, Bk]
        dk_acc_ref[g] = dk_acc_ref[g] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qb == nq - 1)
    def _flush():
        for g in range(gh):
            dk_ref[g] = dk_acc_ref[g].astype(dk_ref.dtype)
            dv_ref[g] = dv_acc_ref[g].astype(dv_ref.dtype)


def _dqkv_fused_kernel(q_ref, k_ref, v_ref, bias_ref, lse_ref, do_ref,
                       delta_ref, dq_ref, dk_ref, dv_ref, *, scale, gh):
    """Single-block backward: when one (Bq, Bk) tile covers the whole
    sequence, dq/dk/dv share ONE score/prob computation and one set of
    input DMAs instead of recomputing them in two kernels."""
    for g in range(gh):
        q = q_ref[g]
        k = k_ref[g]
        v = v_ref[g]
        b = bias_ref[g]  # [1, Bk]
        do = do_ref[g]
        lse = _t(lse_ref[g])  # [Bq, 1]
        delta = _t(delta_ref[g])

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale + b.astype(jnp.float32)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        pb = p.astype(do.dtype)
        dv_ref[g] = jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * scale).astype(q.dtype)  # [Bq, Bk]
        dq_ref[g] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dq_ref.dtype)
        dk_ref[g] = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dk_ref.dtype)


def _bwd(q3, k3, v3, bias3, lse, do, delta, block_q, block_k, interpret):
    bh, s, d = q3.shape
    bq = _pick_block(s, block_q)
    bk = _pick_block(s, block_k)
    scale = 1.0 / (d ** 0.5)
    if bq == s and bk == s:
        return _bwd_fused(q3, k3, v3, bias3, lse, do, delta, interpret)
    # bwd transients per head are ~3x the fwd's (s, p, dp, ds live at once)
    gh = _pick_heads(bh, bq, bk, budget_mb=4.0)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, gh=gh),
        grid=(bh // gh, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((gh, bq, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((gh, bk, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((gh, bk, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((gh, 1, bk), lambda i, j, kb: (i, 0, kb)),
            pl.BlockSpec((gh, 1, bq), lambda i, j, kb: (i, 0, j)),
            pl.BlockSpec((gh, bq, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((gh, 1, bq), lambda i, j, kb: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((gh, bq, d), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((gh, bq, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, bias3, lse, do, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, gh=gh),
        grid=(bh // gh, s // bk, s // bq),
        in_specs=[
            pl.BlockSpec((gh, bq, d), lambda i, j, qb: (i, qb, 0)),
            pl.BlockSpec((gh, bk, d), lambda i, j, qb: (i, j, 0)),
            pl.BlockSpec((gh, bk, d), lambda i, j, qb: (i, j, 0)),
            pl.BlockSpec((gh, 1, bk), lambda i, j, qb: (i, 0, j)),
            pl.BlockSpec((gh, 1, bq), lambda i, j, qb: (i, 0, qb)),
            pl.BlockSpec((gh, bq, d), lambda i, j, qb: (i, qb, 0)),
            pl.BlockSpec((gh, 1, bq), lambda i, j, qb: (i, 0, qb)),
        ],
        out_specs=[
            pl.BlockSpec((gh, bk, d), lambda i, j, qb: (i, j, 0)),
            pl.BlockSpec((gh, bk, d), lambda i, j, qb: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((gh, bk, d), jnp.float32),
            pltpu.VMEM((gh, bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, bias3, lse, do, delta)
    return dq, dk, dv


def _bwd_fused(q3, k3, v3, bias3, lse, do, delta, interpret):
    bh, s, d = q3.shape
    scale = 1.0 / (d ** 0.5)
    # fused kernel holds s, p, dp, ds (~4 full tiles) at once per head
    gh = _pick_heads(bh, s, s, budget_mb=3.0)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_dqkv_fused_kernel, scale=scale, gh=gh),
        grid=(bh // gh,),
        in_specs=[
            pl.BlockSpec((gh, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((gh, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((gh, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((gh, 1, s), lambda i: (i, 0, 0)),
            pl.BlockSpec((gh, 1, s), lambda i: (i, 0, 0)),
            pl.BlockSpec((gh, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((gh, 1, s), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((gh, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((gh, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((gh, s, d), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, s, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v3.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, bias3, lse, do, delta)
    return dq, dk, dv


# --------------------------------------------------------------- public op


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q3, k3, v3, bias3, block_q, block_k, interpret):
    out, _lse = _fwd(q3, k3, v3, bias3, block_q, block_k, interpret)
    return out


def _flash_fwd(q3, k3, v3, bias3, block_q, block_k, interpret):
    # ``out`` may be head-PAIRED [BH//2, S, 2D] (see _fwd): that exact array
    # is what the dots_no_batch_attn remat policy saves per layer, so the
    # packed layout halves the residual's HBM footprint at D=64
    out, lse = _fwd(q3, k3, v3, bias3, block_q, block_k, interpret)
    return out, (q3, k3, v3, bias3, out, lse)


def _flash_bwd(block_q, block_k, interpret, residuals, g):
    q3, k3, v3, bias3, out, lse = residuals
    bh, _, d = q3.shape
    if out.shape[0] != bh:  # paired layout: delta on packed forms, then
        half = bh // 2      # one cheap permutation for the kernels' do
        prod = g.astype(jnp.float32) * out.astype(jnp.float32)
        s_len = prod.shape[1]
        delta = (
            prod.reshape(half, s_len, 2, d).sum(-1)
            .transpose(0, 2, 1).reshape(bh, 1, s_len)
        )
        do = _unpack_heads(g, bh, d)
    else:
        delta = jnp.sum(
            g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
        )[:, None, :]  # [BH, 1, S] row layout (see module docstring)
        do = g
    dq, dk, dv = _bwd(q3, k3, v3, bias3, lse, do, delta, block_q, block_k,
                      interpret)
    # the mask bias is non-differentiable input
    return dq, dk, dv, jnp.zeros_like(bias3)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,  # [B, S_kv] additive
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Exact fused attention; drop-in for dense/blockwise attention.

    ``interpret=None`` auto-selects: compiled on TPU, interpreter elsewhere
    (so CPU tests and the virtual mesh exercise identical kernel code).
    On TPU, effective block sizes must be multiples of 128 (or the whole
    sequence) for the bias/lse BlockSpecs to be Mosaic-legal.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = q.shape
    # named in KERNEL layout so the fused_ln remat policy saves exactly what
    # the flash backward consumes — the replay then skips the [B,S,H,D] ->
    # [BH,S,D] relayout passes too
    from jax.ad_checkpoint import checkpoint_name

    to3 = lambda x, nm: checkpoint_name(
        x.transpose(0, 2, 1, 3).reshape(b * h, s, d), nm
    )
    if bias is None:
        bias3 = jnp.zeros((b * h, 1, s), jnp.float32)
    else:
        bias3 = jnp.broadcast_to(
            bias[:, None, :], (b, h, s)
        ).reshape(b * h, 1, s).astype(jnp.float32)
    out3 = _flash(to3(q, "flash_qkv"), to3(k, "flash_qkv"),
                  to3(v, "flash_qkv"), bias3, block_q, block_k, interpret)
    out3 = _unpack_heads(out3, b * h, d)  # paired layout -> [BH, S, D]
    return out3.reshape(b, h, s, d).transpose(0, 2, 1, 3)
