"""Fused residual-add + LayerNorm as a Pallas TPU kernel (fwd + bwd).

The remat replay's elementwise HBM passes are the second-largest sink in the
ALBERT step after attention (docs/perf.md "Remaining gap"): under
rematerialisation, the backward pass re-runs the layer's add→LayerNorm
chains from saved matmul outputs — each a read+write of a [B,S,H] tensor at
HBM bandwidth, plus fp32 mean/variance recomputation.

This kernel makes the whole post-matmul tail ONE pass each way:

forward   y = LN(x + r) · γ + β      one kernel: reads x, r; writes y and
                                     the backward's residuals (x̂, rstd)
backward  (dy) -> (da, dγ, dβ)       one kernel: da serves both dx and dr
                                     (the residual add backpropagates the
                                     same cotangent to both inputs)

Designed to compose with the ``fused_ln`` remat policy (models/albert.py):
Pallas outputs are saveable, so (y, x̂, rstd) survive remat and the backward
runs straight from them — no add/LN replay at all. The policy drops the two
out-projection matmul saves the adds used to consume (attention out-proj,
FFN down-proj), so the extra x̂ residual is HBM-neutral versus the
``dots_no_batch_attn`` policy.

Layout contract: inputs flatten to [N, H] rows. Per-row scalars (rstd) ride
as ROW vectors [1, N] — a [N, 1] column would be 128×-padded by the TPU's
(8, 128) tiling (same trick as the flash kernel's lse). γ/β ride as [1, H]
rows. dγ/dβ accumulate across the sequential TPU grid directly in their
output blocks (constant index map => the block stays resident in VMEM).

Statistics are fp32 regardless of input dtype; x̂ is stored in the input
dtype (bf16) — the same precision the unfused path's backward sees, since
its replay also recomputes statistics from bf16 activations.

Off-TPU the kernels run under ``interpret=True`` (CPU tests, virtual mesh).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dedloc_tpu.ops.flash_attention import _pick_block


def _t(x):
    return jnp.swapaxes(x, -1, -2)


# ------------------------------------------------------------------ forward


def _fwd_kernel(x_ref, r_ref, gamma_ref, beta_ref, y_ref, xhat_ref,
                rstd_ref, *, eps):
    a = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
    mu = jnp.mean(a, axis=-1, keepdims=True)  # [bn, 1] column (VMEM only)
    centred = a - mu
    var = jnp.mean(centred * centred, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = centred * rstd
    gamma = gamma_ref[:].astype(jnp.float32)  # [1, H] broadcast row
    beta = beta_ref[:].astype(jnp.float32)
    y_ref[:] = (xhat * gamma + beta).astype(y_ref.dtype)
    if xhat_ref is not None:  # y-only variant for non-differentiating calls
        xhat_ref[:] = xhat.astype(xhat_ref.dtype)
        rstd_ref[:] = _t(rstd)  # -> [1, bn] row (HBM tiling)


def _fwd(x2, r2, gamma, beta, eps, block_n, interpret, with_residuals=True):
    """``with_residuals=False`` emits a y-only kernel: inference/eval calls
    skip the [N, H] x̂ + rstd HBM writes that only the backward needs."""
    n, h = x2.shape
    bn = _pick_block(n, block_n)
    row_spec = pl.BlockSpec((bn, h), lambda i: (i, 0))
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((n, h), x2.dtype)]
    if with_residuals:
        out_specs += [row_spec, pl.BlockSpec((1, bn), lambda i: (0, i))]
        out_shape += [
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ]
        kernel = functools.partial(_fwd_kernel, eps=eps)
    else:
        def kernel(x_ref, r_ref, gamma_ref, beta_ref, y_ref):
            _fwd_kernel(x_ref, r_ref, gamma_ref, beta_ref, y_ref,
                        None, None, eps=eps)

    outs = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            row_spec,
            row_spec,
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x2, r2, gamma[None, :], beta[None, :])
    return outs if with_residuals else (outs[0], None, None)


# ----------------------------------------------------------------- backward


def _bwd_kernel(xhat_ref, rstd_ref, gamma_ref, dy_ref, da_ref, dgamma_ref,
                dbeta_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dgamma_ref[:] = jnp.zeros_like(dgamma_ref)
        dbeta_ref[:] = jnp.zeros_like(dbeta_ref)

    xhat = xhat_ref[:].astype(jnp.float32)  # [bn, H]
    dy = dy_ref[:].astype(jnp.float32)
    gamma = gamma_ref[:].astype(jnp.float32)  # [1, H]
    rstd = _t(rstd_ref[:])  # [1, bn] row -> [bn, 1] column

    gdy = dy * gamma
    m1 = jnp.mean(gdy, axis=-1, keepdims=True)  # [bn, 1]
    m2 = jnp.mean(gdy * xhat, axis=-1, keepdims=True)
    da_ref[:] = ((gdy - m1 - xhat * m2) * rstd).astype(da_ref.dtype)

    # γ/β gradients accumulate in the resident output block across the
    # sequential grid (constant index map)
    dgamma_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    dbeta_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


def _bwd(xhat, rstd, gamma, dy, block_n, interpret):
    n, h = xhat.shape
    bn = _pick_block(n, block_n)
    da, dgamma, dbeta = pl.pallas_call(
        _bwd_kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), dy.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=interpret,
    )(xhat, rstd, gamma[None, :], dy)
    return da, dgamma[0], dbeta[0]


# --------------------------------------------------------------- public op


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ln_residual(x2, r2, gamma, beta, eps, block_n, interpret):
    # primal without differentiation (eval/serving): y-only kernel — the
    # x̂/rstd residuals are only materialized by the vjp-fwd rule below
    y, _, _ = _fwd(x2, r2, gamma, beta, eps, block_n, interpret,
                   with_residuals=False)
    return y


def _ln_residual_fwd(x2, r2, gamma, beta, eps, block_n, interpret):
    # (y, xhat, rstd) are Pallas outputs => saved by the fused_ln remat
    # policy: the backward below never replays the add/LN chain
    y, xhat, rstd = _fwd(x2, r2, gamma, beta, eps, block_n, interpret)
    return y, (xhat, rstd, gamma)


def _ln_residual_bwd(eps, block_n, interpret, residuals, dy):
    xhat, rstd, gamma = residuals
    da, dgamma, dbeta = _bwd(xhat, rstd, gamma, dy, block_n, interpret)
    # the residual add fans the same cotangent to both inputs
    return da, da, dgamma, dbeta


_ln_residual.defvjp(_ln_residual_fwd, _ln_residual_bwd)


def _default_block_n() -> int:
    """Rows per grid step (tunable via DEDLOC_FUSED_LN_BLOCK for sweeps;
    256 measured best on v5e at H=1024 — see docs/perf.md)."""
    import os

    return int(os.environ.get("DEDLOC_FUSED_LN_BLOCK", "256"))


def ln_residual(
    x: jnp.ndarray,  # [..., H] (the matmul-output branch)
    r: jnp.ndarray,  # [..., H] (the residual branch)
    gamma: jnp.ndarray,  # [H] fp32
    beta: jnp.ndarray,  # [H] fp32
    eps: float = 1e-12,
    block_n: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """``LayerNorm(x + r) * gamma + beta`` as one fused pass (fp32 stats),
    returned in ``x.dtype``. ``interpret=None`` auto-selects: compiled on
    TPU, interpreter elsewhere."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_n is None:
        block_n = _default_block_n()
    h = x.shape[-1]
    lead = x.shape[:-1]
    x2 = x.reshape(-1, h)
    r2 = r.reshape(-1, h)
    y = _ln_residual(
        x2, r2,
        gamma.astype(jnp.float32), beta.astype(jnp.float32),
        float(eps), block_n, interpret,
    )
    return y.reshape(*lead, h)


def ln_residual_reference(x, r, gamma, beta, eps: float = 1e-12):
    """Pure-jnp twin of ``ln_residual`` (numerics oracle for tests, and the
    fallback for shapes the TPU kernel does not serve)."""
    a = x.astype(jnp.float32) + r.astype(jnp.float32)
    mu = jnp.mean(a, axis=-1, keepdims=True)
    centred = a - mu
    var = jnp.mean(centred * centred, axis=-1, keepdims=True)
    xhat = centred * jax.lax.rsqrt(var + eps)
    y = xhat * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    return y.astype(x.dtype)
