"""Checkpoint manifests: a signed, content-addressed description of one
collaboration state snapshot.

A checkpoint is no longer one opaque ``state.bin``: the state tree is
flattened through the SAME ``TreeLayout`` the averaging wire path uses (one
fp32 vector, name-sorted spec) and cut into fixed-size **shards**. The
manifest records the step, the tree layout, the shard geometry and one
sha256 per shard — so any single shard can be fetched from any peer that
holds it and verified in isolation, and the assembled tree is bit-identical
to the source by construction (fp32 roundtrips exactly through the NONE
wire codec; non-fp32 leaves are checked for exact representability at
build time and refused otherwise).

The manifest itself is small (KBs) and content-addressed by its own sha256
(``digest()``); the DHT catalog record (checkpointing/catalog.py) carries
that digest on the existing signed-record machinery, so a fetcher can pull
the manifest from ANY provider and verify it against the signed digest.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dedloc_tpu.core.serialization import pack_obj, unpack_obj

# NOTE: dedloc_tpu.averaging.partition (TreeLayout) is imported lazily
# inside the functions below — the averager imports this package at module
# scope, and averaging/__init__ imports the averager, so a top-level import
# here would close an import cycle.

DEFAULT_SHARD_SIZE = 1 << 20  # fp32 elements per shard = 4 MiB raw

_MANIFEST_VERSION = 1


@dataclass(frozen=True)
class CheckpointManifest:
    """Immutable description of one sharded checkpoint.

    ``spec`` is the TreeLayout spec with dtypes as strings (msgpack-safe);
    ``shard_digests[i]`` is sha256 over shard i's raw little-endian fp32
    bytes. ``metadata`` is the same small control dict the full-blob state
    path ships ({"step", "local_step", ...}).
    """

    step: int
    shard_size: int  # fp32 elements per shard (last shard may be smaller)
    total_size: int  # fp32 elements overall
    spec: Tuple[Tuple[str, Tuple[int, ...], str], ...]
    shard_digests: Tuple[bytes, ...]
    metadata: Dict[str, Any] = field(default_factory=dict)

    @property
    def num_shards(self) -> int:
        return len(self.shard_digests)

    @property
    def total_bytes(self) -> int:
        return self.total_size * 4

    def shard_span(self, index: int) -> Tuple[int, int]:
        """[start, end) element range of shard ``index`` in the flat vector."""
        if not 0 <= index < self.num_shards:
            raise IndexError(f"shard {index} not in [0, {self.num_shards})")
        start = index * self.shard_size
        return start, min(start + self.shard_size, self.total_size)

    def shard_nbytes(self, index: int) -> int:
        start, end = self.shard_span(index)
        return (end - start) * 4

    def layout_spec(self) -> List[Tuple[str, Tuple[int, ...], np.dtype]]:
        """The spec with real np.dtype objects (unflatten_tree's shape)."""
        return [
            (name, tuple(shape), np.dtype(dtype))
            for name, shape, dtype in self.spec
        ]

    def to_bytes(self) -> bytes:
        return pack_obj(
            {
                "v": _MANIFEST_VERSION,
                "step": int(self.step),
                "shard_size": int(self.shard_size),
                "total_size": int(self.total_size),
                "spec": [
                    [name, list(shape), dtype] for name, shape, dtype in self.spec
                ],
                "digests": list(self.shard_digests),
                "metadata": self.metadata,
            }
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "CheckpointManifest":
        obj = unpack_obj(data)
        if obj.get("v") != _MANIFEST_VERSION:
            raise ValueError(f"unknown manifest version {obj.get('v')!r}")
        manifest = cls(
            step=int(obj["step"]),
            shard_size=int(obj["shard_size"]),
            total_size=int(obj["total_size"]),
            spec=tuple(
                (name, tuple(shape), dtype) for name, shape, dtype in obj["spec"]
            ),
            shard_digests=tuple(obj["digests"]),
            metadata=obj.get("metadata") or {},
        )
        manifest.validate()
        return manifest

    def validate(self) -> None:
        """Structural sanity independent of any shard data — run on every
        manifest received off the wire before trusting its geometry."""
        if self.shard_size <= 0:
            raise ValueError(f"shard_size must be positive: {self.shard_size}")
        if self.total_size < 0:
            raise ValueError(f"negative total_size: {self.total_size}")
        expected = -(-self.total_size // self.shard_size)
        if self.num_shards != expected:
            raise ValueError(
                f"manifest claims {self.num_shards} shards; geometry implies "
                f"{expected}"
            )
        spec_size = sum(
            int(np.prod(shape)) if shape else 1 for _n, shape, _d in self.spec
        )
        if spec_size != self.total_size:
            raise ValueError(
                f"layout spec covers {spec_size} elements, manifest says "
                f"{self.total_size}"
            )
        for d in self.shard_digests:
            if not isinstance(d, (bytes, bytearray)) or len(d) != 32:
                raise ValueError("shard digests must be 32-byte sha256")

    def digest(self) -> bytes:
        """sha256 of the serialized manifest — what the signed DHT catalog
        record carries, and what a fetched manifest is verified against."""
        return hashlib.sha256(self.to_bytes()).digest()


def build_manifest(
    tree: Dict[str, np.ndarray],
    step: int,
    shard_size: int = DEFAULT_SHARD_SIZE,
    metadata: Optional[Dict[str, Any]] = None,
) -> Tuple[CheckpointManifest, np.ndarray]:
    """Flatten ``tree`` (TreeLayout — the averaging path's layout) and cut it
    into content-addressed shards. Returns (manifest, flat) where ``flat``
    is a FRESH fp32 vector the caller owns (checkpoint shards outlive
    averaging rounds, so the averager's reused round buffer is never used).

    Raises ValueError when a non-fp32 leaf does not roundtrip exactly
    through the fp32 flat vector (e.g. int64 counters past 2**24) — such a
    tree must ship over the full-blob path, which preserves dtypes natively.
    """
    from dedloc_tpu.averaging.partition import TreeLayout

    if shard_size <= 0:
        raise ValueError(f"shard_size must be positive, got {shard_size}")
    layout = TreeLayout.for_tree(tree)
    flat = layout.flatten_into(tree, np.empty((layout.total_size,), np.float32))
    for (name, shape, dtype), offset in zip(layout.spec, layout.offsets):
        if dtype == np.float32:
            continue
        size = int(np.prod(shape)) if shape else 1
        restored = flat[offset : offset + size].astype(dtype).reshape(shape)
        if not np.array_equal(restored, np.asarray(tree[name])):
            raise ValueError(
                f"leaf {name!r} ({dtype}) does not roundtrip exactly through "
                "the fp32 flat layout; use the full-blob state path"
            )
    digests = []
    for start in range(0, layout.total_size, shard_size):
        chunk = flat[start : start + shard_size]
        digests.append(hashlib.sha256(np.ascontiguousarray(chunk).tobytes()).digest())
    manifest = CheckpointManifest(
        step=int(step),
        shard_size=int(shard_size),
        total_size=layout.total_size,
        spec=tuple(
            (name, tuple(shape), np.dtype(dtype).str)
            for name, shape, dtype in layout.spec
        ),
        shard_digests=tuple(digests),
        metadata=dict(metadata or {}),
    )
    return manifest, flat


def shard_bytes(flat: np.ndarray, manifest: CheckpointManifest, index: int) -> bytes:
    """Raw little-endian fp32 bytes of shard ``index`` (the content the
    per-shard digest covers)."""
    start, end = manifest.shard_span(index)
    return np.ascontiguousarray(flat[start:end]).tobytes()


def verify_shard(
    manifest: CheckpointManifest, index: int, raw: bytes
) -> np.ndarray:
    """Validate shard ``index``'s raw bytes against the manifest (size AND
    sha256) and return it as an fp32 vector. Raises ValueError on mismatch —
    the fetcher's signal to retry the shard from another provider."""
    if len(raw) != manifest.shard_nbytes(index):
        raise ValueError(
            f"shard {index}: got {len(raw)} bytes, manifest says "
            f"{manifest.shard_nbytes(index)}"
        )
    if hashlib.sha256(raw).digest() != manifest.shard_digests[index]:
        raise ValueError(f"shard {index} failed sha256 verification")
    return np.frombuffer(raw, dtype=np.float32)


def assemble_tree(
    manifest: CheckpointManifest, shards: Dict[int, np.ndarray]
) -> Dict[str, np.ndarray]:
    """Reassemble the state tree from a complete set of verified shards."""
    from dedloc_tpu.averaging.partition import unflatten_tree

    missing = [i for i in range(manifest.num_shards) if i not in shards]
    if missing:
        raise ValueError(f"cannot assemble: missing shards {missing[:8]}")
    flat = np.empty((manifest.total_size,), np.float32)
    for i in range(manifest.num_shards):
        start, end = manifest.shard_span(i)
        flat[start:end] = shards[i]
    return unflatten_tree(flat, manifest.layout_spec())
