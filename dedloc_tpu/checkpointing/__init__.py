"""Swarm checkpointing: sharded, content-addressed checkpoints with a DHT
catalog and multi-peer restore.

A checkpoint is a signed **manifest** (step, tree layout, per-shard sha256)
plus fixed-size content-addressed **shards** cut from the TreeLayout flat
buffer (``manifest``), persisted locally in a dedup'ing ``ShardStore``
(``store``), announced on the DHT via schema-validated, signature-capable
catalog records (``catalog``), and restored by pulling distinct shards from
distinct providers in parallel with per-shard verification and the standard
retry/backoff ladder (``fetcher``).

Operator view: docs/fleet.md "Restart & bootstrap runbook"; counters in
docs/observability.md.
"""
from dedloc_tpu.checkpointing.catalog import (
    CheckpointAnnouncement,
    catalog_key,
    parse_announcements,
    publish_announcement,
    select_target,
)
from dedloc_tpu.checkpointing.fetcher import (
    RestoreFailed,
    fetch_manifest,
    fetch_shards,
    sharded_restore,
)
from dedloc_tpu.checkpointing.manifest import (
    DEFAULT_SHARD_SIZE,
    CheckpointManifest,
    assemble_tree,
    build_manifest,
    shard_bytes,
    verify_shard,
)
from dedloc_tpu.checkpointing.store import (
    ShardStore,
    load_sharded_checkpoint,
    save_sharded_checkpoint,
)

__all__ = [
    "CheckpointAnnouncement",
    "CheckpointManifest",
    "DEFAULT_SHARD_SIZE",
    "RestoreFailed",
    "ShardStore",
    "assemble_tree",
    "build_manifest",
    "catalog_key",
    "fetch_manifest",
    "fetch_shards",
    "load_sharded_checkpoint",
    "parse_announcements",
    "publish_announcement",
    "save_sharded_checkpoint",
    "select_target",
    "shard_bytes",
    "sharded_restore",
    "verify_shard",
]
