"""The DHT checkpoint catalog: who holds which shards of which checkpoint.

One dictionary record per collaboration at ``{prefix}_checkpoint_catalog``,
one subkey per announcing peer (the same signed-record machinery as the
metrics bus: when the subkey is a peer's RSA owner tag, the record is
signature-bound to that peer; the ``CheckpointAnnouncement`` schema below is
validated at every storing node either way, so a malformed or out-of-range
announcement is rejected at the DHT boundary, not discovered mid-restore).

An announcement says: "at ``endpoint`` I serve shards of the checkpoint at
``step`` whose manifest hashes to ``manifest_digest``; I hold ``shards``
(None = all ``num_shards``)". The multi-peer fetcher groups announcements by
(step, manifest_digest), pulls the manifest from any of them, verifies it
against the digest, and spreads the shard downloads across the providers.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from pydantic import BaseModel, StrictBytes, StrictInt, model_validator

from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def catalog_key(prefix: str) -> str:
    return f"{prefix}_checkpoint_catalog"


class CheckpointAnnouncement(BaseModel):
    """Schema for one peer's catalog subkey (validated by the DHT's
    SchemaValidator chain — see collaborative/metrics.py make_validators)."""

    step: StrictInt
    manifest_digest: StrictBytes  # sha256 of the serialized manifest
    num_shards: StrictInt
    endpoint: List  # [host, port] — the peer's averager RPC endpoint
    shards: Optional[List[StrictInt]] = None  # held shard indices; None = all

    @model_validator(mode="after")
    def _check(self) -> "CheckpointAnnouncement":
        if self.step < 0:
            raise ValueError(f"negative step {self.step}")
        if self.num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {self.num_shards}")
        if len(self.manifest_digest) != 32:
            raise ValueError("manifest_digest must be a 32-byte sha256")
        if (
            len(self.endpoint) != 2
            or not isinstance(self.endpoint[0], str)
            or not isinstance(self.endpoint[1], int)
        ):
            raise ValueError(f"endpoint must be [host, port]: {self.endpoint}")
        if self.shards is not None:
            if not self.shards:
                raise ValueError("shards list must not be empty (use None)")
            if min(self.shards) < 0 or max(self.shards) >= self.num_shards:
                raise ValueError(
                    f"shard indices out of range [0, {self.num_shards})"
                )
        return self

    def held_indices(self) -> Optional[frozenset]:
        """Shard indices this provider holds (None = all of them)."""
        return None if self.shards is None else frozenset(self.shards)


def publish_announcement(
    dht,
    prefix: str,
    subkey: bytes,
    announcement: CheckpointAnnouncement,
    expiration: float = 60.0,
) -> None:
    """Store this peer's catalog record (non-blocking, like the provider
    record it rides next to)."""
    dht.store(
        catalog_key(prefix),
        announcement.model_dump(),
        get_dht_time() + expiration,
        subkey=subkey,
        return_future=True,
    )


def parse_announcements(
    entry_items, own_subkeys: Tuple[bytes, ...] = ()
) -> List[CheckpointAnnouncement]:
    """THE one parsing path for catalog records: skip our own subkeys, drop
    anything that fails the schema (defense in depth — a storing node that
    predates the schema may have accepted garbage). ``entry_items`` is an
    iterable of (subkey, unpacked announcement dict)."""
    out: List[CheckpointAnnouncement] = []
    for sk, value in entry_items:
        if sk in own_subkeys:
            continue
        try:
            out.append(CheckpointAnnouncement.model_validate(value))
        except Exception as e:  # noqa: BLE001 — malformed announcement
            logger.debug(f"dropping malformed catalog record: {e!r}")
            continue
    return out


def select_target(
    announcements: List[CheckpointAnnouncement],
) -> Optional[Tuple[int, bytes, List[CheckpointAnnouncement]]]:
    """Pick the restore target: the deepest advertised step, and among
    digests at that step the one with the MOST providers (a lone peer
    announcing a divergent manifest at the same step must not outvote the
    swarm). Returns (step, manifest_digest, providers) or None."""
    if not announcements:
        return None
    best_step = max(a.step for a in announcements)
    at_step = [a for a in announcements if a.step == best_step]
    by_digest: Dict[bytes, List[CheckpointAnnouncement]] = {}
    for a in at_step:
        by_digest.setdefault(a.manifest_digest, []).append(a)
    digest, providers = max(
        by_digest.items(), key=lambda kv: (len(kv[1]), kv[0])
    )
    return best_step, digest, providers
