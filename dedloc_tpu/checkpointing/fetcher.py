"""Multi-peer checkpoint restore: pull distinct shards from distinct
providers in parallel, verify each against the manifest, retry on the
existing backoff ladder, resume partial downloads from a local ShardStore.

This is the joiner/restart half of the swarm checkpoint subsystem: where the
full-blob path downloads hundreds of MB from ONE provider's uplink, the
sharded path spreads the same bytes across every peer announcing the target
manifest in the DHT catalog — restore bandwidth scales with the provider
count, and any single provider dying or serving a corrupt shard costs one
per-shard retry, not the restore.

Runs entirely on the caller's event loop (the averager invokes it on the
DHT loop with its pooled RPCClient).
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from dedloc_tpu.checkpointing.catalog import (
    CheckpointAnnouncement,
    select_target,
)
from dedloc_tpu.checkpointing.manifest import (
    CheckpointManifest,
    assemble_tree,
    verify_shard,
)
from dedloc_tpu.checkpointing.store import ShardStore
from dedloc_tpu.core.serialization import deserialize_array
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.telemetry.links import endpoint_key
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

Endpoint = Tuple[str, int]
# one provider: (endpoint, shard indices it holds; None = all)
Provider = Tuple[Endpoint, Optional[FrozenSet[int]]]


class RestoreFailed(RuntimeError):
    """A sharded restore could not complete (no providers, manifest
    unobtainable, or some shard exhausted its retry ladder). The caller
    falls back to the full-blob path."""


async def fetch_manifest(
    client,
    endpoints: Sequence[Endpoint],
    digest: bytes,
    timeout: float = 30.0,
) -> CheckpointManifest:
    """Pull the manifest from any provider and verify it against the
    catalog's (signed) digest — the manifest can come from ANYONE once the
    digest is pinned."""
    last: Optional[Exception] = None
    for ep in endpoints:
        try:
            reply = await client.call(ep, "ckpt.manifest", {}, timeout=timeout)
            blob = reply["manifest"]
            manifest = CheckpointManifest.from_bytes(blob)
            if manifest.digest() != digest:
                raise ValueError(
                    f"manifest from {ep} does not match the announced digest"
                )
            return manifest
        except Exception as e:  # noqa: BLE001 — next provider
            last = e
            logger.debug(f"manifest fetch from {ep} failed: {e!r}")
    raise RestoreFailed(f"no provider served a valid manifest: {last!r}")


def _candidates_for(
    index: int, providers: Sequence[Provider]
) -> List[Endpoint]:
    """Providers holding shard ``index``, rotated by the index so a full
    restore spreads shards round-robin across the provider set (distinct
    shards land on distinct uplinks instead of all hammering provider 0)."""
    holders = [ep for ep, held in providers if held is None or index in held]
    if not holders:
        return []
    rot = index % len(holders)
    return holders[rot:] + holders[:rot]


async def _fetch_one_shard(
    client,
    manifest: CheckpointManifest,
    index: int,
    providers: Sequence[Provider],
    *,
    retries: int,
    backoff: float,
    timeout: float,
    store: Optional[ShardStore],
    failed_providers: set,
    tele,
    provider_bytes: Optional[Dict[str, int]] = None,
) -> np.ndarray:
    candidates = _candidates_for(index, providers)
    if not candidates:
        raise RestoreFailed(f"no provider announces shard {index}")
    last: Optional[Exception] = None
    for attempt in range(retries + 1):
        if attempt:
            delay = backoff * (2 ** (attempt - 1))
            if tele is not None:
                tele.counter("ckpt.fetch_retries").inc()
            await asyncio.sleep(delay)
        # prefer providers that have not failed yet; when everyone has,
        # retry them all anyway (a transient fault on the only provider
        # must not fail the restore) — same ladder as the blob state sync
        pool = [ep for ep in candidates if ep not in failed_providers]
        pool = pool or candidates
        ep = pool[attempt % len(pool)]
        try:
            # monotonic_clock (not perf_counter): advances with the
            # fake-clock offset, so a simulated transfer's goodput reflects
            # the MODELED link, not host execution noise; production
            # (offset 0) reads identically to a raw monotonic clock
            t0 = telemetry.monotonic_clock()
            reply = await client.call(
                ep, "ckpt.shard", {"index": index}, timeout=timeout
            )
            fetch_s = max(0.0, telemetry.monotonic_clock() - t0)
            raw = np.ascontiguousarray(
                deserialize_array(reply["data"]), dtype=np.float32
            ).tobytes()
            try:
                vec = verify_shard(manifest, index, raw)
            except ValueError as ve:
                if tele is not None:
                    tele.counter("ckpt.verify_failures").inc()
                    tele.event(
                        "ckpt.shard_verify_failure", shard=index, provider=ep,
                        attempt=attempt + 1,
                    )
                # counted as a VERIFY failure above; flag it so the outer
                # handler does not double-count it as a transport failure
                ve._ckpt_verify_counted = True
                raise
            if store is not None:
                # persist as we go: a restore killed mid-flight resumes
                # from here instead of refetching everything
                store.put_shard(manifest.shard_digests[index], raw)
            if tele is not None:
                tele.counter("ckpt.shards_fetched").inc()
                tele.counter("ckpt.shard_bytes_fetched").inc(len(raw))
                # per-provider goodput: what restore provider selection will
                # later prefer fast providers by — and the same observation
                # feeds the per-link estimator (telemetry/links.py), so a
                # provider that is ALSO an averaging partner shares one
                # link record across both subsystems
                tele.histogram("ckpt.provider_goodput").observe(
                    len(raw) / max(fetch_s, 1e-6)
                )
                tele.links().observe_transfer(ep, len(raw), fetch_s)
            if provider_bytes is not None:
                key = endpoint_key(ep)
                provider_bytes[key] = provider_bytes.get(key, 0) + len(raw)
            return vec
        except Exception as e:  # noqa: BLE001 — retry ladder
            failed_providers.add(ep)
            last = e
            # verify failures were counted at the verification site;
            # ckpt.fetch_failures is TRANSPORT failures only (the
            # docs/observability.md contract keeps the two disjoint)
            if tele is not None and not getattr(
                e, "_ckpt_verify_counted", False
            ):
                tele.counter("ckpt.fetch_failures").inc()
                tele.event(
                    "ckpt.shard_fetch_failed", shard=index, provider=ep,
                    attempt=attempt + 1, error=type(e).__name__,
                )
            logger.debug(
                f"shard {index} from {ep} failed "
                f"(attempt {attempt + 1}/{retries + 1}): {e!r}"
            )
    raise RestoreFailed(
        f"shard {index} exhausted {retries + 1} attempts: {last!r}"
    )


async def fetch_shards(
    client,
    manifest: CheckpointManifest,
    providers: Sequence[Provider],
    *,
    parallelism: int = 4,
    retries: int = 2,
    backoff: float = 0.5,
    timeout: float = 30.0,
    store: Optional[ShardStore] = None,
    telemetry_registry=None,
    provider_bytes: Optional[Dict[str, int]] = None,
) -> Dict[int, np.ndarray]:
    """Fetch (and verify) every shard of ``manifest``, resuming from
    ``store`` when given. Raises RestoreFailed if any shard cannot be
    obtained. ``provider_bytes`` (when given) accumulates verified bytes
    per provider endpoint — the restore span's attribution."""
    tele = telemetry.resolve(telemetry_registry)
    shards: Dict[int, np.ndarray] = {}
    needed: List[int] = []
    for i, digest in enumerate(manifest.shard_digests):
        raw = store.get_shard(digest) if store is not None else None
        if raw is not None and len(raw) == manifest.shard_nbytes(i):
            shards[i] = np.frombuffer(raw, dtype=np.float32)
        else:
            needed.append(i)
    if tele is not None and shards:
        # counted even when nothing is left to fetch — a fully-cached
        # restore is the best-case resume, not zero resumed shards
        tele.counter("ckpt.shards_resumed").inc(len(shards))
    sem = asyncio.Semaphore(max(1, parallelism))
    failed_providers: set = set()

    async def one(i: int) -> Tuple[int, np.ndarray]:
        async with sem:
            return i, await _fetch_one_shard(
                client, manifest, i, providers,
                retries=retries, backoff=backoff, timeout=timeout,
                store=store, failed_providers=failed_providers, tele=tele,
                provider_bytes=provider_bytes,
            )

    for i, vec in await asyncio.gather(*(one(i) for i in needed)):
        shards[i] = vec
    return shards


async def sharded_restore(
    client,
    announcements: List[CheckpointAnnouncement],
    *,
    parallelism: int = 4,
    retries: int = 2,
    backoff: float = 0.5,
    timeout: float = 30.0,
    store: Optional[ShardStore] = None,
    max_providers: int = 0,
    telemetry_registry=None,
    stats: Optional[Dict[str, Any]] = None,
) -> Tuple[Dict[str, Any], Dict[str, np.ndarray], CheckpointManifest]:
    """The full restore pipeline: pick the deepest announced (step, digest),
    pull + verify the manifest, fan the shard fetches out across providers,
    assemble. Returns (metadata, tree, manifest); raises RestoreFailed when
    the swarm cannot serve a complete checkpoint (callers fall back to the
    single-provider full-blob path). When ``stats`` is given, the providers
    ACTUALLY used (selected step/digest, after the max_providers cap) are
    recorded there — len(announcements) includes stale/outvoted peers."""
    target = select_target(announcements)
    if target is None:
        raise RestoreFailed("no checkpoint catalog announcements")
    step, digest, anns = target
    if max_providers > 0:
        anns = anns[:max_providers]
    providers: List[Provider] = [
        (tuple(a.endpoint), a.held_indices()) for a in anns
    ]
    if stats is not None:
        stats["providers"] = len(providers)
    manifest = await fetch_manifest(
        client, [ep for ep, _held in providers], digest, timeout=timeout
    )
    provider_bytes: Dict[str, int] = {}
    shards = await fetch_shards(
        client, manifest, providers,
        parallelism=parallelism, retries=retries, backoff=backoff,
        timeout=timeout, store=store, telemetry_registry=telemetry_registry,
        provider_bytes=provider_bytes,
    )
    if stats is not None and provider_bytes:
        # verified bytes actually pulled per provider — the restore span's
        # per-provider attribution (fast/slow providers become visible)
        stats["provider_bytes"] = provider_bytes
    tree = assemble_tree(manifest, shards)
    if store is not None:
        # the resume cache has now served its purpose for this manifest:
        # record the manifest so rotation can key off it, then drop shards
        # only older manifests reference — without this, every restart at a
        # new step grows the cache by a full state's worth of shards forever
        store.put_manifest(manifest)
        store.gc(keep=2)
    logger.info(
        f"sharded restore complete: step {manifest.step}, "
        f"{manifest.num_shards} shards ({manifest.total_bytes / 2**20:.1f} "
        f"MiB) from {len(providers)} provider(s)"
    )
    return manifest.metadata, tree, manifest
