"""Local content-addressed shard store: durable sharded checkpoints and
resumable restore downloads.

Layout under one root directory::

    <root>/manifest-<step>.bin     serialized CheckpointManifest
    <root>/shards/<sha256hex>.bin  raw fp32 shard bytes, content-addressed

Shards are keyed by their own digest, so a shard unchanged between steps is
stored ONCE and shared by every manifest that references it (embedding rows
that stopped moving, frozen heads, optimizer moments at rest) — rotation
keeps the newest ``keep`` manifests and garbage-collects shards nothing
references. Writes are atomic (tmp + rename) and reads re-verify the digest,
so a torn write or bit-rot surfaces as a missing shard, never as silently
adopted garbage.

Two consumers:

- the coordinator writes a sharded checkpoint per pulled state (the durable
  manifest trail next to the legacy ``checkpoint-<step>/state.bin``);
- a restoring peer points the fetcher at a store so partially-downloaded
  restores RESUME: shards fetched before a crash are verified from disk and
  only the missing ones are pulled again.
"""
from __future__ import annotations

import hashlib
import os
import re
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dedloc_tpu.checkpointing.manifest import (
    DEFAULT_SHARD_SIZE,
    CheckpointManifest,
    assemble_tree,
    build_manifest,
    shard_bytes,
)
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_MANIFEST_RE = re.compile(r"^manifest-(\d+)\.bin$")

# a *.tmp file older than this is an orphan from a write killed between
# mkstemp and os.replace (a live put finishes in seconds); same crashed-write
# junk class — and the same age guard — as utils.checkpoint's .ckpt-tmp-* sweep
ORPHAN_TMP_MAX_AGE_S = 3600.0


def _sweep_orphan_tmpfiles(
    directory: str, max_age_s: float = ORPHAN_TMP_MAX_AGE_S
) -> None:
    if not os.path.isdir(directory):
        return
    # dedlint: disable=clock-wall — compared against st_mtime (wall by
    # definition); virtual time would mis-age real files
    now = time.time()  # dedlint: disable=clock-wall
    for name in os.listdir(directory):
        if not name.endswith(".tmp"):
            continue
        path = os.path.join(directory, name)
        try:
            if now - os.path.getmtime(path) >= max_age_s:
                os.unlink(path)
        except OSError:
            continue  # raced with a completing put's os.replace


class ShardStore:
    """Content-addressed shard + manifest storage under one directory."""

    def __init__(self, root: str):
        self.root = root
        self.shard_dir = os.path.join(root, "shards")

    # -------------------------------------------------------------- shards

    def _shard_path(self, digest: bytes) -> str:
        return os.path.join(self.shard_dir, digest.hex() + ".bin")

    def has_shard(self, digest: bytes) -> bool:
        return os.path.isfile(self._shard_path(digest))

    def put_shard(self, digest: bytes, raw: bytes) -> str:
        """Atomically persist a shard (no-op if already present — content
        addressing makes re-puts free)."""
        path = self._shard_path(digest)
        if os.path.isfile(path):
            return path
        os.makedirs(self.shard_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.shard_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(raw)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    def get_shard(self, digest: bytes) -> Optional[bytes]:
        """Read a shard back, RE-VERIFYING its digest: a corrupt cached
        shard (torn write, bit-rot) is deleted and reported missing, so it
        gets re-fetched instead of poisoning a resumed restore."""
        path = self._shard_path(digest)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        if hashlib.sha256(raw).digest() != digest:
            logger.warning(f"dropping corrupt cached shard {digest.hex()[:12]}")
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        return raw

    def missing_shards(self, manifest: CheckpointManifest) -> List[int]:
        return [
            i
            for i, digest in enumerate(manifest.shard_digests)
            if not self.has_shard(digest)
        ]

    # ----------------------------------------------------------- manifests

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.root, f"manifest-{step}.bin")

    def put_manifest(self, manifest: CheckpointManifest) -> str:
        os.makedirs(self.root, exist_ok=True)
        path = self._manifest_path(manifest.step)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(manifest.to_bytes())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    def manifest_steps(self) -> List[int]:
        """All stored manifest steps, oldest -> newest."""
        if not os.path.isdir(self.root):
            return []
        steps = []
        for name in os.listdir(self.root):
            m = _MANIFEST_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def load_manifest(self, step: int) -> Optional[CheckpointManifest]:
        try:
            with open(self._manifest_path(step), "rb") as f:
                return CheckpointManifest.from_bytes(f.read())
        except (OSError, ValueError, KeyError):
            return None

    def latest_manifest(self) -> Optional[CheckpointManifest]:
        """Newest manifest whose file parses; a truncated newest manifest
        falls back to the next one, mirroring load_latest_checkpoint."""
        for step in reversed(self.manifest_steps()):
            manifest = self.load_manifest(step)
            if manifest is not None:
                return manifest
            logger.warning(
                f"sharded manifest-{step}.bin is corrupt; trying next-newest"
            )
        return None

    # ------------------------------------------------------------ rotation

    def gc(self, keep: Optional[int] = 2) -> None:
        """Keep the newest ``keep`` manifests (None = all), delete every
        shard no kept manifest references, and sweep *.tmp orphans left by
        writes killed mid-put (age-guarded so in-flight puts survive)."""
        _sweep_orphan_tmpfiles(self.root)
        _sweep_orphan_tmpfiles(self.shard_dir)
        steps = self.manifest_steps()
        if keep is not None:
            for step in steps[:-keep] if keep else steps:
                try:
                    os.unlink(self._manifest_path(step))
                except OSError:
                    pass
            steps = steps[-keep:] if keep else []
        referenced = set()
        for step in steps:
            manifest = self.load_manifest(step)
            if manifest is not None:
                referenced.update(d.hex() for d in manifest.shard_digests)
        if not os.path.isdir(self.shard_dir):
            return
        for name in os.listdir(self.shard_dir):
            if not name.endswith(".bin"):
                continue
            if name[: -len(".bin")] not in referenced:
                try:
                    os.unlink(os.path.join(self.shard_dir, name))
                except OSError:
                    pass


def save_sharded_checkpoint(
    root: str,
    tree: Dict[str, np.ndarray],
    step: int,
    shard_size: int = DEFAULT_SHARD_SIZE,
    metadata: Optional[Dict[str, Any]] = None,
    keep: Optional[int] = 2,
) -> CheckpointManifest:
    """Write ``tree`` as a manifest + content-addressed shards under
    ``root`` and rotate old manifests. Shards shared with prior steps are
    deduplicated by construction."""
    store = ShardStore(root)
    manifest, flat = build_manifest(
        tree, step, shard_size=shard_size, metadata=metadata
    )
    for i, digest in enumerate(manifest.shard_digests):
        store.put_shard(digest, shard_bytes(flat, manifest, i))
    store.put_manifest(manifest)
    store.gc(keep=keep)
    return manifest


def load_sharded_checkpoint(
    root: str, step: Optional[int] = None
) -> Optional[Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]]:
    """(step, tree, metadata) from the local store (newest manifest when
    ``step`` is None); None when absent or incomplete/corrupt."""
    store = ShardStore(root)
    manifest = (
        store.load_manifest(step) if step is not None else store.latest_manifest()
    )
    if manifest is None:
        return None
    shards: Dict[int, np.ndarray] = {}
    for i, digest in enumerate(manifest.shard_digests):
        raw = store.get_shard(digest)
        if raw is None or len(raw) != manifest.shard_nbytes(i):
            logger.warning(
                f"sharded checkpoint at step {manifest.step} is missing "
                f"shard {i}; cannot load locally"
            )
            return None
        shards[i] = np.frombuffer(raw, dtype=np.float32)
    return manifest.step, assemble_tree(manifest, shards), manifest.metadata
