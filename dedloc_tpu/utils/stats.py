"""Tiny shared statistics helpers.

One definition of the nearest-rank percentile, used by the simulator's
sizing reports AND the digital-twin fitter: the twin's fidelity numbers
are only like-for-like because both sides compute the identical
statistic, so there must be exactly one implementation to drift.
"""
from __future__ import annotations

from typing import List


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation surprises
    across numpy versions); 0.0 on empty input."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return ordered[rank]


def median(values: List[float], default: float = 0.0) -> float:
    """Upper median (nearest-rank at q=0.5), ``default`` on empty input."""
    if not values:
        return default
    ordered = sorted(values)
    return ordered[len(ordered) // 2]
