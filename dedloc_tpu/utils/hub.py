"""Checkpoint hub publication: git-backed and directory-mirror uploaders.

Capability parity with the reference coordinator's hub upload
(albert/run_first_peer.py:123-147): every ``upload_interval`` the coordinator
pulls the collaboration state, writes a local checkpoint, and publishes it —
there via ``save_pretrained`` + ``torch.save`` + ``git add/commit/push`` to
the HF hub, here via a pluggable ``upload_fn(checkpoint_path, step)`` built
by one of these factories. The git uploader works against ANY git remote
(a local bare repo in tests, an HTTPS hub remote in production); the
directory mirror is the zero-dependency fallback.

Git identity is passed per-invocation (``git -c user.name=...``) so the
uploader never touches the user's or repository's git config.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import Callable, Optional

from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

UploadFn = Callable[[str, int], None]

_GIT_ID = [
    "-c", "user.name=dedloc-coordinator",
    "-c", "user.email=coordinator@dedloc.invalid",
]


def _git(repo: str, *argv: str, timeout: float = 300.0) -> str:
    # a hung remote (stalled network during push/fetch) must not wedge the
    # caller forever — the coordinator's loop depends on this bound
    out = subprocess.run(
        ["git", *_GIT_ID, "-C", repo, *argv],
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if out.returncode != 0:
        # surface git's actual stderr — CalledProcessError alone hides it
        raise RuntimeError(
            f"git {' '.join(argv)} failed ({out.returncode}): "
            f"{out.stderr.strip() or out.stdout.strip()}"
        )
    return out.stdout.strip()


def _mirror_checkpoint(checkpoint_path: str, dest: str) -> None:
    """Copy a checkpoint dir's files into ``dest`` (latest-wins layout, like
    the reference overwriting model files in its hub working tree)."""
    os.makedirs(dest, exist_ok=True)
    for name in os.listdir(checkpoint_path):
        src = os.path.join(checkpoint_path, name)
        dst = os.path.join(dest, name)
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            shutil.copy2(src, dst)


def git_hub_uploader(
    work_dir: str,
    remote_url: Optional[str] = None,
    branch: str = "main",
) -> UploadFn:
    """``upload_fn`` that commits each checkpoint into a git working tree at
    ``work_dir`` and (when ``remote_url`` is set) pushes it.

    The working tree holds the LATEST checkpoint's files at its root plus a
    ``step.txt`` marker; history preserves every published step as a commit —
    the same shape as the reference's hub repository.
    """

    def upload(checkpoint_path: str, step: int) -> None:
        os.makedirs(work_dir, exist_ok=True)
        if not os.path.isdir(os.path.join(work_dir, ".git")):
            _git(work_dir, "init", "--initial-branch", branch)
            if remote_url:
                _git(work_dir, "remote", "add", "origin", remote_url)
                # a fresh work_dir against a hub with history (coordinator
                # restart) must build on the remote tip, or every push is
                # rejected as non-fast-forward forever
                try:
                    _git(work_dir, "fetch", "origin", branch)
                    _git(work_dir, "checkout", "-B", branch, "FETCH_HEAD")
                except RuntimeError:
                    pass  # empty remote: first-ever publish
        _mirror_checkpoint(checkpoint_path, work_dir)
        with open(os.path.join(work_dir, "step.txt"), "w") as f:
            f.write(str(step))
        _git(work_dir, "add", "-A")
        status = _git(work_dir, "status", "--porcelain")
        if not status:
            logger.info(f"hub: step {step} identical to HEAD; nothing to push")
            return
        _git(work_dir, "commit", "-m", f"checkpoint at collaboration step {step}")
        if remote_url:
            _git(work_dir, "push", "origin", branch)
        logger.info(f"hub: published checkpoint step {step}")

    return upload


def directory_mirror_uploader(dest_root: str) -> UploadFn:
    """``upload_fn`` that mirrors each checkpoint to
    ``dest_root/checkpoint-<step>`` plus a ``latest`` marker file — the
    zero-dependency hub for air-gapped deployments."""

    def upload(checkpoint_path: str, step: int) -> None:
        dest = os.path.join(dest_root, f"checkpoint-{step}")
        _mirror_checkpoint(checkpoint_path, dest)
        tmp = os.path.join(dest_root, ".latest.tmp")
        with open(tmp, "w") as f:
            f.write(str(step))
        os.replace(tmp, os.path.join(dest_root, "latest"))
        logger.info(f"hub mirror: published checkpoint step {step} -> {dest}")

    return upload


def build_upload_fn(
    hub_git_dir: str = "",
    hub_git_remote: str = "",
    hub_mirror_dir: str = "",
) -> Optional[UploadFn]:
    """Resolve coordinator CLI flags into an upload_fn (None = seam unused)."""
    if hub_git_dir:
        return git_hub_uploader(hub_git_dir, hub_git_remote or None)
    if hub_mirror_dir:
        return directory_mirror_uploader(hub_mirror_dir)
    return None
