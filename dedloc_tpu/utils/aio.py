"""Small asyncio utilities shared across the DHT/averaging/simulator stack.

``keep_task`` is the approved answer to dedlint's ``async-orphan-task``
rule: a bare ``asyncio.ensure_future(coro())`` keeps no strong reference —
the loop only holds a weak one, so the task can be garbage-collected
mid-flight, and an exception inside it is silently parked until interpreter
shutdown prints "Task exception was never retrieved" (the PR 7
catalog-announce flake class). Background work that is deliberately not
awaited must still be retained and must still surface its failures.
"""
from __future__ import annotations

import asyncio
from typing import Coroutine, Optional, Set

from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# strong references to in-flight background tasks; each task discards
# itself on completion, so the set stays bounded by actual concurrency.
# Tasks whose loop was closed/abandoned mid-flight never run their done-
# callback, so a periodic sweep (below) prunes them — without it, per-test
# loop churn would grow the set monotonically for the process lifetime.
# The cadence is deliberately coarse: each sweep is O(live tasks), and a
# large simulation legitimately keeps tens of thousands of parked acceptor
# tasks alive — sweeping those every 512 spawns was pure overhead. Memory
# growth between sweeps stays bounded by _SWEEP_EVERY dead-loop tasks.
_background: Set["asyncio.Future"] = set()
_SWEEP_EVERY = 8192
_spawn_count = 0


def _sweep_dead_loops() -> None:
    for t in list(_background):
        try:
            if t.get_loop().is_closed():
                _background.discard(t)
        except RuntimeError:  # detached future
            _background.discard(t)


def keep_task(
    coro_or_future, name: str = "", log: Optional[object] = None
) -> "asyncio.Future":
    """Schedule background work WITH a retained handle and a done-callback
    that logs any exception (CancelledError excluded — cancellation is how
    owners shut background work down, not a failure).

    Returns the task so callers that also want the handle (e.g. to cancel
    on close) can keep it; retention here does not depend on them doing so.
    """
    global _spawn_count
    _spawn_count += 1
    if _spawn_count % _SWEEP_EVERY == 0:
        _sweep_dead_loops()
    task = asyncio.ensure_future(coro_or_future)
    _background.add(task)
    task_log = log if log is not None else logger
    label = name or getattr(coro_or_future, "__qualname__", "") or "task"

    def _done(t: "asyncio.Future") -> None:
        _background.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            # exc_info keeps the traceback the default "Task exception was
            # never retrieved" handler would have printed
            task_log.warning(
                f"background {label} failed: {exc!r}", exc_info=exc
            )

    task.add_done_callback(_done)
    return task
