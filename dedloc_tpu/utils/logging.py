"""Logging helpers (reference: hivemind.utils.logging.get_logger usage and
rank-0-only verbosity, albert/run_trainer.py:36-53)."""
from __future__ import annotations

import logging
import os
import sys

_FORMAT = "[%(asctime)s.%(msecs)03d][%(levelname)s][%(name)s] %(message)s"
_configured = False


def get_logger(name: str = "dedloc_tpu") -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("DEDLOC_LOGLEVEL", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%Y-%m-%d %H:%M:%S"))
        root = logging.getLogger("dedloc_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    if not name.startswith("dedloc_tpu"):
        # role CLIs run as ``python -m`` get __name__ == "__main__"; fold
        # them under the package root so they share its handler/level
        name = f"dedloc_tpu.{name}"
    return logging.getLogger(name)
