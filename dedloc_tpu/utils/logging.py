"""Logging helpers (reference: hivemind.utils.logging.get_logger usage and
rank-0-only verbosity, albert/run_trainer.py:36-53)."""
from __future__ import annotations

import logging
import os
import sys
import threading

_FORMAT = "[%(asctime)s.%(msecs)03d][%(levelname)s][%(name)s] %(message)s"
_configured = False
# configuration can race: the trainer thread, the DHT loop thread and a
# background backup thread all call get_logger on first use
_configure_lock = threading.Lock()


def _resolve_level(raw: str):
    """``DEDLOC_LOGLEVEL`` value -> logging level int, or None if invalid
    (numeric strings like "15" are accepted; ``setLevel`` would raise on an
    unknown NAME, so validation happens here with an INFO fallback instead
    of crashing the first logger call of the process)."""
    try:
        return int(raw)
    except ValueError:
        pass
    level = logging.getLevelName(raw)
    return level if isinstance(level, int) else None


def get_logger(name: str = "dedloc_tpu") -> logging.Logger:
    global _configured
    if not _configured:
        with _configure_lock:
            if not _configured:  # double-checked: one handler, ever
                raw = os.environ.get("DEDLOC_LOGLEVEL", "INFO").upper()
                level = _resolve_level(raw)
                handler = logging.StreamHandler(sys.stderr)
                handler.setFormatter(
                    logging.Formatter(_FORMAT, datefmt="%Y-%m-%d %H:%M:%S")
                )
                root = logging.getLogger("dedloc_tpu")
                root.addHandler(handler)
                root.setLevel(level if level is not None else logging.INFO)
                root.propagate = False
                _configured = True
                if level is None:
                    root.warning(
                        f"invalid DEDLOC_LOGLEVEL {raw!r}; falling back to "
                        "INFO"
                    )
    if not name.startswith("dedloc_tpu"):
        # role CLIs run as ``python -m`` get __name__ == "__main__"; fold
        # them under the package root so they share its handler/level
        name = f"dedloc_tpu.{name}"
    return logging.getLogger(name)
