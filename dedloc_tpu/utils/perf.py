"""Step-phase performance timers + profiler gate.

Capability of vissl's PerfTimer/PerfMetric/PerfStats (reference:
swav/vissl/vissl/utils/perf_stats.py:12-249) — context-manager timers wrapped
around every phase of the train step (read_sample / forward / loss_compute /
backward / optimizer_step, standard_train_step.py:110-226), aggregated and
reported periodically by a hook.

TPU-native differences from the reference:
- the reference offers optional CUDA-event timing (:170-215); on TPU the
  equivalent is blocking on the step outputs (`jax.block_until_ready`) before
  stopping the timer, which ``PerfTimer(..., block_on=...)`` does. XLA runs
  async — without blocking, a timer around a jitted call measures dispatch,
  not execution.
- whole-program tracing goes through ``jax.profiler`` (xplane traces viewable
  in tensorboard/xprof) behind one config flag — the §5 "tracing behind one
  flag" requirement — instead of per-op CUDA events.

Unified with the swarm-telemetry clock (docs/observability.md): PerfStats
times on ``telemetry.registry.monotonic_clock`` — real monotonic time in
production, FakeClock-offset-aware in fault scenarios — and, whenever a
telemetry registry is active (process-global or injected via
``telemetry=``), every block timing is ALSO observed into that registry's
``perf.<name>`` histogram. One clock source, one sink: the timings ride the
metrics-bus snapshot and the per-peer event trace instead of living only in
this object's private store (kept for the human ``report_str`` view and the
roles' recent-mean publishing).
"""
from __future__ import annotations

import math
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, Iterator, Optional

from dedloc_tpu.telemetry import registry as _telemetry


class PerfMetric:
    """Online stats for one named phase: count/mean/min/max + recent window.

    Mirrors vissl PerfMetric (perf_stats.py:19-78): exact mean over all
    updates plus a smoothed recent-window mean for dashboards.
    """

    WINDOW = 32

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0
        self._recent: Deque[float] = deque(maxlen=self.WINDOW)

    def update(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.min = min(self.min, seconds)
        self.max = max(self.max, seconds)
        self._recent.append(seconds)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    @property
    def recent_mean(self) -> float:
        return sum(self._recent) / len(self._recent) if self._recent else 0.0

    def summary(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "total_s": self.total,
            "mean_ms": self.mean * 1e3,
            "recent_ms": self.recent_mean * 1e3,
            "min_ms": (0.0 if self.count == 0 else self.min * 1e3),
            "max_ms": self.max * 1e3,
        }


class PerfStats:
    """Named collection of PerfMetrics with a human-readable report.

    Usage::

        stats = PerfStats()
        with stats.timer("forward", block_on=loss):
            loss = step(...)
    """

    def __init__(self, enabled: bool = True, telemetry=None) -> None:
        self.enabled = enabled
        self.metrics: Dict[str, PerfMetric] = {}
        # component-scoped telemetry registry; None resolves the process
        # global at each timing (so a registry installed AFTER this object
        # was built — the usual role startup order — still receives them)
        self._telemetry = telemetry

    def metric(self, name: str) -> PerfMetric:
        if name not in self.metrics:
            self.metrics[name] = PerfMetric()
        return self.metrics[name]

    @contextmanager
    def timer(self, name: str, block_on: Any = None) -> Iterator[None]:
        """Time a block. ``block_on``: pytree of jax arrays to block on before
        stopping the clock (the TPU analogue of CUDA-event timing)."""
        if not self.enabled:
            yield
            return
        start = _telemetry.monotonic_clock()
        try:
            yield
        finally:
            if block_on is not None:
                import jax

                jax.block_until_ready(block_on)
            # clamp at 0: a block straddling a FakeClock exit sees the
            # clock retreat by the whole fake offset
            dur = max(0.0, _telemetry.monotonic_clock() - start)
            self.metric(name).update(dur)
            tele = _telemetry.resolve(self._telemetry)
            if tele is not None:
                # the unified sink: the same timing rides the registry
                # (snapshot key ``perf.<name>.mean`` etc.)
                tele.histogram(f"perf.{name}").observe(dur)

    def report(self) -> Dict[str, Dict[str, float]]:
        return {name: m.summary() for name, m in sorted(self.metrics.items())}

    def report_str(self) -> str:
        lines = ["phase                      count   mean_ms  recent_ms    max_ms"]
        for name, m in sorted(self.metrics.items()):
            s = m.summary()
            lines.append(
                f"{name:<24} {s['count']:>7d} {s['mean_ms']:>9.2f}"
                f" {s['recent_ms']:>10.2f} {s['max_ms']:>9.2f}"
            )
        return "\n".join(lines)

    def reset(self) -> None:
        self.metrics.clear()


@contextmanager
def profiler_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Gate a ``jax.profiler`` trace behind one flag (§5 tracing requirement).

    ``log_dir`` falsy → no-op. Otherwise emits an xplane trace for the wrapped
    region (replaces vissl's MONITOR_PERF_STATS + CUDA-event plumbing,
    defaults.yaml:81-83, with the XLA-native profiler).
    """
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
