"""THE hardened JSONL loader (one implementation, many consumers).

Real fleet logs carry exactly two corruptions worth surviving:

- a **truncated final line** (the peer was killed mid-write — the very
  churn the observability tools exist to debug): the fragment is skipped;
- **interleaved writers** (two processes appending one file can jam two
  objects onto one line, or splice one object into another): each line is
  decoded object-by-object with ``raw_decode``, salvaging every complete
  object and counting only the garbage between them.

Consumers: ``tools/runlog_summary.py`` (every telemetry view),
``tools/swarm_watch.py`` (one-shot and the --follow tail, via
``iter_line_objects``), the twin fitter's inputs, and the coordinator's
self-retune read-back of its own metrics JSONL. Keeping one copy is the
point — tolerance rules must not drift between the live and post-hoc
paths.
"""
from __future__ import annotations

import json
import sys
from typing import Callable, List, Optional, Tuple

_DECODER = json.JSONDecoder()


def iter_line_objects(line: str) -> Tuple[List[dict], int]:
    """(complete dict objects on ``line``, dropped fragment count)."""
    rows: List[dict] = []
    dropped = 0
    line = line.strip()
    while line:
        start = line.find("{")
        if start < 0:
            dropped += 1  # no object on what remains
            break
        if start > 0:
            dropped += 1  # leading garbage before the object
        try:
            obj, end = _DECODER.raw_decode(line, start)
        except json.JSONDecodeError:
            dropped += 1  # truncated/spliced fragment
            break
        if isinstance(obj, dict):
            rows.append(obj)
        line = line[end:].strip()
    return rows, dropped


def load_jsonl_rows(
    paths,
    warn: Optional[Callable[[str], None]] = None,
    missing_ok: bool = False,
) -> List[dict]:
    """All decoded dict rows from ``paths`` in file order; callers filter.
    ``warn`` receives one summary message when fragments were dropped
    (default: stderr, the CLI behavior); ``missing_ok`` skips absent files
    (the coordinator reading back a log that has not been created yet)."""
    rows: List[dict] = []
    dropped = 0
    for path in paths:
        try:
            f = open(path, encoding="utf-8", errors="replace")
        except OSError:
            if missing_ok:
                continue
            raise
        with f:
            for line in f:
                got, bad = iter_line_objects(line)
                rows.extend(got)
                dropped += bad
    if dropped:
        message = f"warning: skipped {dropped} unparseable fragment(s)"
        if warn is not None:
            warn(message)
        else:
            print(message, file=sys.stderr)
    return rows
