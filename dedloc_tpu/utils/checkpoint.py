"""Local checkpoint save/resume with rotation.

Capability parity with the reference's local checkpoint mechanism
(albert/run_trainer.py:56-70 scans ``output_dir/checkpoint*`` for the latest
and resumes; albert/arguments.py:125-126 ``save_steps=500,
save_total_limit=2``). The peer-to-peer mechanism (``load_state_from_peers``)
lives in the averager; this module is the disk mirror used when a whole
collaboration restarts.

Format: one directory per step — ``checkpoint-<step>/`` containing
``state.bin`` (framework wire format, see core/serialization.py) and
``metadata.bin``. Writes go to a temp dir first and are renamed into place,
so a crash mid-save never corrupts the latest checkpoint.
"""
from __future__ import annotations

import os
import re
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dedloc_tpu.core.serialization import (
    CompressionType,
    deserialize_tree,
    pack_obj,
    serialize_tree,
    unpack_obj,
)
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CKPT_RE = re.compile(r"^checkpoint-(\d+)$")

# a .ckpt-tmp-* dir older than this is an orphan from a crashed save (a
# LIVE save finishes in seconds-to-minutes); swept on the next save so
# crashed saves stop accumulating junk in output_dir forever
ORPHAN_TMP_MAX_AGE_S = 3600.0


def sweep_orphan_tmpdirs(
    output_dir: str,
    max_age_s: float = ORPHAN_TMP_MAX_AGE_S,
    now: Optional[float] = None,
) -> List[str]:
    """Delete ``.ckpt-tmp-*`` dirs older than ``max_age_s`` (crashed-save
    leftovers). The age guard keeps a CONCURRENT in-flight save's tmp dir
    safe. Returns the swept paths."""
    if not os.path.isdir(output_dir):
        return []
    now = time.time() if now is None else now
    swept = []
    for name in os.listdir(output_dir):
        if not name.startswith(".ckpt-tmp-"):
            continue
        path = os.path.join(output_dir, name)
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            continue  # raced with the rename of a completing save
        if age >= max_age_s:
            logger.info(f"sweeping orphaned checkpoint tmp dir {path}")
            shutil.rmtree(path, ignore_errors=True)
            swept.append(path)
    return swept


def list_checkpoints(output_dir: str) -> List[Tuple[int, str]]:
    """All checkpoints under ``output_dir``, sorted oldest → newest by step."""
    if not os.path.isdir(output_dir):
        return []
    found = []
    for name in os.listdir(output_dir):
        m = _CKPT_RE.match(name)
        path = os.path.join(output_dir, name)
        if m and os.path.isfile(os.path.join(path, "state.bin")):
            found.append((int(m.group(1)), path))
    found.sort()
    return found


def latest_checkpoint(output_dir: str) -> Optional[Tuple[int, str]]:
    ckpts = list_checkpoints(output_dir)
    return ckpts[-1] if ckpts else None


def save_checkpoint(
    output_dir: str,
    step: int,
    tree: Dict[str, np.ndarray],
    metadata: Optional[Dict[str, Any]] = None,
    save_total_limit: Optional[int] = 2,
) -> str:
    """Atomically write ``checkpoint-<step>`` and rotate old ones."""
    os.makedirs(output_dir, exist_ok=True)
    sweep_orphan_tmpdirs(output_dir)
    final = os.path.join(output_dir, f"checkpoint-{step}")
    tmp = tempfile.mkdtemp(prefix=".ckpt-tmp-", dir=output_dir)
    try:
        with open(os.path.join(tmp, "state.bin"), "wb") as f:
            f.write(serialize_tree(tree, CompressionType.NONE))
        with open(os.path.join(tmp, "metadata.bin"), "wb") as f:
            f.write(pack_obj(metadata or {}))
        if os.path.isdir(final):  # re-saving the same step: replace
            shutil.rmtree(final)
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if save_total_limit is not None:
        for _step, path in list_checkpoints(output_dir)[:-save_total_limit]:
            logger.info(f"rotating out old checkpoint {path}")
            shutil.rmtree(path, ignore_errors=True)
    return final


def load_checkpoint(
    path: str,
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    with open(os.path.join(path, "state.bin"), "rb") as f:
        tree = deserialize_tree(f.read())
    meta_path = os.path.join(path, "metadata.bin")
    metadata: Dict[str, Any] = {}
    if os.path.isfile(meta_path):
        with open(meta_path, "rb") as f:
            metadata = unpack_obj(f.read())
    return tree, metadata


def load_latest_checkpoint(
    output_dir: str,
) -> Optional[Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]]:
    """(step, tree, metadata) of the newest LOADABLE checkpoint, or None.

    A corrupt or truncated ``state.bin`` (host died mid-write on a non-
    atomic filesystem, disk bit-rot) falls back to the next-newest
    checkpoint instead of crashing resume — losing ``save_steps`` worth of
    progress beats losing the run."""
    for step, path in reversed(list_checkpoints(output_dir)):
        try:
            tree, metadata = load_checkpoint(path)
            return step, tree, metadata
        except Exception as e:  # noqa: BLE001 — corrupt checkpoint
            logger.warning(
                f"checkpoint {path} is corrupt ({e!r}); trying next-newest"
            )
    return None
