from dedloc_tpu.models.albert import (
    AlbertConfig,
    AlbertForPreTraining,
    albert_pretraining_loss,
)
