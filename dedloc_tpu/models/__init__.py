from dedloc_tpu.models.albert import (
    AlbertConfig,
    AlbertForPreTraining,
    albert_pretraining_loss,
)
from dedloc_tpu.models.resnet import ResNet, ResNetConfig
from dedloc_tpu.models.swav import (
    SwAVConfig,
    SwAVModel,
    SwAVPrototypesHead,
    SwAVQueue,
    SwAVTrainState,
    make_swav_train_step,
    normalize_prototypes,
    sinkhorn_knopp,
    swav_loss,
)
