"""SwAV: prototypes head, distributed sinkhorn assignment, swapped-prediction
loss, embedding queue, and the prototype hooks.

Capability parity with the reference's SwAV stack:
- head: MLP 2048→2048→128 + L2 normalize + bias-free prototype layers
  (swav/vissl/vissl/models/heads/swav_prototypes_head.py:10-112)
- loss: swapped prediction over multicrop views with sinkhorn-knopp
  assignments, 3 iterations, epsilon 0.05, temperature 0.1, optional queue
  and hard assignment (swav/vissl/vissl/losses/swav_loss.py:117-381)
- hooks: queue-score refresh on forward + prototype L2 normalization on
  update (swav/vissl/vissl/hooks/swav_hooks.py:11-93), prototype freezing
  for the first iterations (state_update_hooks.py:235-280)

NOT a port — the distributed design is inverted for TPU: the reference calls
``all_reduce_sum`` inside the sinkhorn loop over NCCL (swav_loss.py:194-236);
here sinkhorn is plain jnp on the GLOBAL (sharded) batch inside jit, so under
pjit the row/column sums lower to ICI psums automatically and the whole loop
fuses into the step program. Queue state is an explicit pytree carried
through the step function (functional, donate-able) instead of module
buffers mutated in place.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct

from dedloc_tpu.models.resnet import ResNet, ResNetConfig


@dataclasses.dataclass(frozen=True)
class SwAVConfig:
    """swav_1node_resnet_submit.yaml defaults (:33-37,68,93-104)."""

    trunk: ResNetConfig = ResNetConfig.resnet50()
    proj_dims: Sequence[int] = (2048, 2048, 128)
    num_prototypes: Sequence[int] = (3000,)
    temperature: float = 0.1
    epsilon: float = 0.05
    sinkhorn_iters: int = 3
    num_crops: int = 8  # 2×224 + 6×96
    crops_for_assign: Sequence[int] = (0, 1)
    queue_length: int = 0  # per-peer feature queue (0 = disabled)
    queue_start_step: int = 0
    freeze_prototypes_steps: int = 313  # TEMP_FROZEN_PARAMS_ITER_MAP capability
    use_bn_in_head: bool = True

    @staticmethod
    def tiny(**overrides) -> "SwAVConfig":
        base = dict(
            trunk=ResNetConfig.tiny(),
            proj_dims=(256, 64, 16),
            num_prototypes=(32,),
            num_crops=4,
            freeze_prototypes_steps=0,
        )
        base.update(overrides)
        return SwAVConfig(**base)


class SwAVPrototypesHead(nn.Module):
    """Projection MLP (BN+ReLU between layers, skipped after the last) →
    L2 normalize → one bias-free Linear per prototype head."""

    cfg: SwAVConfig

    @nn.compact
    def __call__(self, features, train: bool = True):
        cfg = self.cfg
        x = features.astype(jnp.float32)
        dims = list(cfg.proj_dims)
        for i, dim in enumerate(dims[1:]):
            x = nn.Dense(dim, param_dtype=jnp.float32, name=f"proj{i}")(x)
            if i == len(dims) - 2:
                break  # skip_last_bn
            if cfg.use_bn_in_head:
                x = nn.BatchNorm(
                    use_running_average=not train,
                    momentum=0.9,
                    epsilon=1e-5,
                    dtype=jnp.float32,
                    name=f"proj_bn{i}",
                )(x)
            x = nn.relu(x)
        # L2 normalize the embeddings before clustering
        x = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
        scores = [
            nn.Dense(k, use_bias=False, param_dtype=jnp.float32, name=f"prototypes{i}")(
                x
            )
            for i, k in enumerate(cfg.num_prototypes)
        ]
        return x, scores


class SwAVModel(nn.Module):
    """Trunk + head over a multicrop batch.

    ``crops`` is a list of [N, H_i, W_i, C] arrays (one per crop resolution
    group, mirroring multi_res_input_forward at base_ssl_model.py:76 which
    batches same-resolution crops through the trunk together). Returns
    (embeddings [N*num_crops, D], scores list of [N*num_crops, K]).
    """

    cfg: SwAVConfig

    @nn.compact
    def __call__(self, crops: Sequence[jnp.ndarray], train: bool = True):
        trunk = ResNet(self.cfg.trunk, name="trunk")
        feats = jnp.concatenate([trunk(c, train) for c in crops], axis=0)
        return SwAVPrototypesHead(self.cfg, name="head")(feats, train)


# ----------------------------------------------------------------- sinkhorn


def sinkhorn_knopp(
    scores: jnp.ndarray,
    num_iters: int = 3,
    epsilon: float = 0.05,
    hard: bool = False,
) -> jnp.ndarray:
    """Sinkhorn-knopp assignment (swav_loss.py:177-244 semantics).

    ``scores``: [N, K] prototype scores for the assignment crop (the GLOBAL
    batch — under pjit the sums below reduce across devices over ICI; no
    manual collectives, unlike the reference's all_reduce_sum-in-loop).
    Returns [N, K] assignment probabilities (rows sum to 1).
    """
    scores = scores.astype(jnp.float32)
    n, k = scores.shape
    # log-sum-exp stabilization (swav_loss.py:266-271): subtract the global max
    q = jnp.exp(scores / epsilon - jnp.max(scores / epsilon))
    q = q.T  # [K, N] — following the paper's Q convention
    q = q / jnp.maximum(q.sum(), 1e-12)

    def body(_, q):
        # rows (prototypes) to uniform 1/K
        u = jnp.maximum(q.sum(axis=1, keepdims=True), 1e-12)
        q = q / (k * u)
        # columns (samples) to uniform 1/N
        v = jnp.maximum(q.sum(axis=0, keepdims=True), 1e-12)
        q = q / (n * v)
        return q

    q = jax.lax.fori_loop(0, num_iters, body, q)
    q = q / jnp.maximum(q.sum(axis=0, keepdims=True), 1e-12)  # final col norm
    assignments = q.T  # [N, K], rows sum to 1
    if hard:
        idx = jnp.argmax(assignments, axis=1)
        assignments = jax.nn.one_hot(idx, k, dtype=jnp.float32)
    return jax.lax.stop_gradient(assignments)


# --------------------------------------------------------------------- loss


def swav_loss(
    scores: Sequence[jnp.ndarray],
    cfg: SwAVConfig,
    queue_scores: Optional[jnp.ndarray] = None,
    use_queue: bool = False,
    hard_assignment: bool = False,
) -> jnp.ndarray:
    """Swapped-prediction loss over all prototype heads
    (swav_loss.py:246-326 semantics).

    ``scores[h]``: [num_crops*B, K_h], crops stacked along axis 0 in crop
    order. ``queue_scores``: [num_heads, len(crops_for_assign), Q, K] scores
    of queued embeddings (refreshed against CURRENT prototypes by the caller
    — the SwAVUpdateQueueScoresHook capability). Queued samples only sharpen
    the assignment statistics; losses are computed on the live batch.
    """
    total = 0.0
    for h, s in enumerate(scores):
        bs = s.shape[0] // cfg.num_crops
        head_loss = 0.0
        for i, crop_id in enumerate(cfg.crops_for_assign):
            crop_scores = jax.lax.dynamic_slice_in_dim(s, bs * crop_id, bs, 0)
            if use_queue and queue_scores is not None:
                assign_in = jnp.concatenate(
                    [crop_scores, queue_scores[h, i]], axis=0
                )
            else:
                assign_in = crop_scores
            assignments = sinkhorn_knopp(
                assign_in, cfg.sinkhorn_iters, cfg.epsilon, hard=hard_assignment
            )[:bs]
            pred_crops = [p for p in range(cfg.num_crops) if p != crop_id]
            crop_loss = 0.0
            for p in pred_crops:
                logp = jax.nn.log_softmax(
                    jax.lax.dynamic_slice_in_dim(s, bs * p, bs, 0)
                    / cfg.temperature,
                    axis=1,
                )
                crop_loss -= jnp.mean(jnp.sum(assignments * logp, axis=1))
            head_loss += crop_loss / len(pred_crops)
        total += head_loss / len(cfg.crops_for_assign)
    return total / len(scores)


# -------------------------------------------------------------------- queue


class SwAVQueue(struct.PyTreeNode):
    """Embedding queue per assignment crop (swav_loss.py:328-366), as an
    explicit functional pytree: newest embeddings at the front."""

    embeddings: jnp.ndarray  # [len(crops_for_assign), Q, D]

    @classmethod
    def create(cls, cfg: SwAVConfig, rng: jax.Array) -> "SwAVQueue":
        d = cfg.proj_dims[-1]
        stdv = 1.0 / jnp.sqrt(jnp.asarray(d / 3.0))
        emb = jax.random.uniform(
            rng,
            (len(cfg.crops_for_assign), cfg.queue_length, d),
            jnp.float32,
            -stdv,
            stdv,
        )
        return cls(embeddings=emb)

    def update(self, embeddings: jnp.ndarray, cfg: SwAVConfig) -> "SwAVQueue":
        """Shift-in this step's assignment-crop embeddings
        (update_emb_queue semantics: queue[bs:] = queue[:-bs];
        queue[:bs] = new)."""
        bs = embeddings.shape[0] // cfg.num_crops
        new_queues = []
        for i, crop_id in enumerate(cfg.crops_for_assign):
            fresh = jax.lax.dynamic_slice_in_dim(
                embeddings, bs * crop_id, bs, 0
            )
            shifted = jnp.concatenate(
                [fresh, self.embeddings[i, : -bs or None]], axis=0
            )
            new_queues.append(shifted[: self.embeddings.shape[1]])
        return self.replace(embeddings=jnp.stack(new_queues))

    def scores(self, head_params, cfg: SwAVConfig) -> jnp.ndarray:
        """Refresh queue scores against CURRENT prototypes
        (SwAVUpdateQueueScoresHook.on_forward, swav_hooks.py:26-38).
        Returns [num_heads, len(crops_for_assign), Q, K]."""
        per_head = []
        for h in range(len(cfg.num_prototypes)):
            w = head_params[f"prototypes{h}"]["kernel"]  # [D, K]
            per_head.append(jnp.einsum("cqd,dk->cqk", self.embeddings, w))
        return jnp.stack(per_head)


# -------------------------------------------------------------------- hooks


def _is_prototype_path(path) -> bool:
    return any(
        str(getattr(p, "key", "")).startswith("prototypes") for p in path
    )


def normalize_prototypes(params):
    """L2-normalize prototype rows after each update
    (NormalizePrototypesHook.on_update, swav_hooks.py:55-92)."""

    def maybe_normalize(path, leaf):
        if _is_prototype_path(path) and str(getattr(path[-1], "key", "")) == "kernel":
            # [D, K]: each prototype is a column
            norm = jnp.maximum(jnp.linalg.norm(leaf, axis=0, keepdims=True), 1e-12)
            return leaf / norm
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_normalize, params)


def freeze_prototypes_grads(grads, step, freeze_steps: int):
    """Zero prototype gradients for the first ``freeze_steps`` global steps
    (FreezeParametersHook capability, state_update_hooks.py:235-280), as a
    jit-safe mask on the gradient pytree."""
    frozen = step < freeze_steps

    def maybe_freeze(path, leaf):
        if _is_prototype_path(path):
            return jnp.where(frozen, jnp.zeros_like(leaf), leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_freeze, grads)


# --------------------------------------------------------------- train step


class SwAVTrainState(struct.PyTreeNode):
    """Step counter keyed by the GLOBAL collaboration step (fed to the loss
    for queue gating, the fork seam at standard_train_step.py:153)."""

    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    queue: Optional[SwAVQueue] = None


def _swav_shardings(mesh):
    """(replicated, crops-sharded) NamedShardings for the step builders.
    Crops shard over the data axis; with the GLOBAL batch inside jit, the
    sinkhorn row/column sums lower to ICI psums automatically — the
    TPU-native inversion of the reference's all_reduce-in-loop."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P()), NamedSharding(mesh, P("data"))


def make_swav_train_step(model: SwAVModel, cfg: SwAVConfig, tx, mesh=None,
                         num_crop_groups: int = 2):
    """Fused jitted step: forward (BN stats mutable), swav loss (+queue),
    prototype freeze mask, optimizer update, prototype re-normalization,
    queue shift-in. ``use_queue`` is static (two compiled variants, like the
    reference's queue.start_iter gate at swav_loss.py:84-91). With ``mesh``,
    crops shard over the data axis and state replicates."""

    def train_step(state: SwAVTrainState, crops, use_queue: bool):
        queue_scores = (
            state.queue.scores(state.params["head"], cfg)
            if (use_queue and state.queue is not None)
            else None
        )

        def loss_fn(params):
            (emb, scores), mutated = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                crops,
                True,
                mutable=["batch_stats"],
            )
            loss = swav_loss(scores, cfg, queue_scores, use_queue=use_queue)
            return loss, (mutated["batch_stats"], emb)

        (loss, (new_bn, emb)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        grads = freeze_prototypes_grads(
            grads, state.step, cfg.freeze_prototypes_steps
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_params = normalize_prototypes(new_params)
        new_queue = (
            state.queue.update(emb, cfg) if state.queue is not None else None
        )
        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                batch_stats=new_bn,
                opt_state=new_opt,
                queue=new_queue,
            ),
            {"loss": loss},
        )

    kwargs = dict(static_argnums=(2,), donate_argnums=(0,))
    if mesh is not None:
        repl, data = _swav_shardings(mesh)
        kwargs.update(
            in_shardings=(repl, [data] * num_crop_groups),
            out_shardings=(repl, repl),
        )
    return jax.jit(train_step, **kwargs)


def make_swav_accumulate_step(model: SwAVModel, cfg: SwAVConfig, mesh=None,
                              num_crop_groups: int = 2):
    """Collaborative variant: per micro-batch grad accumulation (the shape
    CollaborativeOptimizer.step consumes, like make_accumulate_step for
    ALBERT). BN statistics and the queue are LOCAL per-peer state (exactly as
    in the reference, where the queue lives per-GPU in the loss and BN stats
    per node), so they update every micro-batch; gradients accumulate for the
    collaboration-wide step. The prototype freeze mask keys off the GLOBAL
    step (fork seam capability, standard_train_step.py:153) — zeroing is
    linear, so masking per micro-batch equals masking the averaged grads.

    Returns jitted (params, batch_stats, queue, grad_acc, n_acc, crops,
    global_step, use_queue) -> (grad_acc', n_acc', batch_stats', queue',
    metrics)."""

    def step(params, batch_stats, queue, grad_acc, n_acc, crops, global_step,
             use_queue: bool):
        queue_scores = (
            queue.scores(params["head"], cfg)
            if (use_queue and queue is not None)
            else None
        )

        def loss_fn(p):
            (emb, scores), mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                crops,
                True,
                mutable=["batch_stats"],
            )
            loss = swav_loss(scores, cfg, queue_scores, use_queue=use_queue)
            return loss, (mutated["batch_stats"], emb)

        (loss, (new_bn, emb)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        grads = freeze_prototypes_grads(
            grads, global_step, cfg.freeze_prototypes_steps
        )
        grad_acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32), grad_acc, grads
        )
        new_queue = queue.update(emb, cfg) if queue is not None else None
        return grad_acc, n_acc + 1, new_bn, new_queue, {"loss": loss}

    kwargs = dict(static_argnums=(7,), donate_argnums=(3, 4))
    if mesh is not None:
        # num_crop_groups must equal len(spec.sizes) of the feeding
        # MultiCropSpec — the sharding pytree must mirror the crops list
        repl, data = _swav_shardings(mesh)
        kwargs.update(
            in_shardings=(repl, repl, repl, repl, repl,
                          [data] * num_crop_groups, repl),
            out_shardings=(repl, repl, repl, repl, repl),
        )
    return jax.jit(step, **kwargs)


def make_prototype_post_apply():
    """Jitted TrainState -> TrainState re-normalizing prototypes after every
    global optimizer update (NormalizePrototypesHook.on_update capability) —
    plugs into CollaborativeOptimizer(post_apply=...)."""

    def post(state):
        return state.replace(params=normalize_prototypes(state.params))

    return jax.jit(post, donate_argnums=(0,))
