"""ResNet-50 trunk in Flax, TPU-first (NHWC, bf16 compute, fp32 BN stats).

Capability parity with the vissl trunk the SwAV workload trains
(reference: swav/vissl/vissl/models/trunks/resnext.py:49-172; resnet is an
alias, trunks/resnet.py:4-6). Not a port: layout is NHWC (TPU conv layout),
compute dtype bf16 with fp32 batch-norm statistics.

SyncBN (apex capability, swav_1node_resnet_submit.yaml:73-76) needs no knob
under jit/pjit: BN statistics are means over the GLOBAL batch axis, so when
the batch is sharded over a mesh XLA lowers them to cross-device psums
automatically. ``bn_axis_name`` exists ONLY for shard_map/pmap execution,
where the per-device batch is local and the reduction axis must be named;
leave it None under jit/pjit (a bound name does not exist there and would
fail at trace time).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    """ResNet-50 defaults (the reference's only trunk config)."""

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    width: int = 64
    dtype: Any = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    # ONLY for shard_map/pmap (named-axis) execution; None under jit/pjit,
    # where global-batch BN is automatic (see module docstring)
    bn_axis_name: Optional[str] = None

    @staticmethod
    def resnet50(**overrides) -> "ResNetConfig":
        return ResNetConfig(**overrides)

    @staticmethod
    def tiny(**overrides) -> "ResNetConfig":
        """Test-sized trunk (SURVEY.md §4 fixture pattern)."""
        base = dict(stage_sizes=(1, 1, 1, 1), width=8)
        base.update(overrides)
        return ResNetConfig(**base)

    @property
    def out_features(self) -> int:
        return self.width * 8 * 4  # final stage channels × bottleneck expansion


class _ConvBN(nn.Module):
    cfg: ResNetConfig
    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    use_relu: bool = True

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(
            self.features,
            self.kernel,
            self.strides,
            padding=[(k // 2, k // 2) for k in self.kernel],
            use_bias=False,
            dtype=self.cfg.dtype,
            param_dtype=jnp.float32,
            name="conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=self.cfg.bn_momentum,
            epsilon=self.cfg.bn_eps,
            dtype=jnp.float32,
            axis_name=self.cfg.bn_axis_name if train else None,
            name="bn",
        )(x)
        return nn.relu(x) if self.use_relu else x


class _Bottleneck(nn.Module):
    """1x1 reduce -> 3x3 -> 1x1 expand (x4), residual add."""

    cfg: ResNetConfig
    features: int
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool):
        residual = x
        y = _ConvBN(self.cfg, self.features, (1, 1), name="reduce")(x, train)
        y = _ConvBN(self.cfg, self.features, (3, 3), self.strides, name="conv3x3")(
            y, train
        )
        y = _ConvBN(
            self.cfg, self.features * 4, (1, 1), use_relu=False, name="expand"
        )(y, train)
        if residual.shape != y.shape:
            residual = _ConvBN(
                self.cfg,
                self.features * 4,
                (1, 1),
                self.strides,
                use_relu=False,
                name="proj",
            )(x, train)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Returns globally-pooled [N, out_features] trunk features."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, images, train: bool = True):
        cfg = self.cfg
        x = images.astype(cfg.dtype)
        x = nn.Conv(
            cfg.width,
            (7, 7),
            (2, 2),
            padding=[(3, 3), (3, 3)],
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name="stem_conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=cfg.bn_momentum,
            epsilon=cfg.bn_eps,
            dtype=jnp.float32,
            axis_name=cfg.bn_axis_name if train else None,
            name="stem_bn",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = _Bottleneck(
                    cfg,
                    cfg.width * (2**stage),
                    strides,
                    name=f"stage{stage}_block{block}",
                )(x, train)
        return jnp.mean(x, axis=(1, 2)).astype(jnp.float32)  # global avg pool
