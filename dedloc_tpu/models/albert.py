"""ALBERT in Flax, TPU-first.

Capability parity with the reference's ``AlbertForPreTraining`` workload
(reference: albert/run_trainer.py:56-70 builds transformers'
AlbertForPreTraining — MLM + sentence-order-prediction heads). This is NOT a
port of the torch module: the design exploits ALBERT's cross-layer parameter
sharing with ``nn.scan`` so the HLO contains ONE transformer layer body
iterated ``num_hidden_layers`` times — smaller programs, faster compiles, and
the natural shape for ``jax.checkpoint`` rematerialisation.

TPU notes:
- matmuls run in bf16 with fp32 accumulation (``preferred_element_type``);
  softmax and layernorm statistics in fp32.
- static shapes everywhere; attention mask is an additive bias, no gather.
- remat policy on the scanned layer trades HBM for MXU FLOPs.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

Dtype = Any


@dataclasses.dataclass(frozen=True)
class AlbertConfig:
    """ALBERT-large defaults (the reference's canonical workload config)."""

    vocab_size: int = 30000
    embedding_size: int = 128
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.0
    attention_dropout_prob: float = 0.0
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    pad_token_id: int = 0
    dtype: Any = jnp.bfloat16  # compute dtype; params stay fp32
    remat: bool = True
    # rematerialization policy for the scanned layer: "nothing" saves no
    # activations (min HBM), "dots" saves matmul outputs (fewer recomputed
    # MXU ops when HBM allows), "fused_ln" pairs with fused_ln=True (saves
    # exactly the named matmuls + every Pallas kernel's outputs, so the
    # backward replays no elementwise chain at all)
    remat_policy: str = "nothing"
    # fuse each residual-add + LayerNorm into one Pallas pass (fp32 stats,
    # one-kernel backward); numerics match the unfused path to bf16
    # precision. Off TPU the kernel runs in interpreter mode.
    fused_ln: bool = False
    # "dense" (materialized S² scores), "blockwise" (online-softmax over KV
    # blocks via lax.scan, O(S·block) memory — the long-context path),
    # "flash" (the same math as ONE fused Pallas kernel with a custom-VJP
    # backward: scores never leave VMEM; interpret-mode off TPU), or "ring"
    # (sequence-parallel exact attention: KV shards rotate around the mesh's
    # ``ring_axis`` via ppermute — requires ``ring_mesh``). All exact.
    attention_impl: str = "dense"
    attention_block_size: int = 512
    # sequence-parallel context for attention_impl="ring": the mesh whose
    # ``ring_axis`` the sequence dimension is sharded over (set by the
    # trainer when --training.mesh_seq_devices > 1)
    ring_mesh: Any = None
    ring_axis: str = "seq"
    # pipeline parallelism (--training.mesh_pipe_devices): the mesh whose
    # ``pipe_axis`` the encoder's layer iterations are staged over — ALBERT's
    # shared block applied num_hidden_layers/n_stages times per stage, GPipe
    # microbatch schedule under shard_map (parallel/pipeline.py). The param
    # tree is IDENTICAL to the scanned path (encoder/layer/block/...), so
    # checkpoints and collaborative gradient schemas interchange freely
    # between pipelined and non-pipelined peers.
    pipe_mesh: Any = None
    pipe_axis: str = "pipe"
    pipe_microbatches: int = 0  # 0 = 2 x n_stages (bubble = (S-1)/(M+S-1))
    # Switch-MoE FFN variant (--training.moe_experts, parallel/moe.py): the
    # dense gelu FFN becomes a top-1-routed expert FFN; experts shard over
    # ``moe_axis`` when ``moe_mesh`` is set (--training.mesh_expert_devices),
    # the dispatch einsums lowering to XLA all-to-alls. The Switch
    # load-balancing aux loss is sowed into the "losses" collection.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    moe_mesh: Any = None
    moe_axis: str = "expert"

    @staticmethod
    def named(model_size: str):
        """The one model_size -> config-constructor resolver (role CLIs and
        fine-tune CLIs must agree on names and fail the same way)."""
        ctors = {"tiny": AlbertConfig.tiny, "large": AlbertConfig.large}
        if model_size not in ctors:
            raise ValueError(
                f"unknown model_size {model_size!r} "
                f"(expected one of {sorted(ctors)})"
            )
        return ctors[model_size]

    @staticmethod
    def large(**overrides) -> "AlbertConfig":
        return AlbertConfig(**overrides)

    @staticmethod
    def tiny(**overrides) -> "AlbertConfig":
        """Test-sized config (CI smoke; SURVEY.md §4 fake-backend pattern)."""
        base = dict(
            vocab_size=512,
            embedding_size=16,
            hidden_size=32,
            num_hidden_layers=2,
            num_attention_heads=2,
            intermediate_size=64,
            max_position_embeddings=64,
        )
        base.update(overrides)
        return AlbertConfig(**base)


def _dense(features: int, cfg: AlbertConfig, name: str) -> nn.Dense:
    return nn.Dense(
        features,
        dtype=cfg.dtype,
        param_dtype=jnp.float32,
        kernel_init=nn.initializers.normal(cfg.initializer_range),
        name=name,
    )


class AddLayerNorm(nn.Module):
    """``LayerNorm(x + residual)`` with the same parameter tree as
    ``nn.LayerNorm`` (scale/bias), so checkpoints are interchangeable.

    With ``cfg.fused_ln`` the add→stats→normalize chain runs as ONE Pallas
    pass each way (ops/fused_ln.py) instead of several HBM passes in the
    remat replay. Both paths now perform the residual ADD in fp32 (the
    pre-round-4 code added in ``cfg.dtype`` before the fp32-stat LN, so
    bf16 configs differ from older runs at bf16-rounding level — a strict
    precision improvement, and fused/unfused match each other)."""

    cfg: AlbertConfig

    @nn.compact
    def __call__(self, x, residual):
        cfg = self.cfg
        h = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (h,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (h,), jnp.float32)
        from dedloc_tpu.ops.fused_ln import ln_residual, ln_residual_reference

        if cfg.fused_ln:
            return ln_residual(
                x, residual, scale, bias, eps=cfg.layer_norm_eps
            ).astype(cfg.dtype)
        return ln_residual_reference(
            x.astype(jnp.float32), residual.astype(jnp.float32),
            scale, bias, eps=cfg.layer_norm_eps,
        ).astype(cfg.dtype)


class AlbertSelfAttention(nn.Module):
    cfg: AlbertConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, attn_bias):
        cfg = self.cfg
        deterministic = self.deterministic
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        B, S, H = hidden.shape

        def split_heads(x):
            return x.reshape(B, S, cfg.num_attention_heads, head_dim)

        q = split_heads(_dense(cfg.hidden_size, cfg, "query")(hidden))
        k = split_heads(_dense(cfg.hidden_size, cfg, "key")(hidden))
        v = split_heads(_dense(cfg.hidden_size, cfg, "value")(hidden))

        if (
            cfg.attention_impl in ("flash", "blockwise", "ring")
            and cfg.attention_dropout_prob > 0.0
            and not deterministic
        ):
            # in deterministic (eval/serving) mode dropout is inactive, so a
            # dense-trained model can still be served with the fused impls
            raise ValueError(
                f"attention_impl={cfg.attention_impl!r} does not support "
                "attention dropout in training (the reference recipe uses "
                "0.0); use attention_impl='dense' or attention_dropout_prob=0"
            )
        if cfg.attention_impl == "flash":
            # fused Pallas kernel: scores stay in VMEM, flash backward
            # (attention dropout is 0.0 in the reference recipe, so the
            # fused path loses nothing)
            from dedloc_tpu.ops.flash_attention import flash_attention

            kv_bias = attn_bias[:, 0, 0, :]  # additive [B, S_kv]
            ctx = flash_attention(
                q, k, v, kv_bias,
                block_q=cfg.attention_block_size,
                block_k=cfg.attention_block_size,
            ).reshape(B, S, H)
        elif cfg.attention_impl == "ring":
            # sequence parallelism: S is sharded over ring_mesh's ring_axis;
            # each device keeps its resident queries and rotates KV shards
            # around the ring (ppermute over ICI) — exact, never materializes
            # the S×S score matrix on any one device
            from dedloc_tpu.parallel.ring_attention import ring_attention

            if cfg.ring_mesh is None:
                raise ValueError(
                    "attention_impl='ring' needs ring_mesh (a Mesh with a "
                    f"{cfg.ring_axis!r} axis); the trainer sets it when "
                    "--training.mesh_seq_devices > 1"
                )
            kv_bias = attn_bias[:, 0, 0, :]  # additive [B, S_kv]
            ctx = ring_attention(
                q, k, v, kv_bias, mesh=cfg.ring_mesh, axis=cfg.ring_axis
            ).reshape(B, S, H)
        elif cfg.attention_impl == "blockwise":
            # long-context path: exact online-softmax over KV blocks — never
            # materializes the S×S score matrix
            from dedloc_tpu.parallel.ring_attention import blockwise_attention

            kv_bias = attn_bias[:, 0, 0, :]  # additive [B, S_kv]
            ctx = blockwise_attention(
                q, k, v, kv_bias, block_size=cfg.attention_block_size
            ).reshape(B, S, H)
        else:
            # fp32 logits + softmax for numerical stability; bf16 elsewhere.
            scale = 1.0 / jnp.sqrt(head_dim).astype(jnp.float32)
            logits = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
            ) * scale
            logits = logits + attn_bias  # additive mask: 0 keep / -inf drop
            probs = jax.nn.softmax(logits, axis=-1).astype(cfg.dtype)
            if cfg.attention_dropout_prob > 0.0 and not deterministic:
                probs = nn.Dropout(cfg.attention_dropout_prob)(
                    probs, deterministic=deterministic
                )
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, H)
        out = _dense(cfg.hidden_size, cfg, "dense")(ctx)
        if cfg.hidden_dropout_prob > 0.0 and not deterministic:
            out = nn.Dropout(cfg.hidden_dropout_prob)(out, deterministic=deterministic)
        return AddLayerNorm(cfg, name="layernorm")(out, hidden)


class AlbertLayer(nn.Module):
    """One shared transformer block (attention + FFN, post-LN).

    Returns ``(hidden, aux_loss)`` — aux_loss is the Switch load-balancing
    term when ``cfg.moe_experts`` routes the FFN through experts, else 0.
    """

    cfg: AlbertConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, attn_bias):
        cfg = self.cfg
        deterministic = self.deterministic
        hidden = AlbertSelfAttention(cfg, deterministic, name="attention")(
            hidden, attn_bias
        )
        aux = jnp.zeros([], jnp.float32)
        if cfg.moe_experts > 0:
            ffn, aux = self._moe_ffn(hidden)
        else:
            # named for the fused_ln remat policy: the FFN up-projection is
            # the one matmul output the backward cannot cheaply recompute
            # (gelu's input); everything downstream is covered by saved
            # Pallas outputs
            ffn = checkpoint_name(
                _dense(cfg.intermediate_size, cfg, "ffn")(hidden), "ffn_up"
            )
            # also named so fused_ln_gelu can save the activation output and
            # skip the gelu forward replay in the remat backward (naming is
            # free for policies that don't reference it)
            ffn = checkpoint_name(nn.gelu(ffn, approximate=True), "ffn_gelu")
            ffn = _dense(cfg.hidden_size, cfg, "ffn_output")(ffn)
        if cfg.hidden_dropout_prob > 0.0 and not deterministic:
            ffn = nn.Dropout(cfg.hidden_dropout_prob)(ffn, deterministic=deterministic)
        return AddLayerNorm(cfg, name="layernorm")(ffn, hidden), aux

    def _moe_ffn(self, hidden):
        """Switch-routed FFN (parallel/moe.py): one expert set shared across
        the layer iterations — ALBERT's cross-layer sharing extended to the
        experts. Router/expert weights live in this layer's param tree, so
        checkpoints and the collaborative gradient schema carry them like
        any other leaf."""
        from dedloc_tpu.parallel.moe import MoEConfig, moe_ffn

        cfg = self.cfg
        B, S, H = hidden.shape
        mcfg = MoEConfig(
            hidden_size=cfg.hidden_size,
            ffn_size=cfg.intermediate_size,
            num_experts=cfg.moe_experts,
            capacity_factor=cfg.moe_capacity_factor,
            dtype=cfg.dtype,
        )
        init = nn.initializers.normal(cfg.initializer_range)
        params = {
            "router": self.param(
                "moe_router", init, (H, cfg.moe_experts), jnp.float32
            ),
            "wi": self.param(
                "moe_wi", init,
                (cfg.moe_experts, H, cfg.intermediate_size), jnp.float32,
            ),
            "wo": self.param(
                "moe_wo", init,
                (cfg.moe_experts, cfg.intermediate_size, H), jnp.float32,
            ),
        }
        # bf16 expert compute like the dense FFN; router math is fp32 inside
        params = {
            "router": params["router"],
            "wi": params["wi"].astype(cfg.dtype),
            "wo": params["wo"].astype(cfg.dtype),
        }
        y, aux = moe_ffn(
            params, hidden.reshape(B * S, H), mcfg,
            mesh=cfg.moe_mesh, axis=cfg.moe_axis,
        )
        return y.reshape(B, S, H).astype(cfg.dtype), aux


#: The only policy names that engage the fused add+LN Pallas kernel; a
#: membership test (not a prefix match) so a typo like "fused_ln_geluu"
#: fails fast at the remat-policy table with "unknown remat_policy"
#: instead of enabling the kernel and dying later on a bare KeyError.
FUSED_LN_POLICIES = frozenset({"fused_ln", "fused_ln_gelu"})


def fused_ln_for_policy(remat_policy: str) -> bool:
    """Policy -> whether the fused add+LN Pallas kernel must be on: the
    fused_ln* saved sets only cover the backward when the kernel produces
    the (y, x̂, rstd) outputs they rely on. One source of truth for every
    builder (bench, roles, profiler)."""
    return remat_policy in FUSED_LN_POLICIES


def _pallas_outputs_saveable(prim, *_, **__) -> bool:
    """Remat-policy predicate: save the outputs of Pallas kernels (here the
    flash-attention out/lse residuals) instead of re-running them backward."""
    return getattr(prim, "name", "") == "pallas_call"


def remat_policy_object(name: str):
    """Resolve a remat-policy NAME to the jax.checkpoint policy object — the
    one table both the scanned encoder and the pipeline-parallel stage wrap
    their layer body with (so --training.remat_policy means the same thing
    on every parallelism path). Raises on unknown names."""
    table = {
        "nothing": jax.checkpoint_policies.nothing_saveable,
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch": (
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        ),
        # dots_no_batch + flash-attention outputs (out, lse): the
        # custom-VJP backward then runs straight from saved residuals
        # instead of re-running the forward kernel during remat
        # (~30 MB/layer extra HBM at B=32, measured step win on v5e)
        "dots_no_batch_attn": (
            jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                _pallas_outputs_saveable,
            )
        ),
        # fused-LN recipe (pairs with cfg.fused_ln): save ONLY the
        # named matmul outputs (q/k/v in flash layout, FFN up) plus
        # every Pallas kernel's outputs — flash (out, lse) and the
        # fused add+LN's (y, x̂, rstd). The backward then replays no
        # elementwise chain; dropping the two out-projection dot
        # saves pays for the x̂ residuals, so HBM is ~neutral vs
        # dots_no_batch_attn.
        "fused_ln": (
            jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.save_only_these_names(
                    "flash_qkv", "ffn_up"
                ),
                _pallas_outputs_saveable,
            )
        ),
        # fused_ln + the gelu output: the backward's one remaining
        # forward replay (gelu of the FFN up-projection) runs from a
        # saved residual instead — costs [B,S,intermediate] bf16 per
        # layer iteration of extra HBM (ffn_up stays saved: gelu's
        # VJP still needs its primal input)
        "fused_ln_gelu": (
            jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.save_only_these_names(
                    "flash_qkv", "ffn_up", "ffn_gelu"
                ),
                _pallas_outputs_saveable,
            )
        ),
    }
    if name not in table:
        raise ValueError(
            f"unknown remat_policy {name!r}; expected one of {sorted(table)}"
        )
    return table[name]


class _ScannedAlbertLayer(nn.Module):
    """scan body: carry = hidden states; attn_bias broadcast; per-step out =
    the layer's aux (MoE load-balance) loss."""

    cfg: AlbertConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, attn_bias):
        layer_cls = AlbertLayer
        if self.cfg.remat:
            layer_cls = nn.remat(
                AlbertLayer, policy=remat_policy_object(self.cfg.remat_policy)
            )
        out, aux = layer_cls(self.cfg, self.deterministic, name="block")(
            hidden, attn_bias
        )
        return out, aux


class AlbertEncoder(nn.Module):
    """Shared-parameter layer stack: nn.scan (one layer body in the HLO) —
    or, with ``cfg.pipe_mesh``, the GPipe pipeline path staging the same
    shared block across the mesh's pipe axis (parallel/pipeline.py)."""

    cfg: AlbertConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, attn_bias):
        cfg = self.cfg
        if cfg.pipe_mesh is not None:
            hidden, moe_aux = self._pipelined(hidden, attn_bias)
        else:
            # variable_broadcast shares the single layer's params across all
            # iterations — exactly ALBERT's cross-layer weight sharing.
            scan_layer = nn.scan(
                _ScannedAlbertLayer,
                variable_broadcast="params",
                split_rngs={"params": False, "dropout": True},
                in_axes=nn.broadcast,
                length=cfg.num_hidden_layers,
            )
            hidden, aux_ys = scan_layer(cfg, self.deterministic, name="layer")(
                hidden, attn_bias
            )
            moe_aux = jnp.sum(aux_ys)
        if cfg.moe_experts > 0:
            # the trainer's loss_fn reads this via mutable=("losses",) and
            # adds cfg.moe_aux_weight * moe_aux (Switch load balancing)
            self.sow("losses", "moe_aux", moe_aux)
        return hidden

    def _pipelined(self, hidden, attn_bias):
        """Pipeline-parallel forward: num_hidden_layers/n_stages iterations
        of the ONE shared block per stage, microbatches hopping stage→stage
        (GPipe under shard_map). The param tree is created by the same
        AlbertLayer init as the scan path, under the same names
        (layer/block/...), so both paths share checkpoints and gradient
        schemas. Composes with a "data" mesh axis (microbatch rows sharded
        over it); "seq"/"model" axes and MoE need their own collectives
        inside the stage and are rejected with the reason."""
        from dedloc_tpu.parallel.pipeline import pipeline_apply, shared_stage_fn

        cfg = self.cfg
        mesh, axis = cfg.pipe_mesh, cfg.pipe_axis
        n_stages = int(mesh.shape[axis])
        if cfg.num_hidden_layers % n_stages:
            raise ValueError(
                f"num_hidden_layers ({cfg.num_hidden_layers}) must divide "
                f"evenly into {n_stages} pipeline stages"
            )
        if cfg.moe_experts > 0:
            raise ValueError(
                "pipe_mesh + moe_experts unsupported: the expert all-to-all "
                "would need its own axis inside the pipeline's shard_map"
            )
        if cfg.attention_impl == "ring":
            raise ValueError(
                "pipe_mesh + attention_impl='ring' unsupported: ring "
                "attention opens its own shard_map over the seq axis"
            )
        if not self.deterministic and (
            cfg.hidden_dropout_prob > 0.0 or cfg.attention_dropout_prob > 0.0
        ):
            raise ValueError(
                "the pipeline path does not thread dropout rngs through "
                "shard_map stages; use dropout 0 (the reference recipe)"
            )
        iters = cfg.num_hidden_layers // n_stages
        B, S, H = hidden.shape
        M = cfg.pipe_microbatches or 2 * n_stages
        layer = AlbertLayer(cfg, self.deterministic)
        proto_x = jnp.zeros((max(1, B // M), S, H), hidden.dtype)
        proto_b = jnp.zeros(
            (max(1, B // M),) + attn_bias.shape[1:], attn_bias.dtype
        )
        params = self.param(
            "layer",
            lambda rng: {"block": layer.init(rng, proto_x, proto_b)["params"]},
        )
        if self.is_initializing():
            # init runs with the PER-DEVICE batch (roles init that way so
            # param shapes come cheap) — the pipeline schedule is
            # irrelevant to parameter creation, so apply the block
            # sequentially for the init-time forward value
            h = hidden
            for _ in range(cfg.num_hidden_layers):
                h, _aux = layer.apply({"params": params["block"]}, h, attn_bias)
            return h, jnp.zeros([], jnp.float32)
        if B % M:
            raise ValueError(
                f"batch ({B}) must divide into pipe_microbatches ({M})"
            )

        def block_fn(p, xb):
            h, b = xb
            h2, _aux = layer.apply({"params": p["block"]}, h, b)
            return (h2, b)

        if cfg.remat:
            block_fn = jax.checkpoint(
                block_fn, policy=remat_policy_object(cfg.remat_policy)
            )
        stage = shared_stage_fn(block_fn, iters)
        micro = (
            hidden.reshape(M, B // M, S, H),
            jnp.broadcast_to(
                attn_bias, (B,) + attn_bias.shape[1:]
            ).reshape((M, B // M) + attn_bias.shape[1:]),
        )
        spec = P(None, "data") if "data" in mesh.axis_names else P()
        out_h, _ = pipeline_apply(
            stage, params, micro, mesh, axis=axis,
            stacked_params=False, micro_spec=spec,
        )
        return out_h.reshape(B, S, H), jnp.zeros([], jnp.float32)


class AlbertModel(nn.Module):
    cfg: AlbertConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        deterministic: bool = True,
    ):
        cfg = self.cfg
        B, S = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), dtype=jnp.int32)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((B, S), dtype=jnp.int32)

        word_emb = nn.Embed(
            cfg.vocab_size,
            cfg.embedding_size,
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            param_dtype=jnp.float32,
            name="word_embeddings",
        )
        pos_emb = nn.Embed(
            cfg.max_position_embeddings,
            cfg.embedding_size,
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            param_dtype=jnp.float32,
            name="position_embeddings",
        )
        type_emb = nn.Embed(
            cfg.type_vocab_size,
            cfg.embedding_size,
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            param_dtype=jnp.float32,
            name="token_type_embeddings",
        )
        positions = jnp.arange(S)[None, :]
        emb = word_emb(input_ids) + pos_emb(positions) + type_emb(token_type_ids)
        emb = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                           name="embeddings_layernorm")(emb)
        if cfg.hidden_dropout_prob > 0.0 and not deterministic:
            emb = nn.Dropout(cfg.hidden_dropout_prob)(emb, deterministic=deterministic)

        # Factorized embedding: project emb_size -> hidden_size.
        hidden = _dense(cfg.hidden_size, cfg, "embedding_projection")(
            emb.astype(cfg.dtype)
        )

        attn_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -1e9).astype(
            jnp.float32
        )
        hidden = AlbertEncoder(cfg, deterministic, name="encoder")(hidden, attn_bias)

        pooled = _dense(cfg.hidden_size, cfg, "pooler")(hidden[:, 0])
        pooled = jnp.tanh(pooled)
        return hidden, pooled


class AlbertForPreTraining(nn.Module):
    """ALBERT with MLM + sentence-order-prediction heads.

    The MLM decoder is tied to the word-embedding table (same capability as
    transformers' AlbertForPreTraining used at albert/run_trainer.py:64-67).
    """

    cfg: AlbertConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        deterministic: bool = True,
        mlm_positions=None,
    ):
        """``mlm_positions`` [B, P]: when given, the MLM head runs only on
        those gathered positions (returns [B, P, vocab]) — the TPU-native
        masked-position path that skips ~85% of the vocab-projection FLOPs.
        When None, logits cover every position (reference-equivalent)."""
        cfg = self.cfg
        backbone = AlbertModel(cfg, name="albert")
        hidden, pooled = backbone(
            input_ids, attention_mask, token_type_ids, deterministic
        )

        if mlm_positions is not None:
            # gather [B, P, H] prediction positions before the vocab matmul
            hidden = jnp.take_along_axis(
                hidden, mlm_positions[..., None].astype(jnp.int32), axis=1
            )

        # MLM head: hidden -> embedding_size -> vocab (tied decoder).
        x = _dense(cfg.embedding_size, cfg, "mlm_dense")(hidden)
        x = nn.gelu(x, approximate=True)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="mlm_layernorm")(x).astype(cfg.dtype)
        embedding_table = backbone.variables["params"]["word_embeddings"]["embedding"]
        mlm_logits = jnp.einsum(
            "bsh,vh->bsv",
            x,
            embedding_table.astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        )
        mlm_bias = self.param(
            "mlm_bias", nn.initializers.zeros, (cfg.vocab_size,), jnp.float32
        )
        mlm_logits = mlm_logits + mlm_bias

        sop_logits = _dense(2, cfg, "sop_classifier")(pooled).astype(jnp.float32)
        return mlm_logits, sop_logits


class AlbertForTokenClassification(nn.Module):
    """ALBERT with a per-token classifier head.

    Capability of ``AutoModelForTokenClassification`` as used by the
    reference's NER fine-tune driver (sahajbert/train_ner.py:160-168):
    backbone hidden states -> dropout -> Dense(num_labels) in fp32.
    """

    cfg: AlbertConfig
    num_labels: int
    classifier_dropout: float = 0.1

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        deterministic: bool = True,
    ):
        hidden, _ = AlbertModel(self.cfg, name="albert")(
            input_ids, attention_mask, token_type_ids, deterministic
        )
        if self.classifier_dropout > 0.0 and not deterministic:
            hidden = nn.Dropout(self.classifier_dropout)(
                hidden, deterministic=deterministic
            )
        return _dense(self.num_labels, self.cfg, "classifier")(hidden).astype(
            jnp.float32
        )


class AlbertForSequenceClassification(nn.Module):
    """ALBERT with a pooled-output classifier head.

    Capability of ``AutoModelForSequenceClassification`` as used by the
    reference's news-category fine-tune driver (sahajbert/train_ncc.py:25,159):
    pooled [CLS] -> dropout -> Dense(num_labels) in fp32.
    """

    cfg: AlbertConfig
    num_labels: int
    classifier_dropout: float = 0.1

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        deterministic: bool = True,
    ):
        _, pooled = AlbertModel(self.cfg, name="albert")(
            input_ids, attention_mask, token_type_ids, deterministic
        )
        if self.classifier_dropout > 0.0 and not deterministic:
            pooled = nn.Dropout(self.classifier_dropout)(
                pooled, deterministic=deterministic
            )
        return _dense(self.num_labels, self.cfg, "classifier")(pooled).astype(
            jnp.float32
        )


def _masked_cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Masked-mean CE + accuracy over positions where ``mask`` is 1.

    ``labels`` must already be clamped into [0, num_classes). Returns
    (loss, accuracy, denom) with denom = max(mask.sum(), 1).
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    acc = ((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32) * mask).sum() / (
        denom
    )
    return loss, acc, denom


def classification_loss(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_index: int = -100,
) -> Tuple[jnp.ndarray, dict]:
    """Cross-entropy over any leading shape, masked-mean over labels != -100.

    Serves both fine-tune heads: token classification ([B, S, L] logits with
    -100 on special/continuation tokens, train_ner.py:199-209) and sequence
    classification ([B, L] logits, all labelled).
    """
    mask = (labels != ignore_index).astype(jnp.float32)
    safe = jnp.where(labels == ignore_index, 0, labels)
    loss, acc, _ = _masked_cross_entropy(logits, safe, mask)
    return loss, {"loss": loss, "accuracy": acc, "n_labels": mask.sum()}


def albert_pretraining_loss(
    mlm_logits: jnp.ndarray,
    sop_logits: jnp.ndarray,
    mlm_labels: jnp.ndarray,
    sop_labels: jnp.ndarray,
    ignore_index: int = -100,
) -> Tuple[jnp.ndarray, dict]:
    """MLM + SOP cross-entropy, masked-mean over labelled positions.

    Matches the loss AlbertForPreTraining computes (MLM CE over positions with
    label != -100 plus SOP CE over the pooled output).
    """
    mask = (mlm_labels != ignore_index).astype(jnp.float32)
    safe_labels = jnp.where(mlm_labels == ignore_index, 0, mlm_labels)
    mlm_loss, mlm_acc, _ = _masked_cross_entropy(mlm_logits, safe_labels, mask)

    sop_logp = jax.nn.log_softmax(sop_logits.astype(jnp.float32), axis=-1)
    sop_nll = -jnp.take_along_axis(sop_logp, sop_labels[:, None], axis=-1)[:, 0]
    sop_loss = sop_nll.mean()

    loss = mlm_loss + sop_loss
    metrics = {
        "loss": loss,
        "mlm_loss": mlm_loss,
        "sop_loss": sop_loss,
        "mlm_acc": mlm_acc,
    }
    return loss, metrics


def albert_pretraining_loss_gathered(
    mlm_logits: jnp.ndarray,  # [B, P, vocab] — logits at gathered positions
    sop_logits: jnp.ndarray,
    mlm_label_ids: jnp.ndarray,  # [B, P]
    mlm_weights: jnp.ndarray,  # [B, P] 1.0 real prediction / 0.0 padding
    sop_labels: jnp.ndarray,
) -> Tuple[jnp.ndarray, dict]:
    """Masked-position variant of the MLM+SOP loss (same value as the dense
    loss for equal label sets; see the gathered-head path above)."""
    w = mlm_weights.astype(jnp.float32)
    mlm_loss, mlm_acc, _ = _masked_cross_entropy(mlm_logits, mlm_label_ids, w)

    sop_logp = jax.nn.log_softmax(sop_logits.astype(jnp.float32), axis=-1)
    sop_nll = -jnp.take_along_axis(sop_logp, sop_labels[:, None], axis=-1)[:, 0]
    sop_loss = sop_nll.mean()

    loss = mlm_loss + sop_loss
    metrics = {
        "loss": loss,
        "mlm_loss": mlm_loss,
        "sop_loss": sop_loss,
        "mlm_acc": mlm_acc,
    }
    return loss, metrics
