"""Trainer peer: the canonical collaborative training loop.

Capability parity with albert/run_trainer.py:210-297 — build model + LAMB +
DHT + CollaborativeOptimizer, resume from the latest local checkpoint, pull
newer state from peers at start (on_train_begin semantics :124-128), then
loop: jitted accumulate per micro-batch; at every accumulation boundary hand
control to the collaborative optimizer (global-step averaging, NaN rollback)
and publish signed metrics (:130-170).

TPU-native shape: the hot path is ONE jitted accumulate step with a donated
device-resident grad accumulator; the jit↔Python seam is crossed once per
accumulation boundary, not per micro-batch (SURVEY.md §7 hard-part b).
"""
from __future__ import annotations

import json
import time
from typing import Optional

import jax
import jax.numpy as jnp

from dedloc_tpu.collaborative.metrics import LocalMetrics, publish_metrics
from dedloc_tpu.collaborative.optimizer import CollaborativeOptimizer
from dedloc_tpu.telemetry import steps
from dedloc_tpu.telemetry.links import endpoint_key
from dedloc_tpu.telemetry.steps import (
    StepRecorder,
    albert_tflops_per_sample,
    chip_peak_tflops,
)
from dedloc_tpu.core.config import CollaborationArguments, parse_config
from dedloc_tpu.data.streaming import peer_shuffle_seed
from dedloc_tpu.parallel.train_step import (
    TrainState,
    make_accumulate_step,
    zeros_like_grads,
)
from dedloc_tpu.roles.common import (
    build_dht,
    build_flat_opt_factory,
    build_loss_fn,
    build_model,
    build_optimizer,
    checkpoint_kwargs,
    configure_role_telemetry,
    drop_collator_keys,
    force_cpu_if_requested,
    synthetic_mlm_batches,
)
from dedloc_tpu.utils.checkpoint import load_latest_checkpoint, save_checkpoint
from dedloc_tpu.utils.logging import get_logger
from dedloc_tpu.utils.perf import PerfStats

logger = get_logger(__name__)


def run_trainer(args: CollaborationArguments) -> TrainState:
    force_cpu_if_requested()
    # gated runs: token handshake BEFORE any heavy setup, so bad credentials
    # fail in milliseconds (contributor notebook cell-2 ordering)
    from dedloc_tpu.roles.common import build_authorizer

    authorizer, authority_public_key = build_authorizer(args)
    # slice-as-one-peer: with mesh_devices > 1 this process drives a
    # data-parallel mesh; the micro-batch grad mean lowers to ICI psums and
    # the collaboration sees the whole slice as a single member. A
    # mesh_seq_devices factor carves a "seq" axis out of the slice for
    # sequence parallelism (ring attention).
    mesh = None
    if args.training.mesh_devices > 1:
        from dedloc_tpu.parallel.mesh import make_mesh, put_batch

        sp = max(1, args.training.mesh_seq_devices)
        tp = max(1, args.training.mesh_model_devices)
        pp = max(1, args.training.mesh_pipe_devices)
        ep = max(1, args.training.mesh_expert_devices)
        if args.training.mesh_devices % (sp * tp * pp * ep):
            raise ValueError(
                f"mesh_seq_devices ({sp}) x mesh_model_devices ({tp}) x "
                f"mesh_pipe_devices ({pp}) x mesh_expert_devices ({ep}) "
                f"must divide mesh_devices ({args.training.mesh_devices})"
            )
        if pp > 1 and (sp > 1 or tp > 1):
            # the pipeline stage body runs inside its own shard_map; ring
            # attention ("seq") and the TP layouts ("model") place their
            # collectives via GSPMD annotations, which don't apply there
            raise ValueError(
                "mesh_pipe_devices composes with the data axis only; "
                "seq/model axes need collectives inside the pipeline stage"
            )
        if ep > 1 and not args.training.moe_experts:
            raise ValueError(
                "mesh_expert_devices > 1 needs --training.moe_experts > 0"
            )
        if args.training.moe_experts and (
            args.training.moe_experts % ep
        ):
            raise ValueError(
                f"moe_experts ({args.training.moe_experts}) must divide "
                f"evenly over mesh_expert_devices ({ep})"
            )
        dp = args.training.mesh_devices // (sp * tp * pp * ep)
        names, dims = ["data"], [dp]
        if tp > 1:
            names.append("model"); dims.append(tp)
        if sp > 1:
            names.append("seq"); dims.append(sp)
        if pp > 1:
            names.append("pipe"); dims.append(pp)
        if ep > 1:
            names.append("expert"); dims.append(ep)
        mesh = make_mesh(
            args.training.mesh_devices,
            axis_names=tuple(names),
            shape=tuple(dims) if len(dims) > 1 else None,
            device_offset=args.training.mesh_device_offset,
        )
        logger.info(f"slice mesh: {mesh.shape}")
    elif (
        args.training.mesh_seq_devices > 1
        or args.training.mesh_model_devices > 1
        or args.training.mesh_pipe_devices > 1
        or args.training.mesh_expert_devices > 1
    ):
        raise ValueError(
            "mesh_seq/model/pipe/expert_devices > 1 require mesh_devices > 1"
        )
    if args.training.attention_impl == "ring" and (
        mesh is None or "seq" not in mesh.axis_names
    ):
        # fail here with the cause, not deep inside the first jitted trace
        raise ValueError(
            "attention_impl='ring' needs a sequence-parallel mesh axis: set "
            "--training.mesh_seq_devices > 1 (and mesh_devices divisible by it)"
        )

    cfg, model = build_model(
        args.training.model_size,
        args.training.remat_policy,
        args.training.attention_impl,
        args.training.vocab_size,
        ring_mesh=mesh if args.training.attention_impl == "ring" else None,
        pipe_mesh=(
            mesh if mesh is not None and "pipe" in mesh.axis_names else None
        ),
        pipe_microbatches=args.training.pipe_microbatches,
        moe_experts=args.training.moe_experts,
        moe_mesh=(
            mesh if mesh is not None and "expert" in mesh.axis_names else None
        ),
        moe_capacity_factor=args.training.moe_capacity_factor,
        moe_aux_weight=args.training.moe_aux_weight,
    )
    tx = build_optimizer(args)
    # gated: record-sign with the token key, so the signed subkey digests
    # to this peer's verified identity (ledger binding, roles/common.py)
    dht, public_key = build_dht(
        args,
        private_key=(
            authorizer.local_private_key if authorizer is not None else None
        ),
    )
    logger.info(f"trainer DHT listening on {dht.port}")
    # swarm telemetry (--telemetry.*, docs/observability.md): disabled
    # (default) => None and the instrumented seams stay free
    tele, tele_close = configure_role_telemetry(args, public_key)

    rng = jax.random.PRNGKey(args.training.seed)
    seq = min(args.training.seq_length, cfg.max_position_embeddings)
    slice_batch = args.training.per_device_batch_size * max(
        1, args.training.mesh_devices
    )
    # init with the PER-DEVICE batch: param shapes don't depend on batch
    # size, and a full slice batch would run this forward unsharded on one
    # device — 8x the training-time activation memory on a real slice
    init_ids = jnp.zeros((args.training.per_device_batch_size, seq), jnp.int32)
    params = model.init(rng, init_ids)["params"]
    state = jax.jit(lambda p: TrainState.create(p, tx))(params)

    # local resume (run_trainer.py:56-70): newest checkpoint* dir wins
    resumed = load_latest_checkpoint(args.training.output_dir)
    resumed_local_step = 0
    if resumed is not None:
        step, tree, meta = resumed
        template = jax.device_get((state.params, state.opt_state))
        params_t, opt_t = _named_to_tree_pair(tree, template)
        state = state.replace(
            step=jnp.asarray(step, jnp.int32),
            params=jax.device_put(params_t),
            opt_state=jax.device_put(opt_t),
        )
        # carry the COLLABORATIVE counter too: when a whole collaboration
        # restarts from disk (fresh DHT, nobody to pull state from), round
        # ids and published metrics must continue from the checkpoint's
        # global step, not restart at 0
        resumed_local_step = int(meta.get("local_step", step))
        logger.info(f"resumed from local checkpoint at step {step}")

    if args.training.zero_sharding and mesh is None:
        raise ValueError(
            "--training.zero_sharding shards optimizer moments over a slice "
            "mesh; set --training.mesh_devices > 1"
        )
    # tensor parallelism: Megatron-style param layout over the "model" axis
    # (parallel/sharding.py rules); moments follow their params' layout.
    # EP composes by rule concatenation: the expert-stacked MoE leaves
    # shard over "expert", everything TP doesn't claim stays replicated.
    param_sharding = None
    shard_rules = None
    if mesh is not None and (
        "model" in mesh.axis_names or "expert" in mesh.axis_names
    ):
        from jax.sharding import NamedSharding
        from dedloc_tpu.parallel.sharding import (
            ALBERT_EP_RULES,
            ALBERT_TP_RULES,
            partition_specs,
        )

        shard_rules = tuple(
            (ALBERT_TP_RULES if "model" in mesh.axis_names else ())
        ) + tuple(
            (ALBERT_EP_RULES if "expert" in mesh.axis_names else ())
        )
        param_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            partition_specs(state.params, shard_rules),
        )
    opt_sharding = None
    if mesh is not None and (args.training.zero_sharding
                             or param_sharding is not None):
        # ZeRO-1: LAMB moments shard over the slice's data axis; GSPMD
        # inserts the gathers the elementwise update needs (parallel/zero.py).
        # With TP/EP, moments of sharded params follow the param layout and
        # ZeRO (when enabled) shards only the rest.
        from dedloc_tpu.parallel.zero import opt_state_shardings

        opt_sharding = opt_state_shardings(
            state.opt_state, mesh,
            axis="data" if args.training.zero_sharding else None,
            tp_rules=shard_rules,
        )

    opt = CollaborativeOptimizer(
        tx,
        dht,
        prefix=args.dht.experiment_prefix,
        target_batch_size=args.optimizer.target_batch_size,
        batch_size_per_step=(
            slice_batch * args.training.gradient_accumulation_steps
        ),
        batch_size_lead=args.optimizer.batch_size_lead,
        bandwidth=args.averager.bandwidth,
        compression=args.averager.compression,
        chunk_size=args.averager.chunk_size,
        # hierarchical two-level averaging (--averager.topology_plan):
        # clique-first reduction per the operator-installed plan
        topology_plan=args.averager.topology_plan or None,
        # live re-planning: follow the coordinator's plan record UNLESS
        # the operator pinned a manual plan (pin = opt-out, docs/fleet.md)
        plan_follow=(
            args.averager.plan_follow and not args.averager.topology_plan
        ),
        plan_refresh_period=args.averager.plan_refresh_period,
        error_feedback=args.optimizer.error_feedback,
        overlap_averaging=args.optimizer.overlap_averaging,
        # signed contribution ledger (--optimizer.ledger_claims /
        # --averager.ledger_receipts; docs/observability.md)
        ledger_claims=args.optimizer.ledger_claims,
        claim_period=args.optimizer.claim_period,
        ledger_receipts=args.averager.ledger_receipts,
        target_group_size=args.averager.target_group_size,
        averaging_expiration=args.averager.averaging_expiration,
        averaging_timeout=args.averager.averaging_timeout,
        metadata_expiration=args.averager.metadata_expiration,
        statistics_expiration=args.optimizer.statistics_expiration,
        contrib_clip_per_sample=args.optimizer.contrib_clip_per_sample,
        ramp_rounds=args.optimizer.ramp_rounds,
        health_gate_loss_ratio=args.optimizer.health_gate_loss_ratio,
        state_sync_retries=args.averager.state_sync_retries,
        state_sync_backoff=args.averager.state_sync_backoff,
        # device-resident gradient pipeline + fused flat apply
        # (--optimizer.device_flat / --optimizer.flat_apply; docs/perf.md
        # round 6): compressed D2H streaming and one-buffer apply
        device_flat=args.optimizer.device_flat,
        flat_opt_factory=(
            build_flat_opt_factory(args)
            if args.optimizer.flat_apply else None
        ),
        # swarm checkpointing (--checkpoint.*): sharded state serving +
        # catalog announcements + multi-peer restore, blob as fallback
        **checkpoint_kwargs(args, public_key),
        min_refresh_period=args.averager.min_refresh_period,
        max_refresh_period=args.averager.max_refresh_period,
        default_refresh_period=args.averager.default_refresh_period,
        expected_drift_peers=args.averager.expected_drift_peers,
        expected_drift_rate=args.averager.expected_drift_rate,
        performance_ema_alpha=args.averager.performance_ema_alpha,
        client_mode=args.dht.client_mode,
        relay=args.dht.relay or None,
        listen_port=args.averager.listen_port,
        advertised_host=args.dht.advertised_host or None,
        allow_state_sharing=args.optimizer.allow_state_sharing,
        mesh=mesh,
        opt_state_sharding=opt_sharding,
        param_sharding=param_sharding,
        authorizer=authorizer,
        authority_public_key=authority_public_key,
        verbose=True,
    )
    # catch up with the collaboration before training (:124-128)
    # disk-resume seeds the collaborative counter; a DEEPER live
    # collaboration below still wins — only_if_newer guards the reverse
    # race (a fresh partner that advanced the counter while we compiled
    # must not beat the resumed checkpoint)
    opt.local_step = max(opt.local_step, resumed_local_step)
    # only_if_newer ONLY when a checkpoint was actually restored: a fresh
    # cold-start peer must still adopt a same-step provider's params so
    # simultaneously-starting replicas begin identical
    state = opt.load_state_from_peers(
        state, only_if_newer=resumed_local_step > 0
    )
    if mesh is not None:
        # commit state onto the mesh once — otherwise accumulate's
        # replicated in_shardings would re-broadcast the full params from
        # the default device on every micro-batch until the first global step
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(mesh, P())
        state = state.replace(
            step=jax.device_put(state.step, repl),
            params=jax.device_put(state.params, param_sharding or repl),
            opt_state=jax.device_put(
                state.opt_state, opt_sharding or repl
            ),
        )
    # share a pre-training snapshot: partners that miss the first rounds
    # (slow hosts still compiling) must find a state provider immediately
    opt.seed_state_sharing(state)

    loss_fn = build_loss_fn(model)
    accumulate = make_accumulate_step(
        loss_fn,
        mesh=mesh,
        # sequence-parallel layout: shard batch seq dims over the mesh's
        # "seq" axis so ring attention sees its expected layout with zero
        # per-layer relayout (ADVICE r2: activations were full-S per device)
        seq_axis="seq" if (mesh is not None and "seq" in mesh.axis_names)
        else None,
        seq_length=seq,
        param_sharding=param_sharding,
    )
    grad_acc = zeros_like_grads(state.params)
    n_acc = jnp.zeros([], jnp.int32)

    batches = _make_batches(args, cfg, public_key, slice_batch)
    data_rng = jax.random.PRNGKey(peer_shuffle_seed(public_key))

    # the running loss stays ON DEVICE (a lazy sum) — a float() in the loop
    # would synchronize the host with the accumulate kernels and serialize
    # the input pipeline against XLA dispatch; the host reads one scalar per
    # GLOBAL step, right where the value is published
    loss_sum_dev = jnp.zeros([])
    mini_steps = 0
    boundary = 0
    last_saved_step = opt.local_step
    # telemetry: phase timers on the flagship path (vissl PerfStats
    # capability, vissl/utils/perf_stats.py:12-249). data_wait and the
    # boundary wall are host-honest; per-micro-batch device time is NOT
    # blocked on (that would serialize the async dispatch chain) — it shows
    # up in the boundary wall instead.
    perf = PerfStats()
    # step-phase flight recorder (telemetry/steps.py): per-boundary phase
    # decomposition + online MFU, published through the telemetry registry
    # (no-op while telemetry is disabled). The MFU gauge uses the same
    # analytic model-FLOPs formula and peak table as bench.py, so the
    # in-situ number is comparable to the BENCH_r* trajectory.
    from dedloc_tpu.data.mlm import max_predictions_for

    recorder = StepRecorder(
        telemetry=tele,
        model_tflops_per_sample=albert_tflops_per_sample(
            cfg, seq, max_predictions_for(seq)
        ),
        peak_tflops=chip_peak_tflops(),
    )
    train_log = (
        open(args.training.train_log_path, "a", buffering=1)
        if args.training.train_log_path
        else None
    )
    wall_start = time.perf_counter()
    try:
        while True:
            # one accumulation boundary = gradient_accumulation_steps
            # micro-batches; the flight recorder treats the boundary as ONE
            # step record (data_wait/h2d/fwd_bwd here, grad_flatten/
            # avg_wire/opt_apply/collab inside opt.step via the live
            # step-context)
            boundary_start = time.perf_counter()
            data_wait = 0.0
            with recorder.step(
                step=opt.local_step,
                samples=slice_batch * args.training.gradient_accumulation_steps,
            ) as srec:
                for _ in range(args.training.gradient_accumulation_steps):
                    t0 = time.perf_counter()
                    with steps.phase("data_wait"):
                        batch = drop_collator_keys(next(batches))
                    data_wait += time.perf_counter() - t0
                    if mesh is not None:
                        with steps.phase("h2d"):
                            batch = put_batch(
                                batch, mesh,
                                seq_axis=(
                                    "seq" if "seq" in mesh.axis_names
                                    else None
                                ),
                                seq_length=seq,
                            )
                    data_rng, sub = jax.random.split(data_rng)
                    with steps.phase("fwd_bwd"):
                        grad_acc, n_acc, metrics = accumulate(
                            state.params, grad_acc, n_acc, batch, sub
                        )
                    loss_sum_dev = loss_sum_dev + metrics["loss"]
                    mini_steps += 1
                if srec is not None:
                    # recording only: settle the async dispatch chain so
                    # fwd_bwd measures execution, not dispatch (the
                    # documented cost of in-situ attribution — opt.step
                    # device_gets these grads immediately after anyway)
                    with steps.phase("fwd_bwd"):
                        jax.block_until_ready((grad_acc, n_acc))
                # per-BOUNDARY stall so it is directly comparable to the
                # boundary wall time below
                perf.metric("data_wait").update(data_wait)

                samples = (
                    slice_batch * args.training.gradient_accumulation_steps
                )
                t0 = time.perf_counter()
                state, grad_acc, n_acc, stepped = opt.step(
                    state, grad_acc, n_acc, samples
                )
                if srec is not None:
                    srec.attrs["stepped"] = stepped
                # most boundaries are a cheap DHT progress report; the
                # averaging round only happens when the collaboration steps
                # — keep the two in separate metrics or the round cost is
                # diluted ~targetN x
                perf.metric(
                    "allreduce" if stepped else "collab_report"
                ).update(time.perf_counter() - t0)
                perf.metric("boundary").update(
                    time.perf_counter() - boundary_start
                )
            if stepped:
                loss_sum = float(loss_sum_dev)  # the one sync per global step
                loss_sum_dev = jnp.zeros([])
                # advertise the loss for the trunk-health gate — free here,
                # the scalar is already on the host
                opt.report_loss(loss_sum / max(mini_steps, 1))
                sps = float(opt.performance_ema.samples_per_second)
                publish_metrics(
                    dht,
                    args.dht.experiment_prefix,
                    public_key,
                    LocalMetrics(
                        step=opt.local_step,
                        samples_per_second=sps,
                        samples_accumulated=samples,
                        loss=loss_sum,
                        mini_steps=mini_steps,
                        step_time_ms=perf.metric("boundary").recent_mean * 1e3,
                        data_wait_ms=perf.metric("data_wait").recent_mean * 1e3,
                        allreduce_ms=perf.metric("allreduce").recent_mean * 1e3,
                        hbm_bytes=_hbm_bytes_in_use(),
                        # throttled counter snapshot for the coordinator's
                        # swarm-health aggregation (refreshed at most once
                        # per period; stale-but-present between refreshes)
                        telemetry=(
                            tele.maybe_snapshot(args.telemetry.snapshot_period)
                            if tele is not None
                            else None
                        ),
                        # advertised RPC endpoint: lets the coordinator
                        # resolve OTHER peers' link destinations to this
                        # peer's label in the swarm topology fold
                        endpoint=(
                            endpoint_key(opt.averager.endpoint)
                            if tele is not None
                            and opt.averager.endpoint is not None
                            else None
                        ),
                    ),
                    expiration=args.optimizer.statistics_expiration,
                )
                logger.info(
                    f"global step {opt.local_step}: loss "
                    f"{loss_sum / max(mini_steps, 1):.4f}"
                )
                if train_log is not None:
                    train_log.write(
                        json.dumps(
                            {
                                "wall_s": time.perf_counter() - wall_start,
                                "step": opt.local_step,
                                "loss": loss_sum / max(mini_steps, 1),
                                "samples_per_second": sps,
                                "samples": samples,
                                "boundary_ms": perf.metric(
                                    "boundary"
                                ).recent_mean
                                * 1e3,
                                "data_wait_ms": perf.metric(
                                    "data_wait"
                                ).recent_mean
                                * 1e3,
                                "allreduce_ms": perf.metric(
                                    "allreduce"
                                ).recent_mean
                                * 1e3,
                                # jit↔host seam breakdown (SURVEY §7b):
                                # grads device_get / apply / async backup
                                # list() snapshots atomically under the GIL —
                                # the backup thread may insert its key mid-step
                                "seam_ms": {
                                    k: round(v, 2)
                                    for k, v in list(opt.seam_ms.items())
                                },
                            }
                        )
                        + "\n"
                    )
                if (
                    args.training.log_perf_steps
                    and opt.local_step % args.training.log_perf_steps == 0
                ):
                    logger.info("perf phases:\n" + perf.report_str())
                mini_steps = 0
                if (
                    args.training.save_steps
                    and opt.local_step - last_saved_step
                    >= args.training.save_steps
                ):
                    # cadence by DISTANCE, not divisibility: a collaborative
                    # local_step can jump over exact multiples (catch-ups
                    # adopt the global counter), and a modulo check then
                    # never fires again for the rest of the run
                    _save(args, state, opt.local_step)
                    last_saved_step = opt.local_step

            boundary += 1
            if (
                args.training.max_local_steps
                and boundary >= args.training.max_local_steps
            ):
                logger.info(f"reached max_local_steps={boundary}; stopping")
                break
    finally:
        if train_log is not None:
            train_log.close()
        tele_close()
        opt.shutdown()
        dht.shutdown()
    return state


def _hbm_bytes_in_use() -> Optional[int]:
    """Device bytes_in_use via PJRT memory_stats (None off-TPU/unsupported)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            return int(stats.get("bytes_in_use", 0)) or None
    except Exception:  # noqa: BLE001 — telemetry must never kill training
        pass
    return None


def _save(args: CollaborationArguments, state: TrainState, step: int) -> None:
    from dedloc_tpu.collaborative.optimizer import _tree_to_named

    host = jax.device_get((state.params, state.opt_state))
    save_checkpoint(
        args.training.output_dir,
        step,
        _tree_to_named(host),
        metadata={"step": int(state.step), "local_step": step},
        save_total_limit=args.training.save_total_limit,
    )


def _named_to_tree_pair(named, template):
    from dedloc_tpu.collaborative.optimizer import _named_to_tree

    return _named_to_tree(named, template)


def _make_batches(
    args: CollaborationArguments, cfg, public_key: bytes,
    slice_batch: Optional[int] = None,
):
    """Synthetic fixture by default; a tokenized-on-disk dataset when
    ``dataset_path`` is set (tokenize_wikitext103 output layout)."""
    seed = peer_shuffle_seed(public_key)  # per-peer independent shuffling
    batch_size = slice_batch or args.training.per_device_batch_size
    if args.training.streaming_files:
        # sahajbert-style streaming mode (dataset_streaming.py capability):
        # weighted lazy mix + per-peer shuffle buffer + on-the-fly tokenize
        from dedloc_tpu.data.mlm import SpecialTokens, max_predictions_for
        from dedloc_tpu.data.streaming import (
            make_text_source,
            prefetch,
            split_sentences,
            streaming_mlm_batches,
        )
        from dedloc_tpu.data.tokenizer import load_fast_tokenizer

        tok = load_fast_tokenizer(args.training.tokenizer_path)
        if tok.vocab_size > cfg.vocab_size:
            # fail fast: ids past the embedding table would be silently
            # clamped by XLA's gather, corrupting training without an error
            raise ValueError(
                f"tokenizer vocab ({tok.vocab_size}) exceeds the model's "
                f"vocab_size ({cfg.vocab_size}); retrain the tokenizer or "
                "use a larger model vocab"
            )
        tokens = SpecialTokens(
            cls_id=tok.cls_id, sep_id=tok.sep_id, pad_id=tok.pad_id,
            mask_id=tok.mask_id, vocab_size=tok.vocab_size,
        )
        weights = args.training.streaming_weights or (
            [1.0] * len(args.training.streaming_files)
        )
        seq = min(args.training.seq_length, cfg.max_position_embeddings)
        # http(s):// specs stream remotely with retry/resume; the bounded
        # prefetch overlaps network/tokenization with the training step
        return prefetch(streaming_mlm_batches(
            [make_text_source(p) for p in args.training.streaming_files],
            weights,
            lambda doc: [
                tok.encode_ids(s, add_special_tokens=False)
                for s in split_sentences(doc)
            ],
            tokens,
            batch_size,
            seq,
            seed,
            buffer_size=args.training.streaming_buffer_size,
            max_predictions=max_predictions_for(seq),
        ), size=8)
    if not args.training.dataset_path:
        return synthetic_mlm_batches(
            cfg,
            batch_size,
            args.training.seq_length,
            seed,
        )
    from dedloc_tpu.data.disk import tokenized_dataset_batches

    return tokenized_dataset_batches(
        args.training.dataset_path,
        cfg,
        batch_size,
        args.training.seq_length,
        seed,
    )


def main(argv=None) -> None:
    run_trainer(parse_config(CollaborationArguments, argv))


if __name__ == "__main__":
    main()
