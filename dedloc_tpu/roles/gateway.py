"""Serving gateway: the swarm-facing front door for MoE inference.

A gateway joins the DHT like any peer, keeps an ``ExpertRouter`` warm
against the ``{prefix}_experts`` directory, and exposes one RPC —
``gateway.infer`` — that gates a token batch locally (top-1 Switch
routing over shipped router weights) and fans the per-expert groups out
to the hosting peers, combining gate-weighted outputs with the residual
fall-through for anything the swarm could not serve in time. It is the
deployment shape of ROADMAP item 1: the training swarm doubling as a
serving fleet, fronted by as many stateless gateways as traffic needs.

Run: ``python -m dedloc_tpu.roles.gateway --dht.initial_peers host:port
--serving.request_deadline 2.0`` (all ``--serving.*`` knobs in
core/config.py; routing policy in docs/serving.md).
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from dedloc_tpu.core.config import CollaborationArguments, parse_config
from dedloc_tpu.core.serialization import (
    CompressionType,
    deserialize_array,
    serialize_array,
)
from dedloc_tpu.roles.common import build_dht, force_cpu_if_requested
from dedloc_tpu.serving.router import ExpertRouter, RouterPolicy
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def policy_from_args(args: CollaborationArguments) -> RouterPolicy:
    """--serving.* flags -> the router's dispatch policy (ONE resolution
    point, so role and tests cannot drift)."""
    s = args.serving
    return RouterPolicy(
        deadline_s=float(s.request_deadline),
        attempt_timeout_s=float(s.attempt_timeout),
        retries=int(s.retries),
        backoff_s=float(s.backoff),
        hedge_after_s=float(s.hedge_after),
        refresh_period_s=float(s.refresh_period),
    )


class GatewayService:
    """The embeddable gateway: an ``ExpertRouter`` plus the
    ``gateway.infer`` RPC surface, attachable to any DHTNode (the role
    below and the simulator's serving scenario both use it)."""

    def __init__(
        self,
        node,
        prefix: str,
        policy: Optional[RouterPolicy] = None,
        router_params: Optional[np.ndarray] = None,
        version: Optional[int] = None,
        telemetry_registry=None,
    ):
        self.router = ExpertRouter(
            node, prefix, policy=policy, telemetry_registry=telemetry_registry
        )
        self.router_params = router_params
        self.version = version
        node.server.register("gateway.infer", self._rpc_infer)

    async def _rpc_infer(self, peer, args):
        """One inference request: gate + swarm fan-out + combine."""
        if self.router_params is None:
            raise RuntimeError("gateway has no router weights loaded")
        x = deserialize_array(args["tokens"])
        request_id = str(args.get("request_id") or "req")
        y, stats = await self.router.infer(
            self.router_params, x, request_id, version=self.version
        )
        return {
            "data": serialize_array(
                np.ascontiguousarray(y, dtype=np.float32),
                CompressionType.NONE,
            ),
            **stats,
        }


def run_gateway(
    args: CollaborationArguments,
    router_params: Optional[np.ndarray] = None,
    poll_period: float = 5.0,
    max_iterations: int = 0,
) -> None:
    """Role entry point: DHT (full peer — the gateway must be dialable to
    host ``gateway.infer``), router, refresh loop."""
    force_cpu_if_requested()
    dht, _ = build_dht(args, client_mode=False)
    prefix = args.dht.experiment_prefix
    policy = policy_from_args(args)
    service_box = {}

    async def _attach(node):
        service_box["service"] = GatewayService(
            node, prefix, policy=policy, router_params=router_params,
        )
        await service_box["service"].router.refresh(force=True)
        return service_box["service"].router.known_experts()

    known = dht.run_coroutine(lambda node: _attach(node))
    logger.info(
        f"gateway up at {dht.get_visible_address()} "
        f"(experts known at boot: {known})"
    )
    iterations = 0
    try:
        while True:
            known = dht.run_coroutine(
                lambda node: _refresh(service_box["service"].router)
            )
            logger.info(f"gateway directory: {len(known)} experts live")
            iterations += 1
            if max_iterations and iterations >= max_iterations:
                break
            time.sleep(poll_period)
    finally:
        dht.shutdown()


async def _refresh(router: ExpertRouter):
    await router.refresh(force=True)
    return router.known_experts()


def main(argv=None) -> None:
    run_gateway(parse_config(CollaborationArguments, argv))


if __name__ == "__main__":
    main()
