"""Cloud fleet provisioning: the real-cloud counterpart of ``roles/fleet.py``.

Capability parity with the reference's AWS fleet notebook
(albert/AWS_runner.ipynb): a coordinator VM + auxiliary CPU peers +
preemptible accelerator workers, per-peer bandwidth shaping (the notebook
throttles with wondershaper in each instance's user-data; here the startup
script uses ``tc``), and a respawn supervisor that recreates terminated spot
instances (the notebook's last cell) — but as a scriptable, provider-seamed
module instead of a notebook:

- ``CloudFleetSpec`` describes the fleet (counts, machine/accelerator types,
  bandwidth tiers, the run's DHT/auth coordinates).
- ``Provider`` is the seam: ``GcloudTPUProvider`` shells out to ``gcloud``
  (TPU VMs for workers, GCE for coordinator/aux; ``dry_run=True`` prints the
  exact commands without executing — the tested path in CI, and a copy-paste
  runbook for operators). Other clouds implement the same three methods.
- ``run_cloud_fleet`` is the supervisor: provision everything, then poll and
  recreate missing SPOT workers until stopped. Workers carry their
  config in the startup script, so a respawned instance rejoins the DHT and
  pulls state from peers — elasticity needs nothing cloud-side.

Every worker's startup script launches the one-command join path
(``python -m dedloc_tpu.join``), so the fleet and the volunteer flows are
the same code.
"""
from __future__ import annotations

import shlex
import subprocess
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Sequence

from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class CloudFleetSpec:
    """What to provision (AWS_runner.ipynb cell-2 capability)."""

    experiment_prefix: str = "dedloc"
    coordinator_machine: str = "n2-standard-8"  # r5.large-class
    num_workers: int = 16
    worker_accelerator: str = "v5litepod-1"  # g4dn-class: one chip per peer
    num_aux: int = 4
    aux_machine: str = "n2-standard-4"
    zone: str = "us-central2-b"
    # per-worker egress shaping in Mbit/s, cycled (notebook tiers 200/100/50
    # via wondershaper); 0 = unshaped
    bandwidth_tiers: Sequence[float] = (200.0, 100.0, 100.0, 50.0)
    spot: bool = True  # preemptible workers (spot semantics)
    coordinator_port: int = 31337
    # gated runs: "user:cred,..." hosted by the coordinator's AuthService
    auth_allowlist: str = ""
    # gated runs: the fleet's OWN peers need credentials too — otherwise
    # signed volunteer leaders reject the fleet's unsigned joins and
    # matchmaking partitions into signed/unsigned subsets. A per-fleet
    # credential is auto-generated (or supplied) and appended to the
    # coordinator's allowlist; worker/aux startup scripts join with it.
    # NOTE: supply fleet_credential explicitly when re-running a supervisor
    # against an already-provisioned coordinator — a fresh auto-generated
    # value is unknown to the live coordinator's allowlist, so respawned
    # workers would be rejected.
    fleet_username: str = "fleet"
    fleet_credential: str = ""
    # software setup prefix (image/venv activation) prepended to every
    # startup script; deployments point this at their image's environment
    setup_lines: Sequence[str] = ("set -e",)
    repo_dir: str = "/opt/dedloc_tpu"

    def __post_init__(self) -> None:
        if self.auth_allowlist:
            operators = {
                pair.split(":", 1)[0]
                for pair in self.auth_allowlist.split(",") if pair
            }
            if self.fleet_username in operators:
                # the coordinator lowers the allowlist into a dict, so the
                # appended fleet entry would silently override the
                # operator's user of the same name (locking those
                # volunteers out) — refuse the ambiguity at spec time
                raise ValueError(
                    f"auth_allowlist already contains user "
                    f"{self.fleet_username!r}; rename it or set "
                    f"fleet_username to something else"
                )
            if not self.fleet_credential:
                import secrets

                self.fleet_credential = secrets.token_hex(16)

    @property
    def full_allowlist(self) -> str:
        """Operator allowlist plus the fleet's own credential."""
        if not self.auth_allowlist:
            return ""
        return (
            f"{self.auth_allowlist},"
            f"{self.fleet_username}:{self.fleet_credential}"
        )


class Provider(Protocol):
    """The cloud seam: three methods cover provision/poll/replace."""

    def create(self, name: str, kind: str, machine: str,
               startup_script: str, spot: bool) -> None: ...

    def list_alive(self) -> List[str]: ...

    def delete(self, name: str, kind: str = "tpu") -> None: ...


def _shape_bandwidth_lines(mbps: float) -> List[str]:
    """tc-based egress shaping (the wondershaper capability of the
    notebook's worker user-data)."""
    if not mbps:
        return []
    rate = int(mbps)
    # tbf needs burst >= rate/HZ or it caps throughput far below the
    # nominal rate (HZ can be 100 => 200mbit needs ~250 KB); scale with rate
    burst_kbit = max(1600, rate * 10)
    return [
        "IFACE=$(ip route show default | awk '{print $5; exit}')",
        f"tc qdisc replace dev $IFACE root tbf rate {rate}mbit "
        f"burst {burst_kbit}kbit latency 400ms",
    ]


def coordinator_startup(spec: CloudFleetSpec) -> str:
    lines = list(spec.setup_lines) + [
        f"cd {spec.repo_dir}",
        " ".join([
            "python -m dedloc_tpu.roles.coordinator",
            f"--dht.experiment_prefix {shlex.quote(spec.experiment_prefix)}",
            f"--dht.listen_port {spec.coordinator_port}",
            "--coordinator.upload_interval 3600",
        ] + (
            [f"--coordinator.auth_allowlist {shlex.quote(spec.full_allowlist)}"]
            if spec.auth_allowlist else []
        )),
    ]
    return "\n".join(lines)


def worker_startup(spec: CloudFleetSpec, idx: int,
                   coordinator_host: str) -> str:
    tier = (
        spec.bandwidth_tiers[idx % len(spec.bandwidth_tiers)]
        if spec.bandwidth_tiers else 0.0
    )
    lines = list(spec.setup_lines)
    lines += _shape_bandwidth_lines(tier)
    lines += [
        f"cd {spec.repo_dir}",
        " ".join([
            "python -m dedloc_tpu.join",
            f"--initial_peers {coordinator_host}:{spec.coordinator_port}",
            f"--experiment_prefix {shlex.quote(spec.experiment_prefix)}",
        ] + (
            # gated fleet: join with the fleet credential (the AuthService
            # rides the coordinator's DHT port, join.py's default endpoint)
            [f"--username {shlex.quote(spec.fleet_username)}",
             f"--credential {shlex.quote(spec.fleet_credential)}"]
            if spec.auth_allowlist else []
        ) + ([f"--bandwidth {tier}", f"--training.seed {idx}"]
             if tier else [f"--training.seed {idx}"])),
    ]
    return "\n".join(lines)


def aux_startup(spec: CloudFleetSpec, coordinator_host: str) -> str:
    lines = list(spec.setup_lines) + [
        f"cd {spec.repo_dir}",
        " ".join([
            "python -m dedloc_tpu.roles.aux",
            "--dht.initial_peers "
            f"{coordinator_host}:{spec.coordinator_port}",
            f"--dht.experiment_prefix {shlex.quote(spec.experiment_prefix)}",
        ] + (
            [f"--auth.username {shlex.quote(spec.fleet_username)}",
             f"--auth.credential {shlex.quote(spec.fleet_credential)}"]
            if spec.auth_allowlist else []
        )),
    ]
    return "\n".join(lines)


class _CliProvider:
    """Shared scaffolding for CLI-backed providers: command recording,
    dry-run bookkeeping, and startup-script temp files.

    ``dry_run=True`` records the exact command lines instead of executing —
    CI asserts them, operators copy-paste them (script temp files are KEPT
    in dry-run so the recorded ``file://`` references stay usable)."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.commands: List[str] = []
        self.startup_scripts: Dict[str, str] = {}
        self._dry_alive: List[str] = []

    def _run(self, argv: List[str]) -> str:
        self.commands.append(" ".join(argv))
        if self.dry_run:
            return ""
        out = subprocess.run(
            argv, capture_output=True, text=True, timeout=600
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"{argv[0]} failed ({out.returncode}): {out.stderr.strip()}"
            )
        return out.stdout

    def _with_script_file(self, name: str, content: str, fn) -> None:
        """Write ``content`` to a temp file, call ``fn(path)``, clean up —
        except in dry-run, where the file must outlive the recorded
        command for operators to replay it."""
        import os as _os
        import tempfile

        script_file = tempfile.NamedTemporaryFile(
            "w", prefix=f"startup-{name}-", suffix=".sh", delete=False
        )
        script_file.write(content)
        script_file.close()
        try:
            fn(script_file.name)
        finally:
            if not self.dry_run:
                _os.unlink(script_file.name)


class GcloudTPUProvider(_CliProvider):
    """gcloud-backed provider: TPU VMs for workers, GCE for the rest."""

    def __init__(self, zone: str, dry_run: bool = False):
        super().__init__(dry_run)
        self.zone = zone

    def create(self, name: str, kind: str, machine: str,
               startup_script: str, spot: bool) -> None:
        # the script goes through --metadata-from-file: an inline
        # --metadata value would need quoting the guest shell must NOT see
        # (argv exec adds no shell layer to strip it) and commas inside the
        # script would split metadata entries
        self.startup_scripts[name] = startup_script
        self._with_script_file(
            name, startup_script,
            lambda path: self._create_with_script(name, kind, machine, path,
                                                  spot),
        )
        if self.dry_run:
            self._dry_alive.append(name)

    def _create_with_script(self, name: str, kind: str, machine: str,
                            script_path: str, spot: bool) -> None:
        if kind == "tpu":
            argv = [
                "gcloud", "compute", "tpus", "tpu-vm", "create", name,
                f"--zone={self.zone}",
                f"--accelerator-type={machine}",
                "--version=tpu-ubuntu2204-base",
                f"--metadata-from-file=startup-script={script_path}",
            ]
            if spot:
                argv.append("--spot")
        else:
            argv = [
                "gcloud", "compute", "instances", "create", name,
                f"--zone={self.zone}",
                f"--machine-type={machine}",
                f"--metadata-from-file=startup-script={script_path}",
            ]
            if spot:
                argv.append("--provisioning-model=SPOT")
        self._run(argv)

    def list_alive(self) -> List[str]:
        if self.dry_run:
            self.commands.append("gcloud compute tpus tpu-vm list ...")
            return list(self._dry_alive)
        out = self._run([
            "gcloud", "compute", "tpus", "tpu-vm", "list",
            f"--zone={self.zone}", "--format=value(name)",
        ])
        out2 = self._run([
            "gcloud", "compute", "instances", "list",
            f"--zones={self.zone}", "--format=value(name)",
        ])
        return [n for n in (out + "\n" + out2).splitlines() if n]

    def delete(self, name: str, kind: str = "tpu") -> None:
        if kind == "tpu":
            argv = ["gcloud", "compute", "tpus", "tpu-vm", "delete", name,
                    f"--zone={self.zone}", "--quiet"]
        else:
            argv = ["gcloud", "compute", "instances", "delete", name,
                    f"--zone={self.zone}", "--quiet"]
        self._run(argv)


class AwsEc2Provider(_CliProvider):
    """aws-cli-backed provider — the reference's actual cloud
    (albert/AWS_runner.ipynb: r5.large coordinator + g4dn spot workers +
    CPU aux, provisioned via boto3; here via the ``aws ec2`` CLI so the
    dry-run surface matches the gcloud driver's).

    ``kind`` maps onto instance types, not services: EC2 has no TPU-VM
    analogue, so "tpu" means "accelerated worker instance" (the notebook's
    g4dn class). Spot uses the notebook's one-time,
    terminate-on-interruption semantics — the respawn loop in
    ``run_cloud_fleet`` is what brings capacity back, exactly like the
    notebook's last cell. Instances are discovered by a fleet Name tag."""

    def __init__(self, region: str, ami: str = "AMI_ID",
                 key_name: str = "", security_group: str = "",
                 dry_run: bool = False):
        super().__init__(dry_run)
        self.region = region
        self.ami = ami
        self.key_name = key_name
        self.security_group = security_group

    def create(self, name: str, kind: str, machine: str,
               startup_script: str, spot: bool) -> None:
        self.startup_scripts[name] = startup_script
        # user-data rides in a file as the RAW script: file:// contents are
        # base64-encoded by the aws CLI itself, so pre-encoding would hand
        # cloud-init base64 text instead of an executable script
        self._with_script_file(
            name, startup_script, lambda path: self._run_create(
                name, machine, path, spot
            )
        )
        if self.dry_run:
            self._dry_alive.append(name)

    def _run_create(self, name: str, machine: str, script_path: str,
                    spot: bool) -> None:
        argv = [
            "aws", "ec2", "run-instances",
            f"--region={self.region}",
            f"--image-id={self.ami}",
            f"--instance-type={machine}",
            "--count=1",
            f"--user-data=file://{script_path}",
            "--tag-specifications",
            "ResourceType=instance,Tags=[{Key=Name,Value=%s}]" % name,
        ]
        if self.key_name:
            argv.append(f"--key-name={self.key_name}")
        if self.security_group:
            argv.append(f"--security-group-ids={self.security_group}")
        if spot:
            # the notebook's one-time spot with terminate-on-interruption:
            # a preempted worker is GONE and the supervisor respawns it
            argv += [
                "--instance-market-options",
                "MarketType=spot,SpotOptions={SpotInstanceType=one-time,"
                "InstanceInterruptionBehavior=terminate}",
            ]
        self._run(argv)

    def list_alive(self) -> List[str]:
        if self.dry_run:
            self.commands.append("aws ec2 describe-instances ...")
            return list(self._dry_alive)
        out = self._run([
            "aws", "ec2", "describe-instances",
            f"--region={self.region}",
            "--filters", "Name=instance-state-name,Values=pending,running",
            "--query",
            "Reservations[].Instances[].Tags[?Key=='Name'].Value[]",
            "--output", "text",
        ])
        return [n for n in out.split() if n]

    def delete(self, name: str, kind: str = "tpu") -> None:
        if not self.dry_run:
            ids = self._run([
                "aws", "ec2", "describe-instances",
                f"--region={self.region}",
                "--filters", f"Name=tag:Name,Values={name}",
                "Name=instance-state-name,Values=pending,running",
                "--query", "Reservations[].Instances[].InstanceId",
                "--output", "text",
            ]).split()
        else:
            ids = [f"i-{name}"]
        if ids:
            self._run([
                "aws", "ec2", "terminate-instances",
                f"--region={self.region}", "--instance-ids", *ids,
            ])


def run_cloud_fleet(
    spec: CloudFleetSpec,
    provider: Provider,
    coordinator_host: str = "COORDINATOR_IP",
    poll_interval: float = 60.0,
    max_cycles: int = 0,
) -> Dict[str, int]:
    """Provision the fleet, then supervise: recreate missing SPOT workers
    (the notebook's respawn loop). Returns {"respawned": N} when bounded by
    ``max_cycles`` (tests); runs until interrupted otherwise."""
    prefix = spec.experiment_prefix
    provider.create(
        f"{prefix}-coordinator", "vm", spec.coordinator_machine,
        coordinator_startup(spec), spot=False,
    )
    worker_names = [f"{prefix}-worker-{i}" for i in range(spec.num_workers)]
    for i, name in enumerate(worker_names):
        provider.create(
            name, "tpu", spec.worker_accelerator,
            worker_startup(spec, i, coordinator_host), spot=spec.spot,
        )
    for i in range(spec.num_aux):
        provider.create(
            f"{prefix}-aux-{i}", "vm", spec.aux_machine,
            aux_startup(spec, coordinator_host), spot=False,
        )

    respawned = 0
    cycles = 0
    while True:
        cycles += 1
        if max_cycles and cycles > max_cycles:
            break
        alive = set(provider.list_alive())
        for i, name in enumerate(worker_names):
            if name not in alive:
                logger.info(f"worker {name} preempted; respawning")
                provider.create(
                    name, "tpu", spec.worker_accelerator,
                    worker_startup(spec, i, coordinator_host),
                    spot=spec.spot,
                )
                respawned += 1
        if max_cycles == 0 or cycles < max_cycles:
            time.sleep(poll_interval)
    return {"respawned": respawned}
