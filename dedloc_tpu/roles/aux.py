"""Auxiliary peer: donates bandwidth to averaging, contributes no gradients.

Capability parity with albert/run_aux.py:206-263 — a CPU-only peer that
joins averaging groups with zero weight every 0.5 s
(``CollaborativeOptimizer(auxiliary=True, allow_state_sharing=False)`` +
``step_aux()`` loop). It hosts bandwidth-weighted spans during the group
reduce-scatter, which speeds up rounds for slow GPU/TPU peers.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from dedloc_tpu.collaborative.optimizer import (
    CollaborativeOptimizer,
    _tree_to_named,
)
from dedloc_tpu.core.config import CollaborationArguments, parse_config
from dedloc_tpu.roles.common import (
    build_dht,
    build_model,
    build_optimizer,
    force_cpu_if_requested,
)
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def run_aux(
    args: CollaborationArguments,
    poll_interval: float = 0.5,
    max_iterations: int = 0,
) -> int:
    """Returns the number of averaging rounds joined (for tests)."""
    force_cpu_if_requested()
    # aux needs only gradient SHAPES, never runs the model — but they must
    # match the trainers' exactly, so apply the same config overrides
    cfg, model = build_model(
        args.training.model_size,
        args.training.remat_policy,
        args.training.attention_impl,
        args.training.vocab_size,
    )
    seq = min(args.training.seq_length, cfg.max_position_embeddings)
    params = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, seq), jnp.int32))["params"],
        jax.random.PRNGKey(0),
    )
    template = {
        k: np.zeros(v.shape, np.float32)
        for k, v in _tree_to_named(params).items()
    }

    tx = build_optimizer(args)
    dht, _public_key = build_dht(args)
    logger.info(f"aux peer DHT listening on {dht.port}")
    opt = CollaborativeOptimizer(
        tx,
        dht,
        prefix=args.dht.experiment_prefix,
        target_batch_size=args.optimizer.target_batch_size,
        batch_size_lead=args.optimizer.batch_size_lead,
        bandwidth=args.averager.bandwidth,
        compression=args.averager.compression,
        target_group_size=args.averager.target_group_size,
        averaging_expiration=args.averager.averaging_expiration,
        averaging_timeout=args.averager.averaging_timeout,
        auxiliary=True,
        advertised_host=args.dht.advertised_host or None,
        allow_state_sharing=False,
        verbose=True,
    )
    rounds = iterations = 0
    try:
        while True:
            if opt.step_aux(template):
                rounds += 1
                logger.info(f"joined averaging round (total {rounds})")
            iterations += 1
            if max_iterations and iterations >= max_iterations:
                break
            time.sleep(poll_interval)
    finally:
        opt.shutdown()
        dht.shutdown()
    return rounds


def main(argv=None) -> None:
    run_aux(parse_config(CollaborationArguments, argv))


if __name__ == "__main__":
    main()
