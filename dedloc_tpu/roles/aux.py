"""Auxiliary peer: donates bandwidth to averaging, contributes no gradients.

Capability parity with albert/run_aux.py:206-263 — a CPU-only peer that
joins averaging groups with zero weight every 0.5 s
(``CollaborativeOptimizer(auxiliary=True, allow_state_sharing=False)`` +
``step_aux()`` loop). It hosts bandwidth-weighted spans during the group
reduce-scatter, which speeds up rounds for slow GPU/TPU peers.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from dedloc_tpu.collaborative.optimizer import (
    CollaborativeOptimizer,
    _tree_to_named,
)
from dedloc_tpu.core.config import CollaborationArguments, parse_config
from dedloc_tpu.roles.common import (
    build_authorizer,
    build_dht,
    build_model,
    build_optimizer,
    force_cpu_if_requested,
    single_device_attention_impl,
)
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _local_template(args: CollaborationArguments):
    """Gradient shapes from the local model config — the offline fallback
    when no state provider is live yet (shape-only)."""
    impl = single_device_attention_impl(args.training.attention_impl)
    cfg, model = build_model(
        args.training.model_size,
        args.training.remat_policy,
        impl,
        args.training.vocab_size,
    )
    seq = min(args.training.seq_length, cfg.max_position_embeddings)
    params = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, seq), jnp.int32))["params"],
        jax.random.PRNGKey(0),
    )
    return {
        k: np.zeros(v.shape, np.float32)
        for k, v in _tree_to_named(params).items()
    }


def run_aux(
    args: CollaborationArguments,
    poll_interval: float = 0.5,
    max_iterations: int = 0,
) -> int:
    """Returns the number of averaging rounds joined (for tests).

    The gradient-shape template SELF-BOOTSTRAPS from a live state provider
    (run_aux.py:243-263 capability: the aux learns the model from the
    collaboration, not from the caller); the local model config is only the
    fallback while nobody shares state yet."""
    force_cpu_if_requested()
    # gated runs: aux peers need envelopes too (leaders reject unsigned
    # joins; gated joiners reject unsigned leader replies)
    authorizer, authority_public_key = build_authorizer(args)
    tx = build_optimizer(args)
    # gated: record-sign with the token key, so the signed subkey digests
    # to this peer's verified identity (ledger binding, roles/common.py)
    dht, _public_key = build_dht(
        args,
        private_key=(
            authorizer.local_private_key if authorizer is not None else None
        ),
    )
    logger.info(f"aux peer DHT listening on {dht.port}")
    # swarm telemetry (--telemetry.*, docs/observability.md): an aux donor's
    # join failures / allreduce stragglers are exactly the events operators
    # need when a donor silently loses every matchmaking race
    from dedloc_tpu.roles.common import configure_role_telemetry

    _tele, tele_close = configure_role_telemetry(args, _public_key)
    opt = CollaborativeOptimizer(
        tx,
        dht,
        prefix=args.dht.experiment_prefix,
        target_batch_size=args.optimizer.target_batch_size,
        batch_size_lead=args.optimizer.batch_size_lead,
        bandwidth=args.averager.bandwidth,
        compression=args.averager.compression,
        chunk_size=args.averager.chunk_size,
        # the whole swarm must share one hierarchy: an aux donor without
        # the plan would advertise into the flat scope nobody else forms
        topology_plan=args.averager.topology_plan or None,
        # and it must follow live re-plans for the same reason (unless the
        # operator pinned a manual plan — pin = opt-out, docs/fleet.md)
        plan_follow=(
            args.averager.plan_follow and not args.averager.topology_plan
        ),
        plan_refresh_period=args.averager.plan_refresh_period,
        target_group_size=args.averager.target_group_size,
        averaging_expiration=args.averager.averaging_expiration,
        averaging_timeout=args.averager.averaging_timeout,
        listen_port=args.averager.listen_port,
        auxiliary=True,
        advertised_host=args.dht.advertised_host or None,
        allow_state_sharing=False,
        authorizer=authorizer,
        authority_public_key=authority_public_key,
        verbose=True,
    )
    rounds = iterations = 0
    template = fallback = None
    try:
        while True:
            if template is None:
                # self-bootstrap keeps retrying until a provider appears —
                # a late-started aux needs no model knowledge at all
                template = opt.bootstrap_aux_template(timeout=10.0)
                if template is not None:
                    logger.info(
                        f"bootstrapped gradient template from a state "
                        f"provider ({len(template)} tensors)"
                    )
            current = template
            if current is None:
                # nobody shares state yet: derive shapes locally so the
                # collaboration's very first rounds still get bandwidth
                if fallback is None:
                    fallback = _local_template(args)
                    logger.info(
                        "no state provider yet; using local model shapes"
                    )
                current = fallback
            if opt.step_aux(current):
                rounds += 1
                logger.info(f"joined averaging round (total {rounds})")
            iterations += 1
            if max_iterations and iterations >= max_iterations:
                break
            time.sleep(poll_interval)
    finally:
        tele_close()
        opt.shutdown()
        dht.shutdown()
    return rounds


def main(argv=None) -> None:
    run_aux(parse_config(CollaborationArguments, argv))


if __name__ == "__main__":
    main()
