"""Coordinator ("first peer"): DHT root + metrics aggregation + checkpoints.

Capability parity with albert/run_first_peer.py:24-218: starts the DHT other
peers bootstrap from, never trains; every ``refresh_period`` seconds it
aggregates the signed per-peer metrics from the DHT (alive peers, summed
throughput, loss = Σloss/Σmini_steps) and logs them (wandb when available,
always JSONL — the TPU build's durable equivalent of the wandb dashboard);
periodically pulls the newest collaboration state from peers and writes a
local checkpoint (the reference pushes to the HF hub via git,
run_first_peer.py:123-147 — the upload seam is ``upload_fn``).
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from dedloc_tpu.averaging.averager import DecentralizedAverager
from dedloc_tpu.collaborative.metrics import aggregate_metrics, fetch_metrics
from dedloc_tpu.core.config import CollaborationArguments, parse_config
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.roles.common import build_dht, force_cpu_if_requested
from dedloc_tpu.telemetry import build_swarm_health
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.utils.checkpoint import save_checkpoint
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class CoordinatorExtraArguments:
    """Reference: CoordinatorArguments (run_first_peer.py:24-57)."""

    refresh_period: float = 30.0
    save_checkpoint_step_interval: int = 5
    upload_interval: float = 0.0  # seconds; 0 disables state pulls
    metrics_log_path: str = "coordinator_metrics.jsonl"
    # hub publication (run_first_peer.py:123-147 capability): a git working
    # tree (optionally pushing to hub_git_remote) or a directory mirror
    hub_git_dir: str = ""
    hub_git_remote: str = ""
    hub_mirror_dir: str = ""
    # gated runs: "user:credential,user2:credential2" — hosts the token
    # AuthService on this coordinator's DHT server (the reference's hosted
    # auth endpoint, huggingface_auth.py:46-143); volunteers then join with
    # --auth.username/--auth.credential pointed at this coordinator
    auth_allowlist: str = ""


def run_coordinator(
    args: CollaborationArguments,
    extra: Optional[CoordinatorExtraArguments] = None,
    upload_fn: Optional[Callable[[str, int], None]] = None,
    max_iterations: int = 0,
) -> None:
    """``upload_fn(checkpoint_path, step)`` is the hub-publish seam
    (run_first_peer.py:123-147's git push); ``max_iterations`` bounds the
    loop for tests (0 = run forever)."""
    force_cpu_if_requested()
    extra = extra or CoordinatorExtraArguments()
    if upload_fn is None:
        from dedloc_tpu.utils.hub import build_upload_fn

        upload_fn = build_upload_fn(
            extra.hub_git_dir, extra.hub_git_remote, extra.hub_mirror_dir
        )
    dht, _public_key = build_dht(args)
    logger.info(f"coordinator DHT root listening on {dht.port}")
    # swarm telemetry (--telemetry.*): the coordinator's own counters —
    # notably metrics.malformed_records from fetch_metrics — need a registry
    # too, or they are silently discarded
    from dedloc_tpu.roles.common import configure_role_telemetry

    _tele, tele_close = configure_role_telemetry(args, _public_key)

    if extra.auth_allowlist:
        from dedloc_tpu.core.auth import AllowlistAuthServer, AuthService

        allow = dict(
            pair.split(":", 1) for pair in extra.auth_allowlist.split(",")
        )
        auth_server = AllowlistAuthServer(
            allow, coordinator_endpoint=dht.get_visible_address()
        )

        async def _attach(node):
            AuthService(node.server, auth_server)

        dht.run_coroutine(_attach)
        logger.info(
            f"auth service up ({len(allow)} allowlisted users); run is gated"
        )

    averager: Optional[DecentralizedAverager] = None
    if extra.upload_interval > 0:
        # listens for state only; contributes no gradients and no bandwidth
        averager = DecentralizedAverager(
            dht,
            args.dht.experiment_prefix,
            client_mode=True,
            allow_state_sharing=False,
            # state pulls prefer the multi-peer sharded path (and fall
            # back to the single-provider blob) like any joiner
            checkpoint_shard_size=args.checkpoint.shard_size,
            checkpoint_fetch_parallelism=args.checkpoint.fetch_parallelism,
            checkpoint_max_providers=args.checkpoint.providers,
        )

    wandb_run = _maybe_wandb(args)
    uploads = {"thread": None}  # per-coordinator upload state (NOT global:
    # tests run several coordinators in one process)
    current_step = -1
    last_upload = get_dht_time()
    iterations = 0
    try:
        while True:
            metrics = fetch_metrics(dht, args.dht.experiment_prefix)
            agg = aggregate_metrics(metrics)
            if agg is not None and agg["step"] > current_step:
                current_step = agg["step"]
                agg["time"] = get_dht_time()
                # swarm health (telemetry/health.py): per-peer retry/fault
                # counters off the signed metrics bus folded into straggler
                # attribution + retry rates — the durable "why was step N
                # slow" record next to the throughput aggregate
                health = build_swarm_health(metrics)
                if health is not None:
                    agg["swarm_health"] = health
                    if health["straggler"] is not None:
                        logger.warning(
                            f"step {agg['step']}: straggler "
                            f"{health['straggler']} is stalling the swarm"
                        )
                logger.info(
                    f"step {agg['step']}: {agg['alive_peers']} peers, "
                    f"{agg['samples_per_second']:.1f} samples/s, "
                    f"loss {agg['loss']:.4f}"
                )
                with open(extra.metrics_log_path, "a") as f:
                    f.write(json.dumps(agg) + "\n")
                if wandb_run is not None:
                    wandb_run.log(agg, step=agg["step"])

                if (
                    averager is not None
                    and extra.upload_interval > 0
                    and get_dht_time() - last_upload >= extra.upload_interval
                ):
                    _pull_and_save(
                        args, averager, current_step, upload_fn, uploads
                    )
                    last_upload = get_dht_time()

            iterations += 1
            if max_iterations and iterations >= max_iterations:
                break
            time.sleep(extra.refresh_period)
    finally:
        # let an in-flight hub push finish (it is bounded by the git
        # subprocess timeout): a push killed mid-flight can leave a stale
        # lock in the work tree, and the FINAL checkpoint of a run has no
        # next attempt to cover it
        t = uploads.get("thread")
        if t is not None and t.is_alive():
            logger.info("waiting for the in-flight hub upload to finish")
            t.join(timeout=330.0)
        if averager is not None:
            averager.shutdown()
        tele_close()
        dht.shutdown()


def _pull_and_save(args, averager, step, upload_fn, uploads) -> None:
    result = averager.load_state_from_peers()
    if result is None:
        logger.warning("no state providers yet; skipping checkpoint")
        return
    metadata, tree = result
    path = save_checkpoint(
        args.training.output_dir,
        step,
        tree,
        metadata=metadata,
        save_total_limit=args.training.save_total_limit,
    )
    logger.info(f"saved collaboration checkpoint {path}")
    # swarm checkpointing (--checkpoint.*): write the durable manifest +
    # content-addressed shards next to the legacy blob (shards unchanged
    # between steps are stored once), and drop the manifest into the
    # checkpoint dir so the hub upload below publishes it — a mirror
    # consumer can then verify shard integrity against the signed digest
    if getattr(args, "checkpoint", None) and args.checkpoint.shard_size > 0:
        from dedloc_tpu.checkpointing import save_sharded_checkpoint

        try:
            manifest = save_sharded_checkpoint(
                os.path.join(args.training.output_dir, "sharded"),
                tree,
                step,
                shard_size=args.checkpoint.shard_size,
                metadata=metadata,
                keep=args.training.save_total_limit,
            )
            with open(os.path.join(path, "manifest.bin"), "wb") as f:
                f.write(manifest.to_bytes())
            telemetry.inc("ckpt.manifests_written")
            telemetry.event(
                "ckpt.manifest_written", step=step,
                shards=manifest.num_shards, bytes=manifest.total_bytes,
            )
            logger.info(
                f"wrote sharded checkpoint manifest at step {step} "
                f"({manifest.num_shards} shards)"
            )
        except ValueError as e:
            # a tree that cannot roundtrip the fp32 layout stays blob-only
            logger.warning(f"sharded checkpoint skipped: {e}")
    if upload_fn is not None:
        # background thread (reference behavior, run_first_peer.py:139): a
        # slow push must not block metrics aggregation or checkpointing.
        # One upload in flight at a time — a new checkpoint while the
        # previous push still runs skips its upload (the next interval
        # covers it; the shutdown path joins the last one so the final
        # checkpoint is never abandoned).
        prev = uploads.get("thread")
        if prev is not None and prev.is_alive():
            logger.warning(
                f"previous hub upload still in flight; skipping step {step}"
            )
            return

        def _do_upload(path=path, step=step):
            try:
                upload_fn(path, step)
            except Exception as e:  # noqa: BLE001 — a hub blip must not
                # kill the coordinator; the git helper is also bounded by a
                # subprocess timeout so a stalled remote cannot wedge this
                # thread forever
                logger.warning(f"hub upload failed for step {step}: {e}")

        import threading

        uploads["thread"] = threading.Thread(target=_do_upload)
        uploads["thread"].start()


def _maybe_wandb(args: CollaborationArguments):
    if not args.wandb_project:
        return None
    try:
        import wandb  # type: ignore

        return wandb.init(project=args.wandb_project)
    except Exception as e:  # noqa: BLE001 — wandb genuinely optional
        logger.warning(f"wandb unavailable ({e!r}); JSONL logging only")
        return None


@dataclass
class CoordinatorCLIArguments(CollaborationArguments):
    coordinator: CoordinatorExtraArguments = field(
        default_factory=CoordinatorExtraArguments
    )


def main(argv=None) -> None:
    args = parse_config(CoordinatorCLIArguments, argv)
    run_coordinator(args, args.coordinator)


if __name__ == "__main__":
    main()
