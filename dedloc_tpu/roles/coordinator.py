"""Coordinator ("first peer"): DHT root + metrics aggregation + checkpoints.

Capability parity with albert/run_first_peer.py:24-218: starts the DHT other
peers bootstrap from, never trains; every ``refresh_period`` seconds it
aggregates the signed per-peer metrics from the DHT (alive peers, summed
throughput, loss = Σloss/Σmini_steps) and logs them (wandb when available,
always JSONL — the TPU build's durable equivalent of the wandb dashboard);
periodically pulls the newest collaboration state from peers and writes a
local checkpoint (the reference pushes to the HF hub via git,
run_first_peer.py:123-147 — the upload seam is ``upload_fn``).
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from dedloc_tpu.averaging.averager import DecentralizedAverager
from dedloc_tpu.averaging.topology import TopologyPlan, plan_topology
from dedloc_tpu.collaborative.metrics import aggregate_metrics, fetch_metrics
from dedloc_tpu.core.config import CollaborationArguments, parse_config
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.roles.common import build_dht, force_cpu_if_requested
from dedloc_tpu.telemetry import build_swarm_health
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.utils.checkpoint import save_checkpoint
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class CoordinatorExtraArguments:
    """Reference: CoordinatorArguments (run_first_peer.py:24-57)."""

    refresh_period: float = 30.0
    save_checkpoint_step_interval: int = 5
    upload_interval: float = 0.0  # seconds; 0 disables state pulls
    metrics_log_path: str = "coordinator_metrics.jsonl"
    # live swarm watchdog (telemetry/watch.py): streams every health fold
    # through the anomaly detectors; incident open/close transitions land
    # in their own JSONL (next to the metrics log) and as watch.incident
    # telemetry events
    watchdog_enabled: bool = True
    incident_log_path: str = "coordinator_incidents.jsonl"
    # ROADMAP item 4's closed loop: on a sustained throughput-regression
    # incident, fit a TwinModel from this coordinator's own metrics JSONL
    # and attach a bounded-sweep retuning recommendation to the incident.
    # Costs a few seconds of virtual-time replay; at most once per incident
    # — but a TRANSIENTLY failed fit (jammed JSONL mid-write, thread still
    # busy) retries on a later fold instead of permanently attaching
    # no_recommendation (retune_max_attempts bounds the retries).
    retune_on_regression: bool = True
    retune_max_attempts: int = 3
    # live topology re-planning (ISSUE 16 closed loop): derive a
    # TopologyPlan from each health fold's link topology with the SAME
    # plan_topology detector the --topology view uses, and publish it as an
    # epoch-versioned signed DHT record (averaging/planwire.py) whenever
    # the structure materially changes. Peers with plan-following enabled
    # adopt it between rounds; peers pinned to --averager.topology_plan
    # ignore it (the manual opt-out, docs/fleet.md).
    replan: bool = True
    # min seconds between epoch bumps — re-planning hysteresis so one noisy
    # fold cannot thrash the swarm through plan epochs
    replan_min_interval_s: float = 60.0
    # guard-railed actuation (telemetry/watch.ActuationGuard): APPLY an
    # eligible incident's twin recommendation, bounded per actuation and
    # per plan epoch, auto-rolled-back when the post-change throughput
    # regresses past the pre-change level. The applied config delta rides
    # the plan record's tuning field to the peers. False = PR 12 behavior
    # (recommendation only).
    actuate_retune: bool = True
    actuation_max_change_factor: float = 4.0
    actuation_observe_folds: int = 3
    actuation_rollback_margin: float = 0.1
    actuation_max_per_epoch: int = 2
    # contribution ledger (telemetry/ledger.py): each fold reads the signed
    # claim + receipt records off the DHT, folds them into per-peer credit
    # (credited = min(claimed, receipt-supported x slack)) and appends the
    # cumulative state to its own JSONL — durable and restart-safe (the last
    # row re-seeds the fold), gitignored like the other coordinator logs.
    # Newly-flagged over-claims surface as watch.ledger events.
    ledger_enabled: bool = True
    ledger_log_path: str = "coordinator_ledger.jsonl"
    ledger_slack: float = 1.25  # telemetry/ledger.DEFAULT_SLACK
    # hub publication (run_first_peer.py:123-147 capability): a git working
    # tree (optionally pushing to hub_git_remote) or a directory mirror
    hub_git_dir: str = ""
    hub_git_remote: str = ""
    hub_mirror_dir: str = ""
    # gated runs: "user:credential,user2:credential2" — hosts the token
    # AuthService on this coordinator's DHT server (the reference's hosted
    # auth endpoint, huggingface_auth.py:46-143); volunteers then join with
    # --auth.username/--auth.credential pointed at this coordinator
    auth_allowlist: str = ""


def run_coordinator(
    args: CollaborationArguments,
    extra: Optional[CoordinatorExtraArguments] = None,
    upload_fn: Optional[Callable[[str, int], None]] = None,
    max_iterations: int = 0,
) -> None:
    """``upload_fn(checkpoint_path, step)`` is the hub-publish seam
    (run_first_peer.py:123-147's git push); ``max_iterations`` bounds the
    loop for tests (0 = run forever)."""
    force_cpu_if_requested()
    extra = extra or CoordinatorExtraArguments()
    if upload_fn is None:
        from dedloc_tpu.utils.hub import build_upload_fn

        upload_fn = build_upload_fn(
            extra.hub_git_dir, extra.hub_git_remote, extra.hub_mirror_dir
        )
    dht, _public_key = build_dht(args)
    logger.info(f"coordinator DHT root listening on {dht.port}")
    # swarm telemetry (--telemetry.*): the coordinator's own counters —
    # notably metrics.malformed_records from fetch_metrics — need a registry
    # too, or they are silently discarded
    from dedloc_tpu.roles.common import configure_role_telemetry

    _tele, tele_close = configure_role_telemetry(args, _public_key)

    if extra.auth_allowlist:
        from dedloc_tpu.core.auth import AllowlistAuthServer, AuthService

        allow = dict(
            pair.split(":", 1) for pair in extra.auth_allowlist.split(",")
        )
        auth_server = AllowlistAuthServer(
            allow, coordinator_endpoint=dht.get_visible_address()
        )

        async def _attach(node):
            AuthService(node.server, auth_server)

        dht.run_coroutine(_attach)
        logger.info(
            f"auth service up ({len(allow)} allowlisted users); run is gated"
        )

    averager: Optional[DecentralizedAverager] = None
    if extra.upload_interval > 0:
        # listens for state only; contributes no gradients and no bandwidth
        averager = DecentralizedAverager(
            dht,
            args.dht.experiment_prefix,
            client_mode=True,
            allow_state_sharing=False,
            # state pulls prefer the multi-peer sharded path (and fall
            # back to the single-provider blob) like any joiner
            checkpoint_shard_size=args.checkpoint.shard_size,
            checkpoint_fetch_parallelism=args.checkpoint.fetch_parallelism,
            checkpoint_max_providers=args.checkpoint.providers,
        )

    wandb_run = _maybe_wandb(args)
    uploads = {"thread": None}  # per-coordinator upload state (NOT global:
    # tests run several coordinators in one process)
    import threading

    watch = None
    # one twin retune in flight at a time; the lock serializes every
    # incident-dict mutation/serialization between the fold loop and the
    # retune thread
    retunes = {"thread": None, "lock": threading.Lock()}
    if extra.watchdog_enabled:
        from dedloc_tpu.telemetry.watch import SwarmWatch

        watch = SwarmWatch()
    # live re-planning (ISSUE 16): epoch-versioned plan records derived
    # from the health folds' link topology
    replanner = (
        _Replanner(dht, args.dht.experiment_prefix, extra)
        if extra.replan else None
    )
    # guard-railed retune actuation: the applied config delta rides the
    # plan record's tuning field; the launch config is the starting point
    actuation = None
    if extra.watchdog_enabled and extra.actuate_retune:
        from dedloc_tpu.telemetry.watch import ActuationConfig, ActuationGuard

        actuation = {
            "guard": ActuationGuard(ActuationConfig(
                max_change_factor=extra.actuation_max_change_factor,
                observe_folds=extra.actuation_observe_folds,
                rollback_margin=extra.actuation_rollback_margin,
                max_actuations_per_epoch=extra.actuation_max_per_epoch,
            )),
            "config": {
                "chunk_size": args.averager.chunk_size,
                "overlap": args.optimizer.overlap_averaging,
            },
        }
    # contribution-ledger fold state: prev re-seeds from the last row of
    # the durable JSONL, so a restarted coordinator keeps crediting peers
    # whose records expired while it was down (flagged "stale")
    ledger_state = None
    if extra.ledger_enabled:
        ledger_state = {
            "prev": _prev_ledger(extra.ledger_log_path),
            "flagged": {},
        }
    prev_health = None
    prev_fold_t = None
    current_step = -1
    last_upload = get_dht_time()
    iterations = 0
    try:
        while True:
            metrics = fetch_metrics(dht, args.dht.experiment_prefix)
            agg = aggregate_metrics(metrics)
            if agg is not None and agg["step"] > current_step:
                current_step = agg["step"]
                agg["time"] = get_dht_time()
                # swarm health (telemetry/health.py): per-peer retry/fault
                # counters off the signed metrics bus folded into straggler
                # attribution + retry rates — the durable "why was step N
                # slow" record next to the throughput aggregate. prev/dt
                # window the derived rule rates between consecutive folds.
                health = build_swarm_health(
                    metrics,
                    prev=prev_health,
                    dt_s=(
                        agg["time"] - prev_fold_t
                        if prev_fold_t is not None else None
                    ),
                )
                if health is not None:
                    agg["swarm_health"] = health
                    prev_health, prev_fold_t = health, agg["time"]
                    if health["straggler"] is not None:
                        logger.warning(
                            f"step {agg['step']}: straggler "
                            f"{health['straggler']} is stalling the swarm"
                        )
                logger.info(
                    f"step {agg['step']}: {agg['alive_peers']} peers, "
                    f"{agg['samples_per_second']:.1f} samples/s, "
                    f"loss {agg['loss']:.4f}"
                )
                with open(extra.metrics_log_path, "a") as f:
                    f.write(json.dumps(agg) + "\n")
                if wandb_run is not None:
                    wandb_run.log(agg, step=agg["step"])
                if replanner is not None and health is not None:
                    replanner.fold(health, agg["time"])
                if watch is not None and health is not None:
                    _watch_fold(
                        watch, health, agg, extra, retunes,
                        actuation=actuation, replanner=replanner,
                    )

                if (
                    averager is not None
                    and extra.upload_interval > 0
                    and get_dht_time() - last_upload >= extra.upload_interval
                ):
                    _pull_and_save(
                        args, averager, current_step, upload_fn, uploads
                    )
                    last_upload = get_dht_time()

            if ledger_state is not None:
                # every refresh, NOT gated on metrics progress: claims and
                # receipts live even in a swarm too young (or too wedged)
                # to aggregate a metrics step yet
                _ledger_fold(
                    dht, args.dht.experiment_prefix, extra, ledger_state,
                    t=get_dht_time(), step=current_step,
                )

            iterations += 1
            if max_iterations and iterations >= max_iterations:
                break
            time.sleep(extra.refresh_period)
    finally:
        # let an in-flight hub push finish (it is bounded by the git
        # subprocess timeout): a push killed mid-flight can leave a stale
        # lock in the work tree, and the FINAL checkpoint of a run has no
        # next attempt to cover it
        t = uploads.get("thread")
        if t is not None and t.is_alive():
            logger.info("waiting for the in-flight hub upload to finish")
            t.join(timeout=330.0)
        if averager is not None:
            averager.shutdown()
        tele_close()
        dht.shutdown()


class _Replanner:
    """Live topology re-planning off the coordinator's health folds
    (ISSUE 16 tentpole 1). Each fold's link topology — the SAME fold the
    ``--topology`` view renders — runs through ``plan_topology`` with the
    member ids mapped to ENDPOINT KEYS (what averager matchmaking members
    advertise); on a material structure change the epoch bumps and the plan
    publishes as a signed DHT record (``averaging/planwire.py``). Recent
    per-fold roster loss feeds the planner's ``instability`` signal, so a
    very-unreliable swarm re-plans into gossip mode. Tuning-only updates
    (the actuation guard's applied deltas) re-publish under the SAME epoch
    with a newer ``issued`` stamp — scopes unchanged, no group reshuffle."""

    def __init__(self, dht, prefix: str, extra) -> None:
        self.dht = dht
        self.prefix = prefix
        self.extra = extra
        self.epoch = 0
        self.plan: Optional[TopologyPlan] = None
        self.tuning: dict = {}
        self._structure = None
        self._loss_window = deque(maxlen=4)
        self._prev_labels: set = set()
        self._last_bump_t: Optional[float] = None

    @staticmethod
    def _endpoint_links(topology: dict) -> list:
        """Fold links re-keyed by endpoint ("host:port") — plan member ids
        must match what matchmaking members advertise, not the telemetry
        labels the fold uses. Links whose endpoints the fold does not know
        (client-mode peers) drop out; such peers ride any hierarchical
        plan as direct-WAN singletons (TopologyPlan.assignment)."""
        peers = topology.get("peers") or {}
        out = []
        for link in topology.get("links") or []:
            if not isinstance(link, dict):
                continue
            src_ep = peers.get(link.get("src"))
            dst_ep = link.get("dst_endpoint") or peers.get(link.get("dst"))
            if not src_ep or not dst_ep:
                continue
            rec = dict(link)
            rec["src"], rec["dst"] = str(src_ep), str(dst_ep)
            out.append(rec)
        return out

    @staticmethod
    def _shape(plan: TopologyPlan) -> tuple:
        """The plan's material structure: what has to differ before an
        epoch bump (reason strings and RTT medians churn every fold)."""
        return (
            plan.mode,
            tuple((tuple(c.members), c.delegate) for c in plan.cliques),
            tuple(sorted(plan.peers)),
        )

    def instability(self) -> Optional[float]:
        if not self._loss_window:
            return None
        return sum(self._loss_window) / len(self._loss_window)

    def fold(self, health: dict, t: float) -> Optional[TopologyPlan]:
        """One health fold: update the churn window, derive a plan, and
        publish on material change. Returns the newly published plan (or
        None when nothing changed)."""
        peers_rec = [
            p for p in health.get("peers", []) if isinstance(p, dict)
        ]
        labels = {str(p.get("peer")) for p in peers_rec if p.get("peer")}
        if self._prev_labels:
            lost = self._prev_labels - labels
            self._loss_window.append(
                len(lost) / max(1, len(self._prev_labels))
            )
        self._prev_labels = labels
        topology = health.get("topology")
        if not isinstance(topology, dict):
            return None
        plan = plan_topology(
            self._endpoint_links(topology), instability=self.instability()
        )
        if self._shape(plan) == self._structure:
            return None
        if self.plan is None and plan.mode == "flat":
            # nothing published yet and the planner says "keep today's
            # flat butterfly": publishing epoch 1 of the status quo would
            # only reshuffle scopes for nothing
            self._structure = self._shape(plan)
            return None
        if (
            self._last_bump_t is not None
            and t - self._last_bump_t < self.extra.replan_min_interval_s
        ):
            return None  # re-planning hysteresis: re-derived next fold
        self.epoch += 1
        plan.epoch = self.epoch
        self.plan = plan
        self._structure = self._shape(plan)
        self._last_bump_t = t
        self._publish(plan, t)
        return plan

    def push_tuning(self, tuning: dict, t: float) -> None:
        """Distribute an actuated (or rolled-back) config delta: re-publish
        the current record with the new tuning payload, same epoch."""
        self.tuning = {
            k: v for k, v in dict(tuning).items()
            if isinstance(v, (int, float, bool))
        }
        plan = self.plan
        if plan is None:
            # no topology plan derived yet: a flat epoch-0 carrier record
            # still distributes the tuning delta
            plan = TopologyPlan(
                "flat", "tuning-only record (no topology re-plan yet)"
            )
        self._publish(plan, t)

    def _publish(self, plan: TopologyPlan, t: float) -> bool:
        from dedloc_tpu.averaging.planwire import PlanRecord, publish_plan

        record = PlanRecord(
            epoch=int(plan.epoch),
            plan=plan.to_dict(),
            issued=float(t),
            tuning=dict(self.tuning) if self.tuning else None,
        )
        ok = publish_plan(self.dht, self.prefix, record)
        telemetry.inc("avg.topology.replans")
        telemetry.event(
            "avg.topology.replan",
            epoch=int(plan.epoch),
            mode=plan.mode,
            reason=plan.reason,
            cliques=len(plan.cliques),
            published=bool(ok),
        )
        if ok:
            logger.info(
                f"published topology plan epoch {plan.epoch}: {plan.mode} "
                f"({plan.reason})"
            )
        else:
            logger.warning(
                f"topology plan epoch {plan.epoch} publish failed after "
                "retries; the swarm stays on the previous record"
            )
        return ok


def _load_own_rows(path: str) -> list:
    """Rows of the coordinator's own metrics JSONL, through the SAME
    hardened loader every post-hoc tool uses (utils/jsonl.py): a torn or
    writer-jammed line salvages its complete objects here exactly as it
    would under swarm_watch --recommend, so the self-retune twin fits
    from the same rows. A not-yet-created log reads as empty."""
    from dedloc_tpu.utils.jsonl import load_jsonl_rows

    return load_jsonl_rows([path], warn=logger.warning, missing_ok=True)


def _prev_ledger(path: str) -> Optional[dict]:
    """Last folded ledger state in the durable JSONL (restart-safe seed
    for the next fold); None on a fresh log. Reads through the hardened
    loader, so a torn final line yields the last COMPLETE state."""
    for row in reversed(_load_own_rows(path)):
        if isinstance(row, dict) and isinstance(row.get("ledger"), dict):
            return row["ledger"]
    return None


def _fetch_ledger_records(dht, prefix: str) -> tuple:
    """(claims, receipts) currently live on the DHT, unpacked through the
    same msgpack path the metrics bus uses and re-validated through the
    pydantic schemas (defense in depth over the storing nodes' checks)."""
    from dedloc_tpu.core.serialization import unpack_obj
    from dedloc_tpu.telemetry.ledger import (
        ledger_key,
        parse_claims,
        parse_receipts,
        receipts_key,
    )

    def _items(key: str) -> list:
        entry = dht.get(key, latest=True)
        if entry is None or not hasattr(entry.value, "items"):
            return []
        out = []
        for subkey, v in entry.value.items():
            payload = v.value
            if isinstance(payload, (bytes, bytearray)):
                try:
                    payload = unpack_obj(payload)
                except Exception:  # noqa: BLE001 — undecodable record
                    continue
            out.append((subkey, payload))
        return out

    return (
        parse_claims(_items(ledger_key(prefix))),
        parse_receipts(_items(receipts_key(prefix))),
    )


def _ledger_substance(folded: dict) -> tuple:
    """The fold minus its ever-ticking fields, for change detection: each
    ~30s claim refresh bumps ``last_claim_t``/``train_seconds`` even in a
    live-but-idle swarm, so comparing full per-peer entries would append a
    cumulative ledger row on nearly every tick. Credited/claimed totals,
    rounds, serve bytes, coverage and discrepancies are what a new row is
    FOR — timestamps alone are not."""
    peers = {
        p: {
            k: v
            for k, v in e.items()
            if k not in ("last_claim_t", "train_seconds")
        }
        for p, e in (folded.get("peers") or {}).items()
        if isinstance(e, dict)
    }
    return (peers, folded.get("claims"), folded.get("receipt_signers"))


def _ledger_fold(dht, prefix: str, extra, ledger_state, t, step) -> None:
    """One contribution-ledger fold inline in the coordinator loop: fetch
    the live claim/receipt records, fold them against the previous state
    (telemetry/ledger.fold_ledger), append the cumulative result to the
    durable ledger JSONL, and surface each NEWLY-flagged per-peer
    discrepancy as a ``watch.ledger`` telemetry event + warning. A fold
    that changes nothing of substance (``_ledger_substance`` — fold
    timestamps and per-claim refresh stamps excluded) is not re-appended,
    so neither an idle swarm nor a live-but-idle one grows the log."""
    from dedloc_tpu.telemetry.ledger import fold_ledger

    try:
        claims, receipts = _fetch_ledger_records(dht, prefix)
    except Exception as e:  # noqa: BLE001 — a ledger fetch failure must
        # never take the coordinator loop down; next refresh retries
        logger.warning(f"ledger fetch failed: {e!r}")
        return
    prev = ledger_state.get("prev")
    if not claims and not receipts and prev is None:
        return  # pre-ledger swarm: nothing to fold, nothing to persist
    folded = fold_ledger(
        prev, claims, receipts, slack=extra.ledger_slack, now=t
    )
    changed = prev is None or (
        _ledger_substance(folded) != _ledger_substance(prev)
    )
    ledger_state["prev"] = folded
    if changed:
        try:
            with open(extra.ledger_log_path, "a") as f:
                f.write(
                    json.dumps({
                        "t": folded["t"], "step": step, "ledger": folded,
                    })
                    + "\n"
                )
        except OSError as e:
            logger.warning(f"cannot append ledger log: {e}")
    for peer, entry in folded["peers"].items():
        disc = entry.get("discrepancy")
        if not disc:
            ledger_state["flagged"].pop(peer, None)
            continue
        if ledger_state["flagged"].get(peer) == disc.get("kind"):
            continue  # already surfaced; only a kind change re-fires
        ledger_state["flagged"][peer] = disc.get("kind")
        telemetry.inc("ledger.discrepancies")
        telemetry.event(
            "watch.ledger",
            peer=peer,
            kind=disc.get("kind"),
            claimed_samples=disc.get("claimed_samples"),
            supported_samples=disc.get("supported_samples"),
            ratio=disc.get("ratio"),
            step=step,
        )
        logger.warning(
            f"ledger discrepancy [{disc.get('kind')}] peer {peer}: "
            f"claimed {disc.get('claimed_samples')} vs receipt-supported "
            f"{disc.get('supported_samples')}"
        )


def _append_incident(extra, t, step, transition, incident) -> None:
    """One transition record onto the incident JSONL (replayable by
    ``runlog_summary --incidents``; for recorded logs the view keeps the
    LAST state per incident id, so a later ``recommendation`` record
    supersedes the bare ``retune_eligible`` one)."""
    record = {
        "t": t,
        "step": step,
        "watch": "incident",
        "transition": transition,
        # deep JSON copy: the live incident dict keeps mutating (effects,
        # severity escalation) after this transition
        "incident": json.loads(json.dumps(incident, default=str)),
    }
    try:
        with open(extra.incident_log_path, "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError as e:
        logger.warning(f"cannot append incident log: {e}")


def _spawn_retune(incident, agg, extra, retunes) -> None:
    """Fit-and-recommend OFF the fold loop (same shape as the hub-upload
    thread): the twin fit plus its bounded sweep costs seconds of replay,
    and it must not stall metrics folding on exactly the fleet that is
    already regressing. One retune in flight at a time; the follow-up
    ``recommendation`` record lands when it finishes. The slow fit runs
    into a LOCAL result; the live incident dict is only touched (and
    serialized) under ``retunes["lock"]`` — the fold loop keeps appending
    effects to the same dict while this thread runs."""
    prev = retunes.get("thread")
    if prev is not None and prev.is_alive():
        # busy is TRANSIENT: attach nothing — the per-fold eligibility
        # re-check in _watch_fold dispatches this incident again once the
        # in-flight fit finishes (the old permanent "retune skipped"
        # reason froze the incident without a recommendation forever)
        logger.debug(
            f"retune for {incident['id']} deferred: a previous twin fit "
            "is still running"
        )
        return

    def _do(incident=incident, t=agg["time"], step=agg["step"]):
        try:
            from dedloc_tpu.telemetry.watch import twin_recommendation

            # fit from the coordinator's OWN durable log: on a bus-only
            # fleet (health records carry no round summaries) this reports
            # "no recommendation: <reason>" instead of guessing — point
            # collected per-peer event logs at tools/swarm_watch.py
            # --recommend for the full-fidelity fit
            result = twin_recommendation(
                _load_own_rows(extra.metrics_log_path)
            )
        except Exception as e:  # noqa: BLE001 — a retune failure must
            # never take the watchdog (or the coordinator) down with it.
            # It is also usually TRANSIENT (the metrics JSONL jammed
            # mid-write, a briefly-full disk): count the attempt and let
            # the next fold retry; only a repeatedly-failing fit attaches
            # a permanent reason.
            logger.warning(f"watchdog retune failed: {e!r}")
            with retunes["lock"]:
                attempts = int(incident.get("retune_attempts", 0)) + 1
                incident["retune_attempts"] = attempts
                if attempts >= max(1, extra.retune_max_attempts):
                    incident["recommendation_reason"] = (
                        f"retune failed after {attempts} attempts "
                        f"(last: {e!r})"
                    )
                    _append_incident(
                        extra, t, step, "recommendation", incident
                    )
            return
        with retunes["lock"]:
            if "no_recommendation" in result:
                # a DEFINITIVE reason from the fit itself (insufficient
                # coverage, unvalidated twin): attaching it is final
                incident["recommendation_reason"] = (
                    result["no_recommendation"]
                )
            else:
                incident["recommendation"] = result
            _append_incident(extra, t, step, "recommendation", incident)

    import threading

    retunes["thread"] = threading.Thread(target=_do, daemon=True)
    retunes["thread"].start()


def _watch_fold(watch, health, agg, extra, retunes,
                actuation=None, replanner=None) -> None:
    """One watchdog fold inline in the coordinator loop: stream the fresh
    health record through the detectors, persist every incident transition
    to the incident JSONL (same directory as the metrics log), surface it
    as a ``watch.incident`` telemetry event, kick off the background twin
    retune for eligible incidents (re-dispatched on later folds while a
    transient failure left no recommendation attached), and drive the
    actuation guard (apply → observe → keep-or-rollback).

    The WHOLE fold holds ``retunes["lock"]``: observe_health mutates live
    incident dicts (effects, severity, representative round) that the
    retune thread also mutates and serializes when its fit completes —
    the fold is pure in-memory computation, so the retune thread waits at
    most microseconds, never the other way around (the slow twin fit runs
    OUTSIDE the lock)."""
    with retunes["lock"]:
        transitions = watch.observe_health(
            health,
            t=agg["time"],
            step=agg["step"],
            samples_per_sec=agg.get("samples_per_second"),
        )
        for tr in transitions:
            incident = tr["incident"]
            _append_incident(
                extra, agg["time"], agg["step"], tr["transition"], incident
            )
            telemetry.event(
                "watch.incident",
                transition=tr["transition"],
                incident_id=incident["id"],
                kind=incident["kind"],
                metric=incident["metric"],
                subject=incident["subject"],
                severity=incident["severity"],
                peer=incident.get("peer"),
            )
            log = (
                logger.warning if tr["transition"] == "open"
                else logger.info
            )
            log(
                f"watchdog {tr['transition']}: [{incident['id']}] "
                f"{incident['severity']} {incident['kind']} "
                f"{incident['subject']} ({incident['metric']})"
            )
        if extra.retune_on_regression:
            # per-fold re-check, not just the one-shot retune_eligible
            # transition: an incident whose fit failed transiently (or was
            # deferred behind an in-flight fit) carries neither a
            # recommendation nor a reason yet and is dispatched again
            for incident in watch.open_incidents():
                if (
                    incident.get("retune_eligible")
                    and "recommendation" not in incident
                    and "recommendation_reason" not in incident
                ):
                    _spawn_retune(incident, agg, extra, retunes)
        if actuation is not None:
            _actuation_fold(watch, agg, extra, actuation, replanner)


def _incident_by_id(watch, incident_id):
    for incident in watch.incidents:
        if incident["id"] == incident_id:
            return incident
    return None


def _actuation_fold(watch, agg, extra, actuation, replanner) -> None:
    """Drive the actuation guard for one fold (caller holds the retune
    lock): judge the in-flight actuation against this fold's throughput
    (rolling it back when it regressed past the pre-change level), then
    apply at most one new eligible recommendation under the guard rail.
    Every actuation/rollback lands as an incident effect, an incident-JSONL
    transition and a ``watch.actuation``/``watch.rollback`` event; the
    resulting config delta rides the plan record's tuning field out to the
    peers (``_Replanner.push_tuning``)."""
    from dedloc_tpu.telemetry.watch import rollback_effect

    guard = actuation["guard"]
    epoch = replanner.epoch if replanner is not None else 0
    t, step = agg["time"], agg["step"]
    sps = agg.get("samples_per_second")

    verdict = guard.observe(sps, fold=watch.fold)
    if verdict is not None:
        incident = _incident_by_id(watch, verdict.get("incident"))
        if verdict["verdict"] == "rollback":
            actuation["config"].update(verdict["revert"])
            telemetry.inc("watch.rollbacks")
            telemetry.event(
                "watch.rollback",
                incident_id=verdict.get("incident"),
                applied=json.dumps(verdict["revert"]),
                observed_samples_per_sec=(
                    verdict["observed"][-1] if verdict["observed"] else None
                ),
                baseline_samples_per_sec=(
                    verdict.get("baseline_samples_per_sec")
                ),
            )
            logger.warning(
                f"actuation rolled back for {verdict.get('incident')}: "
                f"reverting {verdict['revert']} (post-change throughput "
                "regressed past the pre-change level)"
            )
            if incident is not None:
                rollback_effect(incident, verdict)
                _append_incident(extra, t, step, "rollback", incident)
            if replanner is not None:
                replanner.push_tuning(actuation["config"], t)
        else:  # kept
            telemetry.event(
                "watch.actuation",
                incident_id=verdict.get("incident"),
                applied=json.dumps(verdict["applied"]),
                verdict="kept",
            )
            logger.info(
                f"actuation kept for {verdict.get('incident')}: "
                f"{verdict['applied']} held through "
                f"{len(verdict['observed'])} fold(s)"
            )
            if incident is not None:
                for effect in incident.get("effects", []):
                    if (
                        effect.get("metric") == "actuation"
                        and effect.get("applied") == verdict["applied"]
                    ):
                        effect["verdict"] = "kept"
                _append_incident(extra, t, step, "actuation", incident)

    for incident in watch.open_incidents():
        recommendation = incident.get("recommendation")
        if not recommendation or incident.get("actuated"):
            continue
        result = guard.consider(
            recommendation, actuation["config"],
            fold=watch.fold, epoch=epoch,
        )
        if "refused" in result:
            # NOT final — cooldowns expire and budgets reset with the next
            # plan epoch, so the guard is re-consulted every fold
            incident["actuation_refused"] = result["refused"]
            continue
        incident.pop("actuation_refused", None)
        actuation["config"].update(result["apply"])
        incident["actuated"] = True
        guard.actuate(
            incident, result["apply"], result["revert"],
            fold=watch.fold, baseline_samples_per_sec=sps,
            epoch=epoch, clamped=tuple(result["clamped"]),
        )
        telemetry.inc("watch.actuations")
        telemetry.event(
            "watch.actuation",
            incident_id=incident["id"],
            applied=json.dumps(result["apply"]),
            verdict="applied",
        )
        logger.warning(
            f"actuating twin recommendation for {incident['id']}: "
            f"applying {result['apply']}"
            + (f" (clamped: {result['clamped']})" if result["clamped"]
               else "")
        )
        _append_incident(extra, t, step, "actuation", incident)
        if replanner is not None:
            replanner.push_tuning(actuation["config"], t)
        break  # one actuation per fold; the guard serializes the rest


def _pull_and_save(args, averager, step, upload_fn, uploads) -> None:
    result = averager.load_state_from_peers()
    if result is None:
        logger.warning("no state providers yet; skipping checkpoint")
        return
    metadata, tree = result
    path = save_checkpoint(
        args.training.output_dir,
        step,
        tree,
        metadata=metadata,
        save_total_limit=args.training.save_total_limit,
    )
    logger.info(f"saved collaboration checkpoint {path}")
    # swarm checkpointing (--checkpoint.*): write the durable manifest +
    # content-addressed shards next to the legacy blob (shards unchanged
    # between steps are stored once), and drop the manifest into the
    # checkpoint dir so the hub upload below publishes it — a mirror
    # consumer can then verify shard integrity against the signed digest
    if getattr(args, "checkpoint", None) and args.checkpoint.shard_size > 0:
        from dedloc_tpu.checkpointing import save_sharded_checkpoint

        try:
            manifest = save_sharded_checkpoint(
                os.path.join(args.training.output_dir, "sharded"),
                tree,
                step,
                shard_size=args.checkpoint.shard_size,
                metadata=metadata,
                keep=args.training.save_total_limit,
            )
            with open(os.path.join(path, "manifest.bin"), "wb") as f:
                f.write(manifest.to_bytes())
            telemetry.inc("ckpt.manifests_written")
            telemetry.event(
                "ckpt.manifest_written", step=step,
                shards=manifest.num_shards, bytes=manifest.total_bytes,
            )
            logger.info(
                f"wrote sharded checkpoint manifest at step {step} "
                f"({manifest.num_shards} shards)"
            )
        except ValueError as e:
            # a tree that cannot roundtrip the fp32 layout stays blob-only
            logger.warning(f"sharded checkpoint skipped: {e}")
    if upload_fn is not None:
        # background thread (reference behavior, run_first_peer.py:139): a
        # slow push must not block metrics aggregation or checkpointing.
        # One upload in flight at a time — a new checkpoint while the
        # previous push still runs skips its upload (the next interval
        # covers it; the shutdown path joins the last one so the final
        # checkpoint is never abandoned).
        prev = uploads.get("thread")
        if prev is not None and prev.is_alive():
            logger.warning(
                f"previous hub upload still in flight; skipping step {step}"
            )
            return

        def _do_upload(path=path, step=step):
            try:
                upload_fn(path, step)
            except Exception as e:  # noqa: BLE001 — a hub blip must not
                # kill the coordinator; the git helper is also bounded by a
                # subprocess timeout so a stalled remote cannot wedge this
                # thread forever
                logger.warning(f"hub upload failed for step {step}: {e}")

        import threading

        uploads["thread"] = threading.Thread(target=_do_upload)
        uploads["thread"].start()


def _maybe_wandb(args: CollaborationArguments):
    if not args.wandb_project:
        return None
    try:
        import wandb  # type: ignore

        return wandb.init(project=args.wandb_project)
    except Exception as e:  # noqa: BLE001 — wandb genuinely optional
        logger.warning(f"wandb unavailable ({e!r}); JSONL logging only")
        return None


@dataclass
class CoordinatorCLIArguments(CollaborationArguments):
    coordinator: CoordinatorExtraArguments = field(
        default_factory=CoordinatorExtraArguments
    )


def main(argv=None) -> None:
    args = parse_config(CoordinatorCLIArguments, argv)
    run_coordinator(args, args.coordinator)


if __name__ == "__main__":
    main()
