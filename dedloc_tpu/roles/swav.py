"""SwAV collaborative trainer peer.

Capability parity with the reference's swav workload driver (reference:
swav/vissl/vissl/trainer/trainer_main.py:138-204 phase loop +
swav/ClassyVision/classy_vision/optim/sgd_collaborative.py:132-171): build
ResNet-50 trunk + prototypes head, LARC-SGD with warmup-cosine schedule,
DHT + CollaborativeOptimizer (target_batch_size 32768), multicrop pipeline,
and run the phase-loop Trainer with the default hook pipeline.

TPU-native shape (SURVEY.md §3.4): the reference's two communication worlds —
NCCL all_reduce inside the sinkhorn loop and hivemind averaging per optimizer
step — become (a) ICI psums XLA inserts when the jitted step is sharded over
a mesh and (b) the DHT/DCN averaging in CollaborativeOptimizer. The GLOBAL
collaboration step (not the local one) gates the queue and the prototype
freeze, exactly as the fork feeds collaboration_state.optimizer_step to the
loss (standard_train_step.py:153).
"""
from __future__ import annotations

from typing import Iterator, List

import jax
import jax.numpy as jnp
import numpy as np

from dedloc_tpu.collaborative.optimizer import CollaborativeOptimizer
from dedloc_tpu.core.config import SwAVCollaborationArguments, parse_config
from dedloc_tpu.core.hooks import default_hooks
from dedloc_tpu.core.trainer import Trainer
from dedloc_tpu.data.multicrop import (
    MultiCropSpec,
    image_folder_multicrop_batches,
    synthetic_multicrop_batches,
)
from dedloc_tpu.models.swav import (
    SwAVConfig,
    SwAVModel,
    SwAVQueue,
    make_prototype_post_apply,
    make_swav_accumulate_step,
)
from dedloc_tpu.optim.lars import lars
from dedloc_tpu.optim.schedules import linear_warmup_cosine_annealing
from dedloc_tpu.parallel.train_step import TrainState, zeros_like_grads
from dedloc_tpu.roles.common import (
    build_dht,
    checkpoint_kwargs,
    force_cpu_if_requested,
)
from dedloc_tpu.utils.checkpoint import save_checkpoint
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def build_swav(args: SwAVCollaborationArguments):
    """(cfg, spec, model, tx) for the requested model size."""
    t = args.training
    if t.model_size == "tiny":
        cfg = SwAVConfig.tiny(
            queue_length=t.queue_length, queue_start_step=t.queue_start_step
        )
        spec = MultiCropSpec.tiny()
    else:
        cfg = SwAVConfig(
            queue_length=t.queue_length, queue_start_step=t.queue_start_step
        )
        spec = MultiCropSpec()
    model = SwAVModel(cfg)
    schedule = linear_warmup_cosine_annealing(
        t.learning_rate, t.warmup_steps, t.total_steps
    )
    tx = lars(
        learning_rate=schedule,
        momentum=t.momentum,
        weight_decay=t.weight_decay,
        trust_coefficient=t.trust_coefficient,
    )
    return cfg, spec, model, tx


def _build_flat_lars_factory(t):
    """(spec, params) -> optim.flat.FlatLars mirroring ``build_swav``'s
    LARS hyperparameters (fused flat apply; --optimizer.flat_apply)."""
    schedule = linear_warmup_cosine_annealing(
        t.learning_rate, t.warmup_steps, t.total_steps
    )

    def factory(spec, params):
        from dedloc_tpu.optim.flat import FlatLars

        # build_swav's lars() passes no exclude_mask_fn: no skipped spans
        return FlatLars(
            spec, [False] * len(spec), schedule,
            momentum=t.momentum,
            weight_decay=t.weight_decay,
            trust_coefficient=t.trust_coefficient,
        )

    return factory


def run_swav(args: SwAVCollaborationArguments) -> TrainState:
    force_cpu_if_requested()
    t = args.training
    cfg, spec, model, tx = build_swav(args)
    dht, _public_key = build_dht(args)
    logger.info(f"swav peer DHT listening on {dht.port}")
    # swarm telemetry (--telemetry.*, docs/observability.md): same wiring as
    # the ALBERT trainer; disabled (default) costs nothing
    from dedloc_tpu.roles.common import configure_role_telemetry

    tele, tele_close = configure_role_telemetry(args, _public_key)

    # slice-as-one-peer (same mapping as the ALBERT trainer): crops shard
    # over the data axis, so the sinkhorn sums inside the jitted loss ride
    # ICI psums — the reference's NCCL all_reduce world, compiler-inserted
    mesh = None
    slice_factor = max(1, t.mesh_devices)
    if t.mesh_devices > 1:
        from dedloc_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(t.mesh_devices, device_offset=t.mesh_device_offset)
        logger.info(f"swav slice mesh: {mesh.shape}")
    slice_batch = t.per_device_batch_size * slice_factor
    if slice_batch < 8:
        # sinkhorn equipartitions THIS peer's local batch over the
        # prototypes: at a handful of global-crop embeddings the transport
        # is pure noise and the peer's gradients carry ~19x the per-sample
        # energy of a B=16 peer (measured at init; core/config.py
        # contrib_clip_per_sample). The clip bounds the damage, but such a
        # peer contributes little signal — prefer a larger batch or aux.
        logger.warning(
            f"per-peer batch {slice_batch} is too small for a stable "
            "sinkhorn assignment; this peer's gradients will be mostly "
            "noise (clipped by optimizer.contrib_clip_per_sample). "
            "Raise --training.per_device_batch_size (>=8) or join as an "
            "aux bandwidth donor instead."
        )

    rng = jax.random.PRNGKey(t.seed)
    init_crops = [
        jnp.zeros((count * t.per_device_batch_size, size, size, spec.channels))
        for size, count in zip(spec.sizes, spec.counts)
    ]
    variables = model.init(rng, init_crops, True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    state = jax.jit(lambda p: TrainState.create(p, tx))(params)
    queue = (
        SwAVQueue.create(cfg, jax.random.PRNGKey(t.seed + 1))
        if cfg.queue_length
        else None
    )

    opt = CollaborativeOptimizer(
        tx,
        dht,
        prefix=args.dht.experiment_prefix,
        target_batch_size=args.optimizer.target_batch_size,
        batch_size_lead=args.optimizer.batch_size_lead,
        batch_size_per_step=(
            slice_batch * t.gradient_accumulation_steps
        ),
        bandwidth=args.averager.bandwidth,
        compression=args.averager.compression,
        chunk_size=args.averager.chunk_size,
        error_feedback=args.optimizer.error_feedback,
        overlap_averaging=args.optimizer.overlap_averaging,
        target_group_size=args.averager.target_group_size,
        averaging_expiration=args.averager.averaging_expiration,
        averaging_timeout=args.averager.averaging_timeout,
        metadata_expiration=args.averager.metadata_expiration,
        statistics_expiration=args.optimizer.statistics_expiration,
        contrib_clip_per_sample=args.optimizer.contrib_clip_per_sample,
        ramp_rounds=args.optimizer.ramp_rounds,
        health_gate_loss_ratio=args.optimizer.health_gate_loss_ratio,
        state_sync_retries=args.averager.state_sync_retries,
        state_sync_backoff=args.averager.state_sync_backoff,
        # device-resident gradient pipeline + fused flat LARS apply (same
        # knobs as the ALBERT trainer; docs/perf.md round 6)
        device_flat=args.optimizer.device_flat,
        flat_opt_factory=(
            _build_flat_lars_factory(t)
            if args.optimizer.flat_apply else None
        ),
        # swarm checkpointing (--checkpoint.*): same wiring as the ALBERT
        # trainer — sharded serving/catalog/restore with blob fallback
        **checkpoint_kwargs(args, _public_key),
        client_mode=args.dht.client_mode,
        relay=args.dht.relay or None,
        listen_port=args.averager.listen_port,
        advertised_host=args.dht.advertised_host or None,
        mesh=mesh,
        post_apply=make_prototype_post_apply(),
        verbose=True,
    )
    # disk resume (same contract as the ALBERT trainer): newest checkpoint
    # restores params + batch_stats and seeds the collaborative counter; a
    # LIVE collaboration below still wins. LARC momentum is not part of the
    # swav checkpoint (the reference's vissl phase resume also rebuilds the
    # optimizer) — it re-warms within a few steps.
    from dedloc_tpu.collaborative.optimizer import _named_to_tree
    from dedloc_tpu.utils.checkpoint import load_latest_checkpoint

    resumed = load_latest_checkpoint(t.output_dir)
    if resumed is not None:
        ckpt_step, tree, meta = resumed
        template = jax.device_get((state.params, batch_stats))
        try:
            params_t, bs_t = _named_to_tree(tree, template)
            state = state.replace(
                step=jnp.asarray(ckpt_step, jnp.int32),
                params=jax.device_put(params_t),
            )
            batch_stats = jax.device_put(bs_t)
            opt.local_step = int(meta.get("local_step", ckpt_step))
            logger.info(f"resumed from local checkpoint at step {ckpt_step}")
        except (KeyError, ValueError) as e:
            logger.warning(f"checkpoint incompatible ({e!r}); starting fresh")
            resumed = None  # genuinely fresh: keep cold-start adoption below
    # a DEEPER live collaboration wins over the disk checkpoint; the
    # reverse race (fresh partner raced ahead while we compiled) must not
    # (only_if_newer — see load_state_from_peers). Cold starts keep the
    # unconditional adopt so simultaneous fresh replicas begin identical.
    state = opt.load_state_from_peers(
        state, only_if_newer=resumed is not None
    )
    # share a pre-training snapshot (same as the ALBERT trainer): partners
    # that start while this peer is still compiling must find a provider —
    # and a resumed peer's deep state must be visible before its first step
    opt.seed_state_sharing(state)

    accumulate = make_swav_accumulate_step(
        model, cfg, mesh=mesh, num_crop_groups=len(spec.sizes)
    )
    grad_acc = zeros_like_grads(state.params)
    n_acc = jnp.zeros([], jnp.int32)
    if t.image_folder:
        # real JPEGs through the full SSL augmentation stack
        # (ImgPilToMultiCrop + flip + color distortion + blur + normalize)
        batches = image_folder_multicrop_batches(
            t.image_folder, spec, slice_batch, seed=t.seed
        )
    else:
        batches = synthetic_multicrop_batches(spec, slice_batch, seed=t.seed)
    samples = slice_batch * t.gradient_accumulation_steps

    # mutable local (non-collaborative) state, closed over by the step fn
    local = {"batch_stats": batch_stats, "queue": queue,
             "grad_acc": grad_acc, "n_acc": n_acc}

    def step_fn(state, micro_batches: List[List[np.ndarray]]):
        # one trainer step = one accumulation boundary
        loss = jnp.zeros([])
        for crops in micro_batches:
            use_queue = bool(
                cfg.queue_length and opt.local_step >= cfg.queue_start_step
            )
            if use_queue and not local.get("queue_engaged"):
                local["queue_engaged"] = True
                logger.info(
                    f"queue engaged at global step {opt.local_step} "
                    f"(queue_start_step={cfg.queue_start_step}, "
                    f"length={cfg.queue_length})"
                )
                if cfg.queue_start_step < 2 * t.warmup_steps:
                    # measured negative (BASELINE.md round 5): engaging the
                    # queue on a near-random trunk fills it with embeddings
                    # that mislead sinkhorn and collapse the representation
                    # (linear probe BELOW the random-trunk control); the
                    # reference engages its queue deep into training
                    # (swav/README.md:28, queue.start_iter ~98-100k)
                    logger.warning(
                        "queue engaged before the trunk is trained "
                        f"(start {cfg.queue_start_step} < 2x warmup "
                        f"{t.warmup_steps}); stale near-random embeddings "
                        "can collapse the representation — prefer a later "
                        "--training.queue_start_step"
                    )
            local["grad_acc"], local["n_acc"], local["batch_stats"], \
                local["queue"], metrics = accumulate(
                    state.params,
                    local["batch_stats"],
                    local["queue"],
                    local["grad_acc"],
                    local["n_acc"],
                    _put_crops(crops),
                    jnp.asarray(opt.local_step, jnp.int32),
                    use_queue,
                )
            loss = metrics["loss"]
        state, local["grad_acc"], local["n_acc"], _stepped = opt.step(
            state, local["grad_acc"], local["n_acc"], samples
        )
        if _stepped:
            # advertise the loss for the trunk-health gate — one host sync
            # per GLOBAL step, the same cadence the ALBERT trainer pays
            loss_host = float(loss)
            opt.report_loss(loss_host)
            # ride the signed metrics bus like the ALBERT trainer
            # (run_first_peer.py:176-218 aggregation): the coordinator's
            # throughput/loss aggregate and swarm-health view work for SwAV
            # fleets too, with the throttled telemetry tail attached
            from dedloc_tpu.collaborative.metrics import (
                LocalMetrics,
                publish_metrics,
            )
            from dedloc_tpu.telemetry.links import endpoint_key

            publish_metrics(
                dht,
                args.dht.experiment_prefix,
                _public_key,
                LocalMetrics(
                    step=opt.local_step,
                    samples_per_second=float(
                        opt.performance_ema.samples_per_second
                    ),
                    samples_accumulated=samples,
                    loss=loss_host,
                    mini_steps=1,
                    telemetry=(
                        tele.maybe_snapshot(args.telemetry.snapshot_period)
                        if tele is not None
                        else None
                    ),
                    # advertised RPC endpoint for the coordinator's link →
                    # peer-label resolution in the swarm topology fold
                    endpoint=(
                        endpoint_key(opt.averager.endpoint)
                        if tele is not None
                        and opt.averager.endpoint is not None
                        else None
                    ),
                ),
                expiration=args.optimizer.statistics_expiration,
            )
        return state, {"loss": loss, "global_step": opt.local_step}

    def _put_crops(crops):
        if mesh is None:
            return [jnp.asarray(c) for c in crops]
        from jax.sharding import NamedSharding, PartitionSpec as P

        data = NamedSharding(mesh, P("data"))
        return [jax.device_put(jnp.asarray(c), data) for c in crops]

    def grouped(it: Iterator, k: int) -> Iterator[list]:
        while True:
            group = []
            for _ in range(k):
                try:
                    group.append(next(it))
                except StopIteration:
                    # PEP 479: returning (not leaking StopIteration) ends the
                    # generator so Trainer stops gracefully on finite data
                    return
            yield group

    def save_fn(ctx):
        host = jax.device_get(
            (ctx.train_state.params, local["batch_stats"])
        )
        from dedloc_tpu.collaborative.optimizer import _tree_to_named

        save_checkpoint(
            t.output_dir,
            opt.local_step,
            _tree_to_named(host),
            metadata={"local_step": opt.local_step},
            save_total_limit=t.save_total_limit,
        )

    trainer = Trainer(
        step_fn,
        hooks=default_hooks(
            log_every=t.log_every,
            save_fn=save_fn if t.save_steps else None,
            save_every=t.save_steps,
            device_stats_every=t.device_stats_every,
        ),
    )
    try:
        state, _ctx = trainer.train(
            state,
            grouped(batches, t.gradient_accumulation_steps),
            max_steps=t.max_local_steps or 10**9,
        )
    finally:
        tele_close()
        opt.shutdown()
        dht.shutdown()
    return state


def main(argv=None) -> None:
    run_swav(parse_config(SwAVCollaborationArguments, argv))


if __name__ == "__main__":
    main()
