"""Initial DHT bootstrap node.

Capability parity with swav/run_initial_dht_node.py:35-40: a standalone DHT
peer that other peers use as ``initial_peers``; a keepalive loop issues a
random get every 30 s so the node notices (and prunes) dead neighbours.
"""
from __future__ import annotations

import time
import uuid

from dedloc_tpu.core.config import CollaborationArguments, parse_config
from dedloc_tpu.roles.common import build_dht, force_cpu_if_requested
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def run_dht_node(
    args: CollaborationArguments,
    keepalive_period: float = 30.0,
    max_iterations: int = 0,
) -> None:
    force_cpu_if_requested()
    dht, _ = build_dht(args, client_mode=False)
    logger.info(
        f"initial DHT node up at {dht.get_visible_address()} "
        f"(bootstrap with --dht.initial_peers host:{dht.port})"
    )
    iterations = 0
    try:
        while True:
            dht.get(uuid.uuid4().hex)  # keepalive (run_initial_dht_node.py:39)
            iterations += 1
            if max_iterations and iterations >= max_iterations:
                break
            time.sleep(keepalive_period)
    finally:
        dht.shutdown()


def main(argv=None) -> None:
    run_dht_node(parse_config(CollaborationArguments, argv))


if __name__ == "__main__":
    main()
