"""Held-out evaluation: MLM+SOP loss of a checkpoint over a tokenized set.

The reference measures progress by training loss and downstream fine-tunes
(sahajbert/train_ner.py, train_ncc.py); this role adds the direct
pretraining metric — masked-LM cross-entropy (and perplexity) on a held-out
corpus — so BASELINE curves can report generalization, not just fit.

Run:
    python -m dedloc_tpu.roles.evaluate \\
        --training.dataset_path data/holdout_tokenized \\
        --training.output_dir outputs  # newest checkpoint-<step> wins \\
        --eval.max_batches 50

Deterministic: the mask RNG is fixed per run (seed flag), so two
evaluations of the same checkpoint are comparable.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from dedloc_tpu.core.config import CollaborationArguments, parse_config
from dedloc_tpu.parallel.train_step import TrainState
from dedloc_tpu.roles.common import (
    build_loss_fn,
    build_model,
    drop_collator_keys,
    force_cpu_if_requested,
)
from dedloc_tpu.utils.checkpoint import load_latest_checkpoint
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class EvalArguments:
    max_batches: int = 50
    checkpoint_path: str = ""  # explicit checkpoint dir; empty = newest in
    # training.output_dir (or fresh init when none exists — smoke mode)


@dataclass
class EvalCLIArguments(CollaborationArguments):
    eval: EvalArguments = field(default_factory=EvalArguments)


def run_eval(args: CollaborationArguments,
             extra: EvalArguments) -> dict:
    force_cpu_if_requested()
    from dedloc_tpu.roles.common import single_device_attention_impl

    impl = single_device_attention_impl(args.training.attention_impl)
    cfg, model = build_model(
        args.training.model_size,
        args.training.remat_policy,
        impl,
        args.training.vocab_size,
    )
    if not args.training.dataset_path:
        raise ValueError("--training.dataset_path: a tokenized dir is required")

    seq = min(args.training.seq_length, cfg.max_position_embeddings)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((args.training.per_device_batch_size, seq), jnp.int32),
    )["params"]

    step = 0
    if extra.checkpoint_path:
        from dedloc_tpu.utils.checkpoint import load_checkpoint

        tree, meta = load_checkpoint(extra.checkpoint_path)
        step = int(meta.get("local_step", meta.get("step", 0)))
        params = _restore(tree, params)
    else:
        resumed = load_latest_checkpoint(args.training.output_dir)
        if resumed is not None:
            step, tree, _meta = resumed
            params = _restore(tree, params)
        else:
            logger.warning("no checkpoint found; evaluating a fresh init")

    loss_fn = build_loss_fn(model)

    @jax.jit
    def eval_step(params, batch, rng):
        loss, metrics = loss_fn(params, batch, rng)
        return metrics

    from dedloc_tpu.data.disk import tokenized_dataset_batches

    batches = tokenized_dataset_batches(
        args.training.dataset_path, cfg,
        args.training.per_device_batch_size, seq, seed=args.training.seed,
    )
    rng = jax.random.PRNGKey(args.training.seed)
    total_mlm = total_sop = 0.0
    n = 0
    for _ in range(extra.max_batches):
        batch = drop_collator_keys(next(batches))
        rng, sub = jax.random.split(rng)
        metrics = eval_step(params, batch, sub)
        total_mlm += float(metrics.get("mlm_loss", metrics["loss"]))
        total_sop += float(metrics.get("sop_loss", 0.0))
        n += 1
    result = {
        "checkpoint_step": step,
        "eval_batches": n,
        "mlm_loss": total_mlm / max(n, 1),
        "mlm_perplexity": float(jnp.exp(total_mlm / max(n, 1))),
        "sop_loss": total_sop / max(n, 1),
    }
    print(json.dumps(result))
    return result


def _restore(tree, params_template):
    """Checkpoint trees hold (params, opt_state) named leaves from the
    trainer's _save; accept either that pair layout or bare params."""
    import numpy as np

    from dedloc_tpu.collaborative.optimizer import _named_to_tree

    host_template = jax.device_get(params_template)
    try:
        params, _opt = _named_to_tree(tree, (host_template, None))
        return jax.device_put(params)
    except (KeyError, TypeError, ValueError):
        pass
    # pair template failed (opt layout unknown here): strip the leading
    # tuple index from the trainer's "[0]..." key paths instead
    stripped = {
        k[3:]: v for k, v in tree.items() if k.startswith("[0]")
    }
    if stripped:
        params = _named_to_tree(stripped, host_template)
        return jax.device_put(params)
    params = _named_to_tree(tree, host_template)
    return jax.device_put(params)


def main(argv=None) -> None:
    args = parse_config(EvalCLIArguments, argv)
    run_eval(args, args.eval)


if __name__ == "__main__":
    main()
