"""Local fleet driver: coordinator + trainer + aux peers with bandwidth
tiers and spot-preemption churn.

Capability parity with the reference's AWS fleet notebook
(albert/AWS_runner.ipynb: coordinator r5.large + aux CPU peers + preemptible
g4dn spot workers, per-peer wondershaper bandwidth throttling in cell 2, and
a respawn loop for terminated spot instances in the last cell) — as an
in-framework, scriptable harness instead of cloud-specific operations:

- every peer is a subprocess running the real role entry points
  (``python -m dedloc_tpu.roles.{coordinator,trainer,aux}``) on localhost,
  pinned to CPU (DEDLOC_FORCE_CPU=1) so they never contend for the TPU chip;
- bandwidth tiers cycle over trainers and flow into the averager's
  bandwidth-weighted partitioning (the advertised-throughput capability of
  ``throughput=bandwidth``, albert/run_trainer.py:258);
- churn injection SIGKILLs a random trainer every ``churn_interval`` seconds
  (spot "terminate" semantics, InstanceInterruptionBehavior) and respawns it
  after ``respawn_delay`` — the respawned peer rejoins via the DHT and pulls
  state from peers, exercising the elasticity path end-to-end.

This doubles as the fault-injection harness SURVEY.md §4 calls the biggest
testing gap: deterministic preemption under a live collaboration. A
``testing.faults.FaultSchedule`` makes the churn fully scripted: victim
selection draws from the schedule's seeded RNG (one seed replays the whole
scenario) and an injected ``fleet.preempt`` fault with a ``target`` names
the exact trainer to kill — so "kill trainer1 on the third churn tick" is a
reproducible test, not a soak.
"""
from __future__ import annotations

import os
import random
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dedloc_tpu.core import timeutils
from dedloc_tpu.core.config import parse_config
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.testing.faults import FaultSchedule
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class FleetArguments:
    num_trainers: int = 4
    num_aux: int = 0
    # advertised Mbps per trainer, cycled (AWS notebook tiers 200/100/50)
    bandwidth_tiers: List[float] = field(
        default_factory=lambda: [200.0, 100.0, 100.0, 50.0]
    )
    churn_interval: float = 0.0  # seconds between preemptions (0 = off)
    respawn_delay: float = 1.0
    duration: float = 60.0  # wall-clock seconds (0 = until interrupted)
    experiment_prefix: str = "fleet"
    target_batch_size: int = 64
    model_size: str = "tiny"
    per_device_batch_size: int = 2
    gradient_accumulation_steps: int = 2
    output_dir: str = "fleet_out"
    coordinator_refresh_period: float = 2.0
    seed: int = 0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalFleet:
    """Process-supervisor for one local collaboration."""

    def __init__(self, args: FleetArguments, extra_trainer_flags:
                 Optional[List[str]] = None,
                 fault_schedule: Optional[FaultSchedule] = None):
        self.args = args
        self.extra_trainer_flags = list(extra_trainer_flags or [])
        self.root_port = _free_port()
        self.root_addr = f"127.0.0.1:{self.root_port}"
        self.procs: Dict[str, subprocess.Popen] = {}
        self.events: List[Dict] = []  # spawn/preempt/respawn log
        # deterministic churn: with a FaultSchedule attached, victim choice
        # draws from ITS seeded RNG (one seed replays the scenario) and
        # injected "fleet.preempt" faults can script exact victims
        self.faults = fault_schedule
        self._rng = (
            fault_schedule.rng if fault_schedule is not None
            else random.Random(args.seed)
        )
        self._harness_killed: set = set()  # pids WE killed (vs external death)
        self._crash_counts: Dict[str, int] = {}
        self.max_crash_respawns = 5  # per-peer cap on crash-loop restarts
        os.makedirs(args.output_dir, exist_ok=True)

    # ------------------------------------------------------------- spawning

    def _spawn(self, name: str, module: str, flags: List[str]) -> None:
        env = dict(os.environ, DEDLOC_FORCE_CPU="1")
        # the child duplicates the descriptor; close the parent's handle so
        # churn respawns don't leak one fd per spawn
        with open(os.path.join(self.args.output_dir, f"{name}.log"), "ab") as log:
            proc = subprocess.Popen(
                [sys.executable, "-m", module, *flags],
                env=env, stdout=log, stderr=subprocess.STDOUT,
            )
        self.procs[name] = proc
        self.events.append(
            {"t": get_dht_time(), "event": "spawn", "peer": name}
        )
        logger.info(f"spawned {name} (pid {proc.pid})")

    def _common_flags(self, initial_peers: bool = True) -> List[str]:
        a = self.args
        flags = [
            "--dht.experiment_prefix", a.experiment_prefix,
            "--dht.listen_host", "127.0.0.1",
            "--averager.averaging_expiration", "1.0",
            "--averager.min_refresh_period", "0.1",
            "--averager.default_refresh_period", "0.5",
            "--optimizer.target_batch_size", str(a.target_batch_size),
        ]
        if initial_peers:
            flags += ["--dht.initial_peers", self.root_addr]
        return flags

    def start_coordinator(self) -> None:
        a = self.args
        self._spawn(
            "coordinator",
            "dedloc_tpu.roles.coordinator",
            self._common_flags(initial_peers=False) + [
                "--dht.listen_port", str(self.root_port),
                "--coordinator.refresh_period",
                str(a.coordinator_refresh_period),
                "--coordinator.metrics_log_path",
                os.path.join(a.output_dir, "coordinator_metrics.jsonl"),
                "--coordinator.ledger_log_path",
                os.path.join(a.output_dir, "coordinator_ledger.jsonl"),
            ],
        )

    def start_trainer(self, idx: int) -> None:
        a = self.args
        tier = a.bandwidth_tiers[idx % len(a.bandwidth_tiers)]
        self._spawn(
            f"trainer{idx}",
            "dedloc_tpu.roles.trainer",
            self._common_flags() + [
                "--averager.bandwidth", str(tier),
                "--training.model_size", a.model_size,
                "--training.seq_length", "64",
                "--training.per_device_batch_size",
                str(a.per_device_batch_size),
                "--training.gradient_accumulation_steps",
                str(a.gradient_accumulation_steps),
                "--training.seed", str(a.seed + idx),
                "--training.save_steps", "0",
                "--training.output_dir",
                os.path.join(a.output_dir, f"trainer{idx}"),
                *self.extra_trainer_flags,
            ],
        )

    def start_aux(self, idx: int) -> None:
        self._spawn(
            f"aux{idx}",
            "dedloc_tpu.roles.aux",
            self._common_flags() + ["--training.model_size",
                                    self.args.model_size],
        )

    def start(self) -> None:
        self.start_coordinator()
        time.sleep(1.0)  # let the DHT root come up before peers bootstrap
        for i in range(self.args.num_trainers):
            self.start_trainer(i)
        for i in range(self.args.num_aux):
            self.start_aux(i)

    # ---------------------------------------------------------------- churn

    def preempt_random_trainer(self) -> Optional[str]:
        """Spot-terminate semantics: SIGKILL, no graceful shutdown."""
        alive = [
            n for n, p in self.procs.items()
            if n.startswith("trainer") and p.poll() is None
        ]
        if not alive:
            return None
        victim = None
        if self.faults is not None:
            fault = self.faults.fire("fleet.preempt", alive=alive)
            if fault is not None and fault.target in alive:
                victim = fault.target  # scripted kill
        if victim is None:
            victim = self._rng.choice(alive)
        self._harness_killed.add(self.procs[victim].pid)
        self.procs[victim].kill()
        self.procs[victim].wait()
        self.events.append(
            {"t": get_dht_time(), "event": "preempt", "peer": victim}
        )
        logger.info(f"preempted {victim}")
        return victim

    def respawn(self, name: str) -> None:
        idx = int(name.removeprefix("trainer"))
        self.start_trainer(idx)
        self.events[-1]["event"] = "respawn"

    def run(self) -> None:
        """Supervise until ``duration`` elapses; churn + respawn throughout
        (the notebook's spot-respawn loop)."""
        a = self.args
        deadline = (
            timeutils.monotonic() + a.duration if a.duration else None
        )
        next_churn = (
            timeutils.monotonic() + a.churn_interval
            if a.churn_interval else None
        )
        pending_respawn: List[tuple] = []  # (respawn_at, name)
        try:
            while deadline is None or timeutils.monotonic() < deadline:
                time.sleep(0.2)
                now = timeutils.monotonic()
                if next_churn is not None and now >= next_churn:
                    victim = self.preempt_random_trainer()
                    if victim is not None:
                        pending_respawn.append(
                            (now + a.respawn_delay, victim)
                        )
                    next_churn = now + a.churn_interval
                for at, name in list(pending_respawn):
                    if now >= at:
                        pending_respawn.remove((at, name))
                        self.respawn(name)
                # respawn trainers that died EXTERNALLY (OOM kill, crash) —
                # identified by pid bookkeeping, not signal numbers, so a
                # kill -9 from outside still gets a respawn while our own
                # churn preemptions (already queued above) don't double up.
                # Clean exits (returncode 0, e.g. max_local_steps reached)
                # stay down; crash loops are capped with linear backoff.
                for name, proc in list(self.procs.items()):
                    if (
                        name.startswith("trainer")
                        and proc.poll() is not None
                        and proc.pid not in self._harness_killed
                        and proc.returncode != 0
                        and not any(n == name for _, n in pending_respawn)
                    ):
                        crashes = self._crash_counts.get(name, 0) + 1
                        self._crash_counts[name] = crashes
                        self.events.append(
                            {"t": get_dht_time(), "event": "died",
                             "peer": name, "returncode": proc.returncode}
                        )
                        if crashes > self.max_crash_respawns:
                            logger.warning(
                                f"{name} crashed {crashes} times; giving up"
                            )
                            del self.procs[name]
                            continue
                        pending_respawn.append(
                            (now + a.respawn_delay * crashes, name)
                        )
        finally:
            self.stop()

    def stop(self) -> None:
        for name, proc in self.procs.items():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in self.procs.items():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        logger.info(f"fleet stopped ({len(self.events)} lifecycle events)")


def run_fleet(args: FleetArguments,
              extra_trainer_flags: Optional[List[str]] = None) -> LocalFleet:
    fleet = LocalFleet(args, extra_trainer_flags)
    fleet.start()
    fleet.run()
    return fleet


def main(argv=None) -> None:
    run_fleet(parse_config(FleetArguments, argv))


if __name__ == "__main__":
    main()
