"""Shared builders for role entry points: model, optimizer, DHT, data."""
from __future__ import annotations

import os
from typing import Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dedloc_tpu.collaborative.metrics import make_validators
from dedloc_tpu.core.config import CollaborationArguments
from dedloc_tpu.data.mlm import SpecialTokens, mask_tokens, max_predictions_for
from dedloc_tpu.dht.dht import DHT
from dedloc_tpu.models.albert import (
    AlbertConfig,
    AlbertForPreTraining,
    albert_pretraining_loss,
    albert_pretraining_loss_gathered,
)
from dedloc_tpu.optim import lamb, linear_warmup_linear_decay
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def force_cpu_if_requested() -> None:
    """Multi-process drives must not contend for the single TPU chip: set
    DEDLOC_FORCE_CPU=1 (or JAX_PLATFORMS=cpu) in each peer subprocess (the
    chip is exclusive). JAX_PLATFORMS must be re-applied through jax.config
    because a container sitecustomize may pin the TPU plugin after env-var
    processing — the env var alone silently loses."""
    if (
        os.environ.get("DEDLOC_FORCE_CPU") == "1"
        or os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
    ):
        jax.config.update("jax_platforms", "cpu")


def build_model(
    model_size: str,
    remat_policy: str = "",
    attention_impl: str = "",
    vocab_size: int = 0,
    ring_mesh=None,
    pipe_mesh=None,
    pipe_microbatches: int = 0,
    moe_experts: int = 0,
    moe_mesh=None,
    moe_capacity_factor: float = 0.0,
    moe_aux_weight: float = -1.0,
) -> Tuple[AlbertConfig, AlbertForPreTraining]:
    overrides = {}
    if remat_policy:
        overrides["remat_policy"] = remat_policy
        from dedloc_tpu.models.albert import fused_ln_for_policy

        overrides["fused_ln"] = fused_ln_for_policy(remat_policy)
    if attention_impl:
        overrides["attention_impl"] = attention_impl
    if vocab_size:
        overrides["vocab_size"] = vocab_size
    if ring_mesh is not None:
        overrides["ring_mesh"] = ring_mesh
    if pipe_mesh is not None:
        overrides["pipe_mesh"] = pipe_mesh
        overrides["pipe_microbatches"] = pipe_microbatches
    if moe_experts:
        overrides["moe_experts"] = moe_experts
        if moe_mesh is not None:
            overrides["moe_mesh"] = moe_mesh
        if moe_capacity_factor > 0:
            overrides["moe_capacity_factor"] = moe_capacity_factor
        if moe_aux_weight >= 0:
            overrides["moe_aux_weight"] = moe_aux_weight
    cfg = AlbertConfig.named(model_size)(**overrides)
    return cfg, AlbertForPreTraining(cfg)


def build_optimizer(args: CollaborationArguments):
    """LAMB + linear warmup/decay (reference recipe,
    albert/arguments.py:104-121 via run_trainer.py:73-100)."""
    schedule = linear_warmup_linear_decay(
        args.training.learning_rate,
        warmup_steps=args.training.warmup_steps,
        total_steps=args.training.total_steps,
    )
    return lamb(
        learning_rate=schedule,
        weight_decay=args.training.weight_decay,
        clamp_value=args.training.clamp_value,
        max_grad_norm=args.training.max_grad_norm,
    )


def build_flat_opt_factory(args: CollaborationArguments):
    """(spec, params) -> optim.flat.FlatLamb for the SAME hyperparameters
    as ``build_optimizer`` — the fused flat apply's math twin of the
    per-leaf chain (--optimizer.flat_apply; equivalence locked by
    tests/test_optim.py). Returns a factory because the TreeLayout spec
    only exists once the first gradient tree does."""
    schedule = linear_warmup_linear_decay(
        args.training.learning_rate,
        warmup_steps=args.training.warmup_steps,
        total_steps=args.training.total_steps,
    )

    def factory(spec, params):
        from dedloc_tpu.optim.flat import FlatLamb, tree_flags
        from dedloc_tpu.optim.lamb import albert_weight_decay_mask

        flags = tree_flags(
            albert_weight_decay_mask(params), params,
            [name for name, _shape, _dtype in spec],
        )
        return FlatLamb(
            spec, flags, schedule,
            weight_decay=args.training.weight_decay,
            clamp_value=args.training.clamp_value,
            max_grad_norm=args.training.max_grad_norm,
        )

    return factory


def single_device_attention_impl(impl: str) -> str:
    """Attention impl for shape-only / single-device roles (aux template
    fallback, evaluate): 'ring' needs the trainer's sequence-parallel mesh
    to trace, but every impl is exact and shares one param tree, so it
    safely degrades to 'dense' outside the trainer."""
    return "dense" if impl == "ring" else impl


def build_authorizer(args: CollaborationArguments):
    """Gated-run handshake (contributor notebook cell 2 / huggingface_auth
    capability): when --auth.username is set, fetch a signed access token
    from the AuthService (default host: the first initial peer, where the
    coordinator attaches it) and return (authorizer, authority_public_key);
    (None, None) for open runs."""
    if not args.auth.username:
        return None, None
    spec = args.auth.endpoint or (
        args.dht.initial_peers[0] if args.dht.initial_peers else ""
    )
    if not spec:
        raise ValueError(
            "--auth.username given but no --auth.endpoint and no "
            "--dht.initial_peers to default to"
        )
    host, _, port = spec.rpartition(":")
    from dedloc_tpu.core.auth import remote_auth_handshake

    authorizer = remote_auth_handshake(
        (host, int(port)), args.auth.username, args.auth.credential
    )
    from dedloc_tpu.core.timeutils import get_dht_time

    remaining = authorizer._token.expiration_time - get_dht_time()
    logger.info(
        f"authorized as {args.auth.username!r} "
        f"(token valid for {remaining:.0f}s; auto-refreshes)"
    )
    return authorizer, authorizer.authority_public_key


def build_dht(
    args: CollaborationArguments,
    client_mode: Optional[bool] = None,
    private_key=None,
):
    """DHT with the signed-metrics validator chain. Returns (dht, subkey).

    ``private_key`` lets a gated peer sign DHT records with its TOKEN key
    (pass ``authorizer.local_private_key``): the owner-tag subkey then
    digests to the same peer id matchmaking verified from the token, so
    contribution-ledger records are identity-bound end to end
    (telemetry/ledger.subkey_owner_id). Open runs leave it None and get a
    fresh per-process key."""
    validators, public_key = make_validators(
        args.dht.experiment_prefix, private_key
    )
    dht = DHT(
        initial_peers=args.dht.initial_peers,
        start=True,
        listen_host=args.dht.listen_host,
        listen_port=args.dht.listen_port,
        client_mode=args.dht.client_mode if client_mode is None else client_mode,
        record_validators=validators,
        advertised_host=args.dht.advertised_host or None,
    )
    return dht, public_key


def checkpoint_kwargs(args, public_key: bytes) -> Dict:
    """Resolve ``--checkpoint.*`` knobs into CollaborativeOptimizer kwargs
    (docs/fleet.md restart runbook). THE one resolution point for the shard
    cache dir: empty = ``<output_dir>/shard_cache`` (restores resume across
    process restarts), "none" = no cache."""
    ck = args.checkpoint
    if ck.cache_dir == "none":
        cache_dir = None
    else:
        cache_dir = ck.cache_dir or os.path.join(
            args.training.output_dir, "shard_cache"
        )
    return dict(
        checkpoint_shard_size=ck.shard_size,
        checkpoint_fetch_parallelism=ck.fetch_parallelism,
        checkpoint_max_providers=ck.providers,
        checkpoint_dir=cache_dir,
        # catalog announcements ride the peer's SIGNED metrics subkey, so
        # the existing validator chain signature-binds them to this peer
        signed_subkey=public_key,
    )


def configure_role_telemetry(args, public_key: bytes):
    """Install the process-global swarm-telemetry registry for a role
    (docs/observability.md, ``--telemetry.*`` knobs). THE one place the
    peer label is derived: the sha1 fingerprint ``fetch_metrics`` computes
    from the signed metrics subkey, so per-peer event logs and the
    coordinator's swarm-health rows join on the same id. Returns
    ``(registry_or_None, close_fn)``; call ``close_fn()`` on shutdown."""
    import hashlib

    from dedloc_tpu import telemetry

    tele = telemetry.configure(
        args.telemetry, peer=hashlib.sha1(public_key).hexdigest()[:12]
    )

    def close() -> None:
        if tele is not None:
            tele.close()
            telemetry.uninstall(tele)

    return tele, close


def build_loss_fn(model: AlbertForPreTraining) -> Callable:
    """Gathered masked-position loss when the batch carries ``mlm_positions``
    (the fast TPU layout); dense per-position loss otherwise. With an MoE
    config the Switch load-balancing aux loss (sowed into the "losses"
    collection by the encoder) is added at ``cfg.moe_aux_weight``."""
    moe = getattr(model.cfg, "moe_experts", 0) > 0

    def loss_fn(params, batch, rng):
        gathered = "mlm_positions" in batch
        apply_kwargs = dict(
            mlm_positions=batch["mlm_positions"] if gathered else None,
        )
        if moe:
            (mlm_logits, sop_logits), mutated = model.apply(
                {"params": params},
                batch["input_ids"],
                batch["attention_mask"],
                batch["token_type_ids"],
                mutable=("losses",),
                **apply_kwargs,
            )
        else:
            mlm_logits, sop_logits = model.apply(
                {"params": params},
                batch["input_ids"],
                batch["attention_mask"],
                batch["token_type_ids"],
                **apply_kwargs,
            )
        if gathered:
            loss, metrics = albert_pretraining_loss_gathered(
                mlm_logits,
                sop_logits,
                batch["mlm_label_ids"],
                batch["mlm_weights"],
                batch["sop_labels"],
            )
        else:
            loss, metrics = albert_pretraining_loss(
                mlm_logits, sop_logits, batch["mlm_labels"], batch["sop_labels"]
            )
        if moe:
            aux = sum(
                jnp.sum(leaf)
                for leaf in jax.tree_util.tree_leaves(mutated["losses"])
            )
            loss = loss + model.cfg.moe_aux_weight * aux
            metrics = dict(metrics, moe_aux=aux)
        return loss, metrics

    return loss_fn


def synthetic_mlm_batches(
    cfg: AlbertConfig,
    batch_size: int,
    seq_length: int,
    seed: int,
) -> Iterator[Dict[str, np.ndarray]]:
    """Synthetic fixture stream (SURVEY.md §4 SyntheticImageDataset pattern):
    random token documents, real masking path. Deterministic per peer seed."""
    rng = np.random.default_rng(seed)
    tokens = SpecialTokens(vocab_size=cfg.vocab_size)
    seq_length = min(seq_length, cfg.max_position_embeddings)
    max_predictions = max_predictions_for(seq_length)
    while True:
        ids = rng.integers(
            tokens.num_reserved, cfg.vocab_size, (batch_size, seq_length)
        ).astype(np.int32)
        batch = {
            "input_ids": ids,
            "attention_mask": np.ones((batch_size, seq_length), np.int32),
            "token_type_ids": np.zeros((batch_size, seq_length), np.int32),
            "special_tokens_mask": np.zeros((batch_size, seq_length), np.int32),
            "sop_labels": rng.integers(0, 2, (batch_size,)).astype(np.int32),
        }
        yield mask_tokens(batch, rng, tokens, max_predictions=max_predictions)


def drop_collator_keys(batch: Dict[str, np.ndarray]) -> Dict[str, jnp.ndarray]:
    """Keep only what the jitted loss consumes (static arg structure)."""
    if "mlm_positions" in batch:
        keep = (
            "input_ids",
            "attention_mask",
            "token_type_ids",
            "mlm_positions",
            "mlm_label_ids",
            "mlm_weights",
            "sop_labels",
        )
    else:
        keep = (
            "input_ids",
            "attention_mask",
            "token_type_ids",
            "mlm_labels",
            "sop_labels",
        )
    return {k: jnp.asarray(batch[k]) for k in keep}
