"""ZeRO-style optimizer-state sharding over the data axis.

The reference ships ZeRO/FSDP only as unused stock options
(swav/vissl/vissl/trainer/train_zero_task.py, ClassyVision optim/zero.py —
SURVEY.md §2.5 "present as unused options"); here the capability is native:
optimizer moments (the 2x params HBM of LAMB/Adam) shard over the mesh's
data axis, and XLA's GSPMD inserts the gathers the update needs. Params and
gradients stay replicated (the collaborative averager works on full host
gradients), so this is ZeRO stage-1: state memory / n_devices.

Usage::

    mesh = make_mesh(8)
    state = TrainState.create(params, tx)
    opt_sh = opt_state_shardings(state.opt_state, mesh)
    state = state.replace(opt_state=shard_opt_state(state.opt_state, mesh))
    apply = make_apply_step(tx, mesh=mesh, opt_state_sharding=opt_sh)
"""
from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _spec_for_leaf(leaf, mesh: Mesh, axis: str) -> P:
    """Shard the largest dimension divisible by the axis size; scalars and
    indivisible shapes replicate."""
    n = mesh.shape[axis]
    shape = getattr(leaf, "shape", ())
    if not shape:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % n == 0 and shape[d] >= n:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def opt_state_shardings(
    opt_state: Any, mesh: Mesh, axis: Any = "data", tp_rules: Any = None
) -> Any:
    """NamedSharding pytree for an optimizer state — feed this to
    ``make_apply_step(opt_state_sharding=...)``.

    ``axis``: ZeRO-1 data-axis sharding (None disables). ``tp_rules``:
    tensor-parallel path rules (parallel/sharding.py) — moment leaves whose
    paths match (mu/nu mirror the param tree's paths) follow their param's
    TP layout, and ZeRO applies only to what TP left replicated."""
    from jax.sharding import PartitionSpec as P

    flat, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
    rule_axes = {
        name
        for _, spec in (tp_rules or ())
        for entry in tuple(spec)
        for name in (entry if isinstance(entry, tuple) else (entry,))
        if name is not None
    }
    out = []
    for path, leaf in flat:
        spec = P()
        if tp_rules is not None and rule_axes & set(mesh.axis_names):
            from dedloc_tpu.parallel.sharding import spec_for_path

            spec = spec_for_path(jax.tree_util.keystr(path), tp_rules)
        if spec == P() and axis is not None:
            spec = _spec_for_leaf(leaf, mesh, axis)
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_opt_state(opt_state: Any, mesh: Mesh, axis: str = "data") -> Any:
    """Device-put the optimizer state with moments sharded over ``axis``."""
    return jax.tree.map(
        lambda l: jax.device_put(
            l, NamedSharding(mesh, _spec_for_leaf(l, mesh, axis))
        ),
        opt_state,
    )


def opt_state_bytes_per_device(opt_state: Any, mesh: Mesh,
                               axis: str = "data") -> int:
    """Post-sharding per-device footprint (for memory planning/logging)."""
    n = mesh.shape[axis]
    total = 0
    for leaf in jax.tree.leaves(opt_state):
        size = int(np.prod(getattr(leaf, "shape", ()) or (1,)))
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        spec = _spec_for_leaf(leaf, mesh, axis)
        sharded = any(s == axis for s in spec)
        total += size * itemsize // (n if sharded else 1)
    return total
