"""Expert parallelism: Switch-style mixture-of-experts FFN, TPU-native.

The reference repo has no MoE (SURVEY.md §2.5: EP "out of scope" — though
hivemind, the library it builds on, began life as a decentralized
mixture-of-experts system). This module supplies the EP axis the TPU
framework would use for sparse scaling: experts shard over a mesh axis and
the token shuffle lowers to XLA all-to-alls, in the classic GShard/Switch
dispatch-einsum formulation — no hand-written collectives, the sharding
annotations alone place the communication on ICI.

Design (top-1 / Switch routing, jit-exact and static-shaped):
- router logits -> softmax gate, top-1 expert per token;
- capacity C = ceil(T / E · capacity_factor): each expert processes at most
  C tokens per batch, tokens beyond capacity fall through on the residual
  path (standard Switch behavior; static shapes are what the MXU wants);
- dispatch/combine as one-hot einsums: ``[T,E,C]`` masks against token
  activations — under pjit with ``wi/wo`` sharded ``P(axis)`` and tokens
  sharded over data, XLA inserts the all-to-alls;
- auxiliary load-balancing loss (mean gate · mean assignment per expert,
  scaled by E) exactly as in Switch, returned for the trainer to add.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    ffn_size: int
    num_experts: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32


def init_moe_params(cfg: MoEConfig, rng: jax.Array) -> Dict[str, jnp.ndarray]:
    """Router + per-expert FFN stacks (leading expert axis — shard it with
    ``expert_param_sharding`` so each device holds E/n experts)."""
    kr, ki, ko = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(cfg.hidden_size)
    scale_out = 1.0 / math.sqrt(cfg.ffn_size)
    return {
        "router": (
            jax.random.normal(kr, (cfg.hidden_size, cfg.num_experts)) * scale_in
        ).astype(jnp.float32),
        "wi": (
            jax.random.normal(
                ki, (cfg.num_experts, cfg.hidden_size, cfg.ffn_size)
            ) * scale_in
        ).astype(cfg.dtype),
        "wo": (
            jax.random.normal(
                ko, (cfg.num_experts, cfg.ffn_size, cfg.hidden_size)
            ) * scale_out
        ).astype(cfg.dtype),
    }


def expert_param_sharding(mesh: Mesh, axis: str = "expert"):
    """Pytree of shardings for ``init_moe_params`` output: experts split
    over ``axis``, the router replicated."""
    return {
        "router": NamedSharding(mesh, P()),
        "wi": NamedSharding(mesh, P(axis)),
        "wo": NamedSharding(mesh, P(axis)),
    }


def moe_ffn(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,  # [T, H] tokens (flatten batch x seq first)
    cfg: MoEConfig,
    mesh: Optional[Mesh] = None,
    axis: str = "expert",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (y [T, H], aux_loss scalar). Over-capacity tokens pass
    through as zeros (add the residual connection outside).

    With ``mesh``, intermediate expert blocks are sharding-constrained to
    ``P(axis)`` so the dispatched tokens travel to their expert's device
    (the all-to-all) and the FFN runs expert-local.
    """
    T = x.shape[0]
    E = cfg.num_experts
    capacity = max(1, math.ceil(T / E * cfg.capacity_factor))

    gate_logits = x.astype(jnp.float32) @ params["router"]  # [T, E]
    gates = jax.nn.softmax(gate_logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)  # [T]
    gate = jnp.take_along_axis(gates, expert_idx[:, None], axis=-1)[:, 0]

    assign = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, E]
    # position of each token within its expert's queue (0-based)
    position = jnp.cumsum(assign, axis=0) * assign - 1.0
    in_capacity = (position < capacity) & (assign > 0)
    pos_in_expert = jnp.clip(position, 0, capacity - 1).astype(jnp.int32)

    # Switch aux loss: E * Σ_e (fraction of tokens on e) · (mean gate for e)
    density = jnp.mean(assign, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    aux_loss = E * jnp.sum(density * density_proxy)

    # [T, E, C] dispatch mask (in_capacity already excludes non-assigned
    # slots); combine carries the gate weight
    dispatch = (
        in_capacity.astype(jnp.float32)[:, :, None]
        * jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)
    )
    combine = dispatch * gate[:, None, None]

    # tokens -> expert blocks (the all-to-all when experts are sharded)
    expert_in = jnp.einsum(
        "tec,th->ech", dispatch.astype(cfg.dtype), x.astype(cfg.dtype)
    )
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(axis))
        )
    h = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", expert_in, params["wi"]))
    expert_out = jnp.einsum("ecf,efh->ech", h, params["wo"])
    if mesh is not None:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(axis))
        )
    # expert blocks -> tokens (the reverse all-to-all), gate-weighted
    y = jnp.einsum(
        "tec,ech->th", combine.astype(cfg.dtype), expert_out
    )
    return y.astype(x.dtype), aux_loss
