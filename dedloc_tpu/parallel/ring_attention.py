"""Long-context attention: blockwise (memory-efficient) and ring (sequence-
parallel) variants.

The reference has NO long-context support (SURVEY.md §5: seq fixed at 512,
no ring/blockwise/Ulysses anywhere) — this module is a TPU-native extension
that makes sequence length a first-class scaling axis:

- ``blockwise_attention``: online-softmax attention computed in KV blocks
  under ``lax.scan`` — activation memory O(S·block) instead of O(S²), the
  single-device long-context workhorse (same math as FlashAttention).
- ``ring_attention``: shard the sequence over a mesh axis; each device holds
  S/n of Q, K, V and rotates its KV shard around the ring with
  ``lax.ppermute`` while accumulating online-softmax partials for its local
  queries. Peak memory O((S/n)²) per device and the KV transfer overlaps
  compute steps; collectives ride ICI. Exact (bitwise-stable softmax
  rescaling), not an approximation.

Both are bidirectional (ALBERT-style); an additive bias [B, S_kv] travels
with the KV shards.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax (< 0.4.5x) exposes it under experimental
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _block_update(
    q: jnp.ndarray,  # [B, Sq, H, D]
    k: jnp.ndarray,  # [B, Skv, H, D]
    v: jnp.ndarray,  # [B, Skv, H, D]
    bias: Optional[jnp.ndarray],  # [B, Skv] additive (0 keep / -inf drop)
    acc: jnp.ndarray,  # [B, Sq, H, D] fp32 running numerator
    row_max: jnp.ndarray,  # [B, Sq, H] fp32 running max
    row_sum: jnp.ndarray,  # [B, Sq, H] fp32 running denominator
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-softmax accumulation step against a KV block."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias[:, None, None, :].astype(jnp.float32)
    block_max = jnp.max(s, axis=-1)  # [B, H, Sq]
    new_max = jnp.maximum(row_max, block_max.transpose(0, 2, 1))
    correction = jnp.exp(row_max - new_max)
    p = jnp.exp(s - new_max.transpose(0, 2, 1)[..., None])  # [B, H, Sq, K]
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    acc = acc * correction[..., None] + pv
    row_sum = row_sum * correction + jnp.sum(p, axis=-1).transpose(0, 2, 1)
    return acc, new_max, row_sum


def blockwise_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,  # [B, S] additive kv-position bias
    block_size: int = 512,
) -> jnp.ndarray:
    """Exact attention with KV processed in blocks via lax.scan."""
    b, s, h, d = q.shape
    num_blocks = max(1, s // block_size)
    assert s % num_blocks == 0, "seq length must divide block size grid"
    bs = s // num_blocks
    k_blocks = k.reshape(b, num_blocks, bs, h, d).transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(b, num_blocks, bs, h, d).transpose(1, 0, 2, 3, 4)
    bias_blocks = (
        bias.reshape(b, num_blocks, bs).transpose(1, 0, 2)
        if bias is not None
        else None
    )

    acc = jnp.zeros((b, s, h, d), jnp.float32)
    row_max = jnp.full((b, s, h), NEG_INF, jnp.float32)
    row_sum = jnp.zeros((b, s, h), jnp.float32)

    def body(carry, blocks):
        acc, row_max, row_sum = carry
        if bias_blocks is not None:
            kb, vb, bb = blocks
        else:
            kb, vb = blocks
            bb = None
        acc, row_max, row_sum = _block_update(q, kb, vb, bb, acc, row_max, row_sum)
        return (acc, row_max, row_sum), None

    xs = (k_blocks, v_blocks, bias_blocks) if bias is not None else (k_blocks, v_blocks)
    (acc, row_max, row_sum), _ = jax.lax.scan(body, (acc, row_max, row_sum), xs)
    return (acc / row_sum[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, S, H, D] — S GLOBAL; sharded over ``axis`` by caller
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,  # [B, S]
    *,
    mesh: Mesh,
    axis: str = "seq",
) -> jnp.ndarray:
    """Sequence-parallel exact attention over a ring of devices.

    Inputs/outputs are GLOBAL arrays; shard them over ``axis`` on the S
    dimension (``P(None, axis)``...) before calling for zero relayout. Inside
    shard_map each device starts with its local KV shard and passes it to the
    next ring neighbour each step (lax.ppermute over ICI), accumulating
    online-softmax partials for its resident queries.
    """
    n = mesh.shape[axis]

    def local(q_l, k_l, v_l, bias_l):
        b, s_l, h, d = q_l.shape
        acc = jnp.zeros((b, s_l, h, d), jnp.float32)
        row_max = jnp.full((b, s_l, h), NEG_INF, jnp.float32)
        row_sum = jnp.zeros((b, s_l, h), jnp.float32)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(i, carry):
            acc, row_max, row_sum, k_cur, v_cur, bias_cur = carry
            acc, row_max, row_sum = _block_update(
                q_l, k_cur, v_cur, bias_cur, acc, row_max, row_sum
            )
            # rotate the KV shard to the next neighbour (skip after last use)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            bias_nxt = (
                jax.lax.ppermute(bias_cur, axis, perm)
                if bias_cur is not None
                else None
            )
            return acc, row_max, row_sum, k_nxt, v_nxt, bias_nxt

        carry = (acc, row_max, row_sum, k_l, v_l, bias_l)
        for i in range(n):  # static unroll: n is a mesh constant
            carry = body(i, carry)
        acc, row_max, row_sum = carry[:3]
        return (acc / row_sum[..., None]).astype(q_l.dtype)

    # the batch dim rides the mesh's data axis when one exists (dp x sp
    # composition: the trainer shards batches P("data", "seq")); a pure-sp
    # mesh replicates B
    b_axis = "data" if "data" in dict(mesh.shape) else None
    qkv_spec = P(b_axis, axis, None, None)
    bias_spec = P(b_axis, axis)
    if bias is None:
        fn = shard_map(
            lambda a, b_, c: local(a, b_, c, None),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
        )
        return fn(q, k, v)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, bias_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, bias)


def dense_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Reference O(S²) attention for testing equivalence."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if bias is not None:
        s = s + bias[:, None, None, :].astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
