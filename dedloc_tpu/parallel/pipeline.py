"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference has NO pipeline parallelism (SURVEY.md §2.5: DP is its only
parallelism; PP listed "not required") — this module is a TPU-native
extension so deep stacks can shard *layers* across devices when tensor
parallelism alone runs out of per-device memory. Design follows the
scaling-book recipe rather than torch-style stage processes: one SPMD
program under ``shard_map``, activations hopping stage→stage with
``lax.ppermute`` while every device computes in lockstep, autodiff
differentiating straight through the loop (the backward pipeline is the
transposed forward — ppermute's transpose is the reverse hop, so GPipe's
reverse schedule falls out of ``jax.grad`` for free).

Schedule: classic GPipe fill-and-drain. With S stages and M microbatches
the loop runs T = M + S - 1 ticks; stage s computes microbatch m at tick
s + m. Bubble fraction = (S-1)/T, amortized by raising M (the collaborative
trainer accumulates many micro-batches per optimizer step anyway, so M is
naturally large here).

Stage parameters may be
- stacked:   every leaf carries a leading ``[S, ...]`` stage axis, sharded
  ``P(axis)`` over the pipe axis so each device holds only its stage's
  slice (the memory win PP exists for), or
- shared:    no stage axis (ALBERT's cross-layer weight sharing) — the same
  params replicated to every stage; each stage then applies the shared
  block a slice of the iteration count.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map

    _SHARD_MAP_KWARGS = {}
    _pcast = jax.lax.pcast
except (ImportError, AttributeError):
    # older jax (< 0.4.5x): shard_map lives under experimental and has no
    # varying-manual-axes type system (lax.pcast) — disable its replication
    # checker instead, which is what the pcast annotation exists to satisfy
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_KWARGS = {"check_rep": False}

    def _pcast(x, axes, to=None):
        return x


def stage_param_sharding(mesh: Mesh, axis: str = "pipe") -> NamedSharding:
    """Sharding for stacked stage params: leading stage axis over ``axis``."""
    return NamedSharding(mesh, P(axis))


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    microbatches: jnp.ndarray,
    mesh: Mesh,
    axis: str = "pipe",
    stacked_params: bool = True,
    micro_spec: P = P(),
) -> jnp.ndarray:
    """Run ``microbatches`` through S pipelined stages; returns ``[M, ...]``.

    stage_fn(params_s, x) -> y must keep the activation structure (a
    transformer block, a stage of them, ...). ``microbatches`` is ``[M, ...]``
    with M the microbatch count — an array or a pytree of arrays sharing the
    leading M (e.g. ``(hidden, attn_bias)`` when each microbatch carries its
    own mask); non-leading dims may additionally be sharded over other mesh
    axes (e.g. batch over "data") — the pipe loop is independent of them. With ``stacked_params`` every leaf of ``stage_params`` has a
    leading ``[S, ...]`` axis (place it with ``stage_param_sharding`` so the
    slice lives on its stage's device); otherwise params are taken as shared
    and replicated. ``micro_spec`` shards the microbatch array's *other*
    dims over other mesh axes (e.g. ``P(None, "data")`` for a ``[M, B, ...]``
    input batch-sharded over data parallelism); it must not use ``axis``.

    Outputs are returned with the same spec as the inputs: replicated over
    the pipe axis (one psum at the end — costs one activation-sized transfer
    per microbatch; cheap next to the stage compute it ships).
    """
    spec_axes = [
        name
        for entry in tuple(micro_spec)
        for name in (entry if isinstance(entry, tuple) else (entry,))
    ]
    if axis in spec_axes:
        raise ValueError(f"micro_spec must not shard over the pipe axis {axis!r}")
    n_stages = mesh.shape[axis]
    micro_leaves = jax.tree_util.tree_leaves(microbatches)
    n_micro = micro_leaves[0].shape[0]
    if any(leaf.shape[0] != n_micro for leaf in micro_leaves):
        raise ValueError(
            "every microbatch leaf needs the same leading microbatch count; "
            f"got {[leaf.shape[0] for leaf in micro_leaves]}"
        )
    if stacked_params:
        for path, leaf in jax.tree_util.tree_leaves_with_path(stage_params):
            if leaf.shape[:1] != (n_stages,):
                # a multiple of n_stages would legally split under P(axis)
                # and then silently drop all but one stage per device
                raise ValueError(
                    f"stacked stage params need leading dim {n_stages} "
                    f"(= mesh axis {axis!r}); got {leaf.shape} at "
                    f"{jax.tree_util.keystr(path)}"
                )

    param_spec = (
        jax.tree_util.tree_map(lambda _: P(axis), stage_params)
        if stacked_params
        else jax.tree_util.tree_map(lambda _: P(), stage_params)
    )
    # other mesh axes (data, model, ...) pass through untouched via
    # micro_spec; the pipe loop itself never shards the microbatch array
    in_specs = (param_spec, micro_spec)
    out_spec = micro_spec

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    tmap = jax.tree_util.tree_map

    def pipelined(params, micro):
        stage = jax.lax.axis_index(axis)
        if stacked_params:
            # shard_map hands each device its [1, ...] stage slice
            params = tmap(lambda p: p[0], params)

        # T = M + S - 1 ticks: feed zeros during the drain phase (stage 0
        # ignores them once m >= M). micro may be a pytree (e.g. an
        # (activation, per-microbatch-bias) pair) — every op below maps
        # leaf-wise.
        feed = tmap(
            lambda m: jnp.concatenate(
                [m, jnp.zeros((n_stages - 1,) + m.shape[1:], m.dtype)], axis=0
            ),
            micro,
        )

        def tick(buf, x_in):
            # stage 0 ingests the next microbatch; others take the hop input
            x = tmap(lambda i, b: jnp.where(stage == 0, i, b), x_in, buf)
            y = stage_fn(params, x)
            # last stage's result this tick IS a finished microbatch during
            # the drain window; everyone else forwards theirs down the pipe
            hopped = jax.lax.ppermute(y, axis, fwd_perm)
            done = tmap(
                lambda v: jnp.where(stage == n_stages - 1, v, jnp.zeros_like(v)),
                y,
            )
            return hopped, done

        # the carry is device-varying (each stage holds a different
        # activation) while the zeros literal is replicated — mark it so
        # the scan's carry type is stable under shard_map's VMA checks
        buf0 = tmap(
            lambda m: _pcast(
                jnp.zeros_like(m[0]), (axis,), to="varying"
            ),
            micro,
        )
        _, dones = jax.lax.scan(tick, buf0, feed)
        # microbatch m finishes at tick m + S - 1 on the last stage; every
        # other device contributed zeros, so a psum replicates the result
        outs = tmap(lambda d: d[n_stages - 1 : n_stages - 1 + n_micro], dones)
        return jax.lax.psum(outs, axis)

    return shard_map(
        pipelined, mesh=mesh, in_specs=in_specs, out_specs=out_spec,
        **_SHARD_MAP_KWARGS,
    )(stage_params, microbatches)


def shared_stage_fn(
    block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray], iters_per_stage: int
) -> Callable[[Any, jnp.ndarray], jnp.ndarray]:
    """ALBERT-style stage: apply ONE shared block ``iters_per_stage`` times
    (cross-layer weight sharing means stages differ only in position, models
    /albert.py encoder scan). Use with ``stacked_params=False``."""

    def stage(params, x):
        def body(h, _):
            return block_fn(params, h), None

        out, _ = jax.lax.scan(body, x, None, length=iters_per_stage)
        return out

    return stage
