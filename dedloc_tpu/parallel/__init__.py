from dedloc_tpu.parallel.mesh import make_mesh, shard_batch, replicate
from dedloc_tpu.parallel.moe import (
    MoEConfig,
    expert_param_sharding,
    init_moe_params,
    moe_ffn,
)
from dedloc_tpu.parallel.pipeline import (
    pipeline_apply,
    shared_stage_fn,
    stage_param_sharding,
)
from dedloc_tpu.parallel.train_step import (
    TrainState,
    make_accumulate_step,
    make_apply_step,
    make_local_train_step,
    params_are_finite,
)
