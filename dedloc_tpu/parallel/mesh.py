"""Device-mesh construction and sharding helpers.

The TPU-native replacement for the reference's two communication worlds
(SURVEY.md §1/§2.6): a pod slice is ONE logical collaborative peer; gradient
averaging inside the slice is the psum XLA inserts for the sharded-batch mean
over ICI; the asyncio averager only ever runs BETWEEN slices.

Axes:
  data  — pure data parallelism (the only parallelism the reference has)
  model — reserved for tensor-parallel shardings of large models (free via
          pjit; not required for capability parity, see SURVEY.md §2.5)
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = ("data",),
    shape: Optional[Sequence[int]] = None,
    device_offset: int = 0,
) -> Mesh:
    """``device_offset`` lets several in-process "slices" carve disjoint
    device ranges out of one virtual mesh (multi-slice tests without
    multi-host hardware)."""
    all_devices = jax.devices()
    n = len(all_devices) - device_offset if n_devices is None else n_devices
    if n <= 0 or device_offset < 0 or device_offset + n > len(all_devices):
        raise ValueError(
            f"device_offset {device_offset} + n_devices {n} exceeds the "
            f"{len(all_devices)} available devices (or is non-positive)"
        )
    devices = all_devices[device_offset : device_offset + n]
    if shape is None:
        shape = [len(devices)] + [1] * (len(axis_names) - 1)
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def shard_batch(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for a batch pytree: leading axis split over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def put_batch(batch, mesh: Mesh, axis: str = "data",
              seq_axis=None, seq_length=None):
    """Device-put a host batch with the batch axis sharded over ``axis``.

    With ``seq_axis``/``seq_length`` (sequence-parallel slices), leaves whose
    second dim is the sequence go straight to P(axis, seq_axis) — the host
    ships only the S/sp slice per device instead of replicating the full
    sequence and resharding on-device."""
    sharding = shard_batch(mesh, axis)
    if seq_axis is None:
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
    seq_sharding = NamedSharding(mesh, P(axis, seq_axis))

    def _put(x):
        if x.ndim >= 2 and seq_length and x.shape[1] == seq_length:
            return jax.device_put(x, seq_sharding)
        return jax.device_put(x, sharding)

    return jax.tree.map(_put, batch)
