"""Parameter partition rules: tensor/sequence parallelism via pjit shardings.

The reference has no TP/SP (SURVEY.md §2.5 — it scales batch, not model or
sequence), but the TPU-native design gets both almost for free: annotate the
parameter layout over a named mesh axis and let XLA insert the ICI collectives.
This module provides path-regex → PartitionSpec rule matching (the idiom used
by most public JAX LLM codebases) plus the canonical Megatron-style rule set
for the ALBERT family:

  column-parallel:  qkv projections, ffn up-projection  → shard output dim
  row-parallel:     attention output, ffn down-projection → shard input dim
  vocab-parallel:   word-embedding table and tied MLM decoder bias

With these rules a single jitted train step runs dp×tp×sp over one
``Mesh(("data", "model", "seq"))``; gradients of replicated params get the
psum XLA inserts automatically, so no hand-written collective code exists
anywhere in the training path.
"""
from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


Rules = Sequence[Tuple[str, P]]

# Megatron-style sharding of the shared ALBERT block. Patterns match against
# jax.tree_util.keystr paths like "['albert']['encoder']...['query']['kernel']".
ALBERT_TP_RULES: Rules = (
    (r"\['attention'\]\['(query|key|value)'\]\['kernel'\]", P(None, "model")),
    (r"\['attention'\]\['(query|key|value)'\]\['bias'\]", P("model")),
    (r"\['attention'\]\['dense'\]\['kernel'\]", P("model", None)),
    (r"\['ffn'\]\['kernel'\]", P(None, "model")),
    (r"\['ffn'\]\['bias'\]", P("model")),
    (r"\['ffn_output'\]\['kernel'\]", P("model", None)),
    (r"\['word_embeddings'\]\['embedding'\]", P("model", None)),
    (r"\['mlm_bias'\]", P("model")),
)

# Expert parallelism for the Switch-MoE FFN variant (parallel/moe.py,
# models/albert.py _moe_ffn): the expert-stacked FFN weights shard their
# leading expert axis over the mesh's "expert" axis; the router stays
# replicated. Concatenate with ALBERT_TP_RULES when both axes exist.
ALBERT_EP_RULES: Rules = (
    (r"\['moe_(wi|wo)'\]", P("expert")),
)


def spec_for_path(path_str: str, rules: Rules) -> P:
    for pattern, spec in rules:
        if re.search(pattern, path_str):
            return spec
    return P()


def partition_specs(params: Any, rules: Rules = ALBERT_TP_RULES) -> Any:
    """Pytree of PartitionSpec matching ``params``, by path-regex rules."""

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = [spec_for_path(jax.tree_util.keystr(p), rules) for p, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_params(params: Any, mesh: Mesh, rules: Rules = ALBERT_TP_RULES) -> Any:
    """device_put params with TP shardings; downstream jit propagates them."""

    specs = partition_specs(params, rules)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def mesh_shape_for(n_devices: int) -> Tuple[Tuple[int, int, int], Tuple[str, str, str]]:
    """Factor n devices into a (data, model, seq) grid.

    Keeps the model axis ≤ 2 and the seq axis ≤ 2 so small test meshes still
    exercise every parallelism form; data parallelism absorbs the rest (the
    reference's only axis, SURVEY.md §2.5).
    """
    axes = ("data", "model", "seq")
    if n_devices % 8 == 0:
        return (n_devices // 4, 2, 2), axes
    if n_devices % 4 == 0:
        return (n_devices // 4, 2, 2), axes
    if n_devices % 2 == 0:
        return (n_devices // 2, 2, 1), axes
    return (n_devices, 1, 1), axes
