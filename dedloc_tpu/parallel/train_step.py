"""pjit train-step builders: local accumulation vs global apply.

The collaborative loop (SURVEY.md §3.1) splits one "step" into two phases with
different cadences, so we compile them separately:

  accumulate — per micro-batch: forward/backward under jit, grads summed into
               a persistent accumulator (donated). Sharded batch ⇒ the grad
               mean rides an ICI psum inserted by XLA. Runs constantly.
  apply      — once per GLOBAL optimizer step, on (possibly peer-averaged)
               gradients: optimizer update + LR schedule by global step.

``make_local_train_step`` fuses both (scan over micro-batches) for the
single-peer / CI path — capability of the plain HF Trainer loop with
gradient_accumulation_steps (albert/arguments.py:109).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class TrainState(struct.PyTreeNode):
    """Model + optimizer state keyed by the GLOBAL collaboration step.

    ``step`` mirrors ``collaboration_state.optimizer_step`` in the reference
    (consumed by the swav loss at standard_train_step.py:153).
    """

    step: chex.Array
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros([], jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )


LossFn = Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]


def zeros_like_grads(params):
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)


def make_accumulate_step(
    loss_fn: LossFn,
    mesh: Optional[Mesh] = None,
    seq_axis: Optional[str] = None,
    seq_length: Optional[int] = None,
    param_sharding: Optional[Any] = None,
) -> Callable:
    """Build jitted (params, grad_acc, n_acc, batch, rng) -> (grad_acc', n_acc', metrics).

    grad_acc holds the running SUM of per-micro-batch mean gradients; n_acc
    counts micro-batches so the caller can normalize before averaging/apply.
    The accumulator is donated: it lives in device memory across calls, so the
    host<->device traffic per micro-batch is just the batch itself.

    ``seq_axis``/``seq_length``: for sequence-parallel (ring-attention)
    meshes, batch leaves whose second dim is the sequence get constrained to
    P("data", seq_axis) at step entry, so inter-layer activations PROPAGATE
    seq-sharded and ring attention's in_specs match with zero per-layer
    relayout — and non-attention activations are S/n per device, the full
    O(S/n) memory win, not just the score matrix's.
    """

    def step(params, grad_acc, n_acc, batch, rng):
        if mesh is not None and seq_axis is not None:
            def _constrain(x):
                if x.ndim >= 2 and seq_length and x.shape[1] == seq_length:
                    spec = P("data", seq_axis)
                else:
                    spec = P("data")
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, spec)
                )

            batch = jax.tree.map(_constrain, batch)
        (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, rng
        )
        grad_acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32), grad_acc, grads
        )
        return grad_acc, n_acc + 1, metrics

    kwargs = dict(donate_argnums=(1, 2))
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        # tensor parallelism: params (and the param-shaped grad accumulator)
        # carry the Megatron-style layout; XLA inserts the ICI collectives
        p_sh = param_sharding if param_sharding is not None else repl
        # seq-parallel: leave the batch sharding UNSPECIFIED so the per-leaf
        # layout committed by put_batch (seq dims over seq_axis) flows in
        # as-is; the in-step constraint above is then a no-op safety net
        # instead of an every-micro-batch reshard
        data = None if seq_axis is not None else NamedSharding(mesh, P("data"))
        kwargs.update(
            in_shardings=(p_sh, p_sh, repl, data, repl),
            out_shardings=(p_sh, repl, repl),
        )
    return jax.jit(step, **kwargs)


def make_apply_step(
    tx: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    opt_state_sharding: Optional[Any] = None,
    param_sharding: Optional[Any] = None,
) -> Callable:
    """Build jitted (state, mean_grads) -> state'. Runs once per global step.

    ``opt_state_sharding`` (a NamedSharding pytree from
    ``parallel.zero.opt_state_shardings``) keeps optimizer moments sharded
    ZeRO-style across updates; ``param_sharding`` keeps params (and the
    incoming mean grads) in their tensor-parallel layout. GSPMD inserts
    whatever movement the elementwise update needs.
    """

    def apply(state: TrainState, grads) -> TrainState:
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )

    kwargs = dict(donate_argnums=(0,))
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        p_sh = param_sharding if param_sharding is not None else repl
        if opt_state_sharding is not None or param_sharding is not None:
            state_sh = TrainState(
                step=repl, params=p_sh,
                opt_state=opt_state_sharding
                if opt_state_sharding is not None else repl,
            )
            kwargs.update(
                in_shardings=(state_sh, p_sh), out_shardings=state_sh
            )
        else:
            kwargs.update(in_shardings=(repl, repl), out_shardings=repl)
    return jax.jit(apply, **kwargs)


def _all_finite(tree) -> jnp.ndarray:
    """Fused all-finite reduce over a pytree (or a single flat buffer) —
    traced INSIDE a jit, unlike the standalone ``params_are_finite`` whose
    host ``bool()`` readback costs a device sync per call."""
    finite = jnp.array(True)
    for leaf in jax.tree.leaves(tree):
        finite &= jnp.all(jnp.isfinite(leaf))
    return finite


def make_guarded_apply_step(
    tx: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    opt_state_sharding: Optional[Any] = None,
    param_sharding: Optional[Any] = None,
    post_apply: Optional[Callable[["TrainState"], "TrainState"]] = None,
) -> Callable:
    """``make_apply_step`` with the NaN guard FUSED into the jit: returns
    jitted (state, mean_grads) -> (state', ok).

    The collaborative optimizer's rollback used to cost a full
    ``jax.numpy.copy`` of (step, params, opt_state) before every apply
    (donation eats the inputs) plus a host-synced ``params_are_finite``
    readback. Here the all-finite reduce and the ``jnp.where`` rollback run
    inside the same jitted program: non-finite params select the pre-apply
    buffers leaf-wise, no extra HBM snapshot, no host round-trip — ``ok``
    comes back as a device scalar the caller may fetch asynchronously.
    ``post_apply`` (e.g. SwAV prototype re-normalization) is folded in
    BEFORE the finite check, preserving the legacy ordering (a post-apply
    that produces non-finite params also rolls back).
    """

    def apply(state: TrainState, grads):
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        if post_apply is not None:
            new_state = post_apply(new_state)
        ok = _all_finite(new_state.params)
        # roll back exactly what the legacy host-side guard restored —
        # (step, params, opt_state); auxiliary fields (e.g. SwAV batch
        # stats) keep their post-apply values, as before
        guarded = new_state.replace(
            step=jnp.where(ok, new_state.step, state.step),
            params=jax.tree.map(
                lambda n, o: jnp.where(ok, n, o),
                new_state.params, state.params,
            ),
            opt_state=jax.tree.map(
                lambda n, o: jnp.where(ok, n, o),
                new_state.opt_state, state.opt_state,
            ),
        )
        return guarded, ok

    kwargs = dict(donate_argnums=(0,))
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        p_sh = param_sharding if param_sharding is not None else repl
        if opt_state_sharding is not None or param_sharding is not None:
            state_sh = TrainState(
                step=repl, params=p_sh,
                opt_state=opt_state_sharding
                if opt_state_sharding is not None else repl,
            )
            kwargs.update(
                in_shardings=(state_sh, p_sh), out_shardings=(state_sh, repl)
            )
        else:
            kwargs.update(in_shardings=(repl, repl), out_shardings=(repl, repl))
    return jax.jit(apply, **kwargs)


def _replace_opt_states(state, replacements):
    """Rebuild an optax (possibly chained/nested-tuple) opt_state with the
    given per-TYPE replacements applied; unknown member states pass through
    untouched. ``replacements`` maps state type -> replacement callable."""
    for typ, fn in replacements.items():
        if isinstance(state, typ):
            return fn(state)
    if isinstance(state, tuple) and not hasattr(state, "_fields"):
        return tuple(_replace_opt_states(s, replacements) for s in state)
    return state


def _find_opt_state(state, typ):
    if isinstance(state, typ):
        return state
    if isinstance(state, tuple) and not hasattr(state, "_fields"):
        for s in state:
            found = _find_opt_state(s, typ)
            if found is not None:
                return found
    return None


def make_flat_apply_step(
    flat_tx: Any,
    spec,
    post_apply: Optional[Callable[["TrainState"], "TrainState"]] = None,
    from_tree: bool = False,
) -> Callable:
    """Fused FLAT apply: jitted (state, flat_mean_grads) -> (state', ok).

    ``flat_tx`` is an ``optim.flat.FlatLamb`` / ``FlatLars`` adapter and
    ``spec`` the TreeLayout spec (sorted names) the flat gradient buffer
    follows — the SAME spec the averaging wire uses, so the averaged result
    device_puts as ONE buffer and feeds the apply with no per-leaf host
    work. Inside the one jit: params and moments are flattened onto the
    layout (pure relayout, fused by XLA), the whole LAMB/LARS update runs
    as segment reductions over the flat buffer, the all-finite NaN guard
    reduces over the new flat params in one pass, and the ``jnp.where``
    rollback selects pre-apply buffers on failure. The persistent
    ``opt_state`` stays the optax TREE state (checkpoints / peer state
    sync / schema fingerprints unchanged); moments only take their flat
    form transiently inside the jit. Donation end-to-end: the state's
    buffers alias their successors (see the donate note at the bottom).

    ``from_tree=True`` builds the same program taking a params-shaped
    gradient TREE instead of the flat buffer (the solo fast path, where
    gradients never left the device and were never flattened).

    Single-mesh only: sharded layouts keep the per-leaf chain
    (``make_guarded_apply_step``) — GSPMD wants the tree structure.
    """
    from dedloc_tpu.optim.flat import FlatLamb, FlatLars
    from dedloc_tpu.optim.lamb import ScaleByLambState
    from dedloc_tpu.optim.lars import LarsState

    names = [name for name, _shape, _dtype in spec]
    shapes = [shape for _name, shape, _dtype in spec]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]

    def _tree_order(template):
        """Permutation: position in spec (sorted names) per tree leaf."""
        flat = jax.tree_util.tree_flatten_with_path(template)[0]
        leaf_names = [
            jax.tree_util.keystr(path) or f"leaf{i}"
            for i, (path, _leaf) in enumerate(flat)
        ]
        index = {n: i for i, n in enumerate(names)}
        if sorted(leaf_names) != sorted(names):
            raise ValueError(
                "flat apply spec does not match the parameter tree"
            )
        return [index[n] for n in leaf_names], leaf_names

    def _flatten(tree, order):
        leaves = jax.tree.leaves(tree)
        by_spec = [None] * len(leaves)
        for leaf, pos in zip(leaves, order):
            by_spec[pos] = leaf.astype(jnp.float32).reshape(-1)
        return jnp.concatenate(by_spec) if by_spec else jnp.zeros(
            (0,), jnp.float32
        )

    def _unflatten_like(flat, template, order):
        leaves, treedef = jax.tree_util.tree_flatten(template)
        offsets = np.cumsum([0] + sizes)
        out = []
        for leaf, pos in zip(leaves, order):
            chunk = flat[offsets[pos]:offsets[pos] + sizes[pos]]
            out.append(chunk.reshape(leaf.shape).astype(leaf.dtype))
        return jax.tree_util.tree_unflatten(treedef, out)

    def apply(state: TrainState, grads):
        order, _ = _tree_order(state.params)
        flat_grads = _flatten(grads, order) if from_tree else grads
        flat_params = _flatten(state.params, order)
        sched = _find_opt_state(state.opt_state, optax.ScaleByScheduleState)
        sched_count = (
            sched.count if sched is not None else jnp.zeros([], jnp.int32)
        )
        if isinstance(flat_tx, FlatLamb):
            inner = _find_opt_state(state.opt_state, ScaleByLambState)
            assert inner is not None, "flat LAMB needs a lamb() opt_state"
            updates, new_mu, new_nu, new_count = flat_tx.update(
                flat_grads, flat_params,
                _flatten(inner.mu, order), _flatten(inner.nu, order),
                inner.count, sched_count,
            )
            replacements = {
                ScaleByLambState: lambda s: ScaleByLambState(
                    count=new_count,
                    mu=_unflatten_like(new_mu, s.mu, order),
                    nu=_unflatten_like(new_nu, s.nu, order),
                ),
                optax.ScaleByScheduleState: lambda s: (
                    optax.ScaleByScheduleState(count=s.count + 1)
                ),
            }
        elif isinstance(flat_tx, FlatLars):
            inner = _find_opt_state(state.opt_state, LarsState)
            assert inner is not None, "flat LARS needs a lars() opt_state"
            updates, new_mom = flat_tx.update(
                flat_grads, flat_params,
                _flatten(inner.momentum, order), sched_count,
            )
            replacements = {
                LarsState: lambda s: LarsState(
                    momentum=_unflatten_like(new_mom, s.momentum, order)
                ),
                optax.ScaleByScheduleState: lambda s: (
                    optax.ScaleByScheduleState(count=s.count + 1)
                ),
            }
        else:  # pragma: no cover - guarded by the caller
            raise TypeError(f"unsupported flat optimizer {type(flat_tx)!r}")
        new_flat_params = flat_params + updates
        new_state = state.replace(
            step=state.step + 1,
            params=_unflatten_like(new_flat_params, state.params, order),
            opt_state=_replace_opt_states(state.opt_state, replacements),
        )
        if post_apply is not None:
            new_state = post_apply(new_state)
            ok = _all_finite(new_state.params)
        else:
            # one fused reduce over the flat buffer
            ok = jnp.all(jnp.isfinite(new_flat_params))
        guarded = new_state.replace(
            step=jnp.where(ok, new_state.step, state.step),
            params=jax.tree.map(
                lambda n, o: jnp.where(ok, n, o),
                new_state.params, state.params,
            ),
            opt_state=jax.tree.map(
                lambda n, o: jnp.where(ok, n, o),
                new_state.opt_state, state.opt_state,
            ),
        )
        return guarded, ok

    # donation end-to-end applies to the STATE (params/moments alias their
    # successors in-place). The incoming gradient buffer/tree is consumed
    # by the relayout but has no same-shaped output to alias — declaring
    # it donated would only emit the unusable-donation warning.
    return jax.jit(apply, donate_argnums=(0,))


def make_local_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    grad_accum_steps: int = 1,
    mesh: Optional[Mesh] = None,
) -> Callable:
    """Single-peer fused step: scan over micro-batches, then optimizer apply.

    batch leaves must have shape [grad_accum_steps, per_step_batch, ...].
    """

    def train_step(state: TrainState, batch, rng):
        def micro(carry, mb):
            grad_acc, r = carry
            r, sub = jax.random.split(r)
            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, mb, sub
            )
            grad_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32) / grad_accum_steps,
                grad_acc,
                grads,
            )
            return (grad_acc, r), metrics

        (grads, _), metrics = jax.lax.scan(
            micro, (zeros_like_grads(state.params), rng), batch
        )
        metrics = jax.tree.map(lambda m: m.mean(), metrics)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        return new_state, metrics

    kwargs = dict(donate_argnums=(0,))
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P(None, "data"))
        kwargs.update(
            in_shardings=(repl, data, repl), out_shardings=(repl, repl)
        )
    return jax.jit(train_step, **kwargs)


@jax.jit
def params_are_finite(params) -> jnp.ndarray:
    """All-finite check over a pytree (reference: CollaborativeCallback.
    params_are_finite, albert/run_trainer.py:181-186). Used by the NaN-guard
    rollback in the collaborative wrapper."""
    leaves = jax.tree.leaves(params)
    finite = jnp.array(True)
    for leaf in leaves:
        finite &= jnp.all(jnp.isfinite(leaf))
    return finite
