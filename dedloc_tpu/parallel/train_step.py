"""pjit train-step builders: local accumulation vs global apply.

The collaborative loop (SURVEY.md §3.1) splits one "step" into two phases with
different cadences, so we compile them separately:

  accumulate — per micro-batch: forward/backward under jit, grads summed into
               a persistent accumulator (donated). Sharded batch ⇒ the grad
               mean rides an ICI psum inserted by XLA. Runs constantly.
  apply      — once per GLOBAL optimizer step, on (possibly peer-averaged)
               gradients: optimizer update + LR schedule by global step.

``make_local_train_step`` fuses both (scan over micro-batches) for the
single-peer / CI path — capability of the plain HF Trainer loop with
gradient_accumulation_steps (albert/arguments.py:109).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class TrainState(struct.PyTreeNode):
    """Model + optimizer state keyed by the GLOBAL collaboration step.

    ``step`` mirrors ``collaboration_state.optimizer_step`` in the reference
    (consumed by the swav loss at standard_train_step.py:153).
    """

    step: chex.Array
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros([], jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )


LossFn = Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]


def zeros_like_grads(params):
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)


def make_accumulate_step(
    loss_fn: LossFn,
    mesh: Optional[Mesh] = None,
    seq_axis: Optional[str] = None,
    seq_length: Optional[int] = None,
    param_sharding: Optional[Any] = None,
) -> Callable:
    """Build jitted (params, grad_acc, n_acc, batch, rng) -> (grad_acc', n_acc', metrics).

    grad_acc holds the running SUM of per-micro-batch mean gradients; n_acc
    counts micro-batches so the caller can normalize before averaging/apply.
    The accumulator is donated: it lives in device memory across calls, so the
    host<->device traffic per micro-batch is just the batch itself.

    ``seq_axis``/``seq_length``: for sequence-parallel (ring-attention)
    meshes, batch leaves whose second dim is the sequence get constrained to
    P("data", seq_axis) at step entry, so inter-layer activations PROPAGATE
    seq-sharded and ring attention's in_specs match with zero per-layer
    relayout — and non-attention activations are S/n per device, the full
    O(S/n) memory win, not just the score matrix's.
    """

    def step(params, grad_acc, n_acc, batch, rng):
        if mesh is not None and seq_axis is not None:
            def _constrain(x):
                if x.ndim >= 2 and seq_length and x.shape[1] == seq_length:
                    spec = P("data", seq_axis)
                else:
                    spec = P("data")
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, spec)
                )

            batch = jax.tree.map(_constrain, batch)
        (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, rng
        )
        grad_acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32), grad_acc, grads
        )
        return grad_acc, n_acc + 1, metrics

    kwargs = dict(donate_argnums=(1, 2))
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        # tensor parallelism: params (and the param-shaped grad accumulator)
        # carry the Megatron-style layout; XLA inserts the ICI collectives
        p_sh = param_sharding if param_sharding is not None else repl
        # seq-parallel: leave the batch sharding UNSPECIFIED so the per-leaf
        # layout committed by put_batch (seq dims over seq_axis) flows in
        # as-is; the in-step constraint above is then a no-op safety net
        # instead of an every-micro-batch reshard
        data = None if seq_axis is not None else NamedSharding(mesh, P("data"))
        kwargs.update(
            in_shardings=(p_sh, p_sh, repl, data, repl),
            out_shardings=(p_sh, repl, repl),
        )
    return jax.jit(step, **kwargs)


def make_apply_step(
    tx: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    opt_state_sharding: Optional[Any] = None,
    param_sharding: Optional[Any] = None,
) -> Callable:
    """Build jitted (state, mean_grads) -> state'. Runs once per global step.

    ``opt_state_sharding`` (a NamedSharding pytree from
    ``parallel.zero.opt_state_shardings``) keeps optimizer moments sharded
    ZeRO-style across updates; ``param_sharding`` keeps params (and the
    incoming mean grads) in their tensor-parallel layout. GSPMD inserts
    whatever movement the elementwise update needs.
    """

    def apply(state: TrainState, grads) -> TrainState:
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )

    kwargs = dict(donate_argnums=(0,))
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        p_sh = param_sharding if param_sharding is not None else repl
        if opt_state_sharding is not None or param_sharding is not None:
            state_sh = TrainState(
                step=repl, params=p_sh,
                opt_state=opt_state_sharding
                if opt_state_sharding is not None else repl,
            )
            kwargs.update(
                in_shardings=(state_sh, p_sh), out_shardings=state_sh
            )
        else:
            kwargs.update(in_shardings=(repl, repl), out_shardings=repl)
    return jax.jit(apply, **kwargs)


def make_local_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    grad_accum_steps: int = 1,
    mesh: Optional[Mesh] = None,
) -> Callable:
    """Single-peer fused step: scan over micro-batches, then optimizer apply.

    batch leaves must have shape [grad_accum_steps, per_step_batch, ...].
    """

    def train_step(state: TrainState, batch, rng):
        def micro(carry, mb):
            grad_acc, r = carry
            r, sub = jax.random.split(r)
            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, mb, sub
            )
            grad_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32) / grad_accum_steps,
                grad_acc,
                grads,
            )
            return (grad_acc, r), metrics

        (grads, _), metrics = jax.lax.scan(
            micro, (zeros_like_grads(state.params), rng), batch
        )
        metrics = jax.tree.map(lambda m: m.mean(), metrics)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        return new_state, metrics

    kwargs = dict(donate_argnums=(0,))
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P(None, "data"))
        kwargs.update(
            in_shardings=(repl, data, repl), out_shardings=(repl, repl)
        )
    return jax.jit(train_step, **kwargs)


@jax.jit
def params_are_finite(params) -> jnp.ndarray:
    """All-finite check over a pytree (reference: CollaborativeCallback.
    params_are_finite, albert/run_trainer.py:181-186). Used by the NaN-guard
    rollback in the collaborative wrapper."""
    leaves = jax.tree.leaves(params)
    finite = jnp.array(True)
    for leaf in leaves:
        finite &= jnp.all(jnp.isfinite(leaf))
    return finite
