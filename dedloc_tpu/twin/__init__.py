"""Telemetry-replay digital twin (ROADMAP item 4).

``fit.py`` fits the simulator's network/compute model from a real run's
telemetry; ``replay.py`` replays the recorded workload over the fitted
model in virtual time and scores twin fidelity. ``tools/twin_sweep.py``
sweeps configurations over a fitted model; ``tools/runlog_summary.py
--twin`` renders the fidelity report.
"""
from dedloc_tpu.twin.fit import TwinModel, fit_twin
from dedloc_tpu.twin.replay import fidelity_report, replay_twin

__all__ = ["TwinModel", "fit_twin", "fidelity_report", "replay_twin"]
