"""Fit the simulator's network/compute model from a real run's telemetry.

The repo has two halves that never talked (ROADMAP item 4): production
telemetry — per-link RTT/goodput EWMAs (PR 6), step phases and the overlap
ledger (PR 8), matchmaking/round spans (PR 2) — and a deterministic
discrete-event simulator with latency/bandwidth/loss models (PR 7). This
module is the bridge: it reads a run's per-peer event logs (the
``--telemetry.event_log_path`` JSONL) or a coordinator metrics JSONL whose
``swarm_health`` records already folded the per-peer snapshots, and fits a
serializable **TwinModel**: per-directed-link latency/jitter/bandwidth/loss,
per-peer compute, and the recorded workload shape (round cadence, group
size, span/chunk bytes, boundaries, restores).

Fitting rules — each one exists to keep the twin honest:

- **Latency/jitter** come from ``link.*`` RTT stats (the free SYN/SYN-ACK
  probe): one-way latency is half the base RTT — the MINIMUM sample when
  recorded, since every connect timing carries event-loop scheduling noise
  a replay would otherwise pay twice — and jitter is half the RTT
  deviation EWMA. Links that carried traffic but never got an RTT sample
  (the per-peer ``link.stats`` emission is top-K bounded) inherit the
  measured median, never the global constant.
- **Bandwidth is fitted as the sender's serialized UPLINK rate**, which is
  what the simulator's ``LinkSpec.bandwidth_bps`` actually models. Per-flow
  telemetry (goodput EWMAs, per-chunk rates) is measured while the sender's
  uplink is shared across all of a round's partners — installing it
  verbatim would make the replay charge the contention twice. The primary
  estimator is per-round wire volume over the latency-corrected round wall
  (``allreduce.link`` bytes × 2 for the gather replies ÷ the ``avg.round``
  span minus the request/ack chain), taken at each sender's LEAST-blocked
  round, and lower-bounded by the best latency-corrected per-flow rate;
  fallbacks (goodput/peak/wire aggregates, scaled by the recorded
  concurrency) are noted in the coverage summary.
- **Loss** is connection deaths over transfers (``rpc.conn_lost`` events
  per endpoint; per-peer ``conns_lost``/``rpc_calls`` from a swarm-health
  fold), clamped to the simulator's meaningful range.
- **Compute** is the ``step.phase.fwd_bwd`` mean per peer (event logs:
  ``step.record`` phases; coordinator JSONL: the folded ``phases`` map).
- **Nothing is fitted silently.** Every dimension that degrades to a
  default lands in ``coverage`` — the fit of a jammed, truncated or
  pre-link-schema log *reports* its blind spots instead of hiding them.

The model is deliberately JSON-flat (``TwinModel.to_dict``): it is an
artifact operators diff, archive next to checkpoints, and feed to
``tools/twin_sweep.py`` or the ``twin_replay`` scenario.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from dedloc_tpu.simulator.network import LinkSpec
# the catalog module is aliased: this file's row lists are named ``events``
from dedloc_tpu.telemetry import events as ev
from dedloc_tpu.utils.logging import get_logger

# the SAME nearest-rank percentile the simulator's reports use
# (utils/stats.py): observed and predicted statistics are like-for-like
# by construction, not because two copies stayed in sync
from dedloc_tpu.utils.stats import median as _median
from dedloc_tpu.utils.stats import percentile

logger = get_logger(__name__)

SCHEMA_VERSION = 1

# fleet-shaped fallbacks for unmeasured dimensions (the docs/simulator.md
# volunteer-link default): 20 ms one-way, ~100 Mbit/s uplink, no loss.
DEFAULT_LINK = {
    "latency_s": 0.02,
    "bandwidth_bps": 12_500_000.0,
    "loss": 0.0,
    "jitter_s": 0.0,
}
DEFAULT_COMPUTE_S = 0.1
DEFAULT_SAMPLES_PER_BOUNDARY = 16

LINK_KEY_SEP = "|"  # "src|dst" in the serialized link table


def safe_label(raw) -> str:
    """Peer labels are fleet-controlled input, and the serialized link
    table keys on ``src|dst`` — a label carrying the separator would make
    those keys ambiguous (and crash the key round trip). Sanitized once at
    ingestion; the fit must degrade, never crash, on hostile input."""
    return str(raw).replace(LINK_KEY_SEP, "_")




@dataclass
class TwinModel:
    """A fitted digital twin: everything ``twin/replay.py`` needs to
    re-instantiate the swarm in the simulator, plus the OBSERVED metrics
    the replay's predictions are judged against (the fidelity report) and
    the fit-coverage summary that says which numbers are measurements and
    which are defaults."""

    peers: Dict[str, Dict[str, float]] = field(default_factory=dict)
    links: Dict[str, Dict[str, float]] = field(default_factory=dict)
    default_link: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_LINK)
    )
    workload: Dict[str, Any] = field(default_factory=dict)
    observed: Dict[str, Any] = field(default_factory=dict)
    coverage: Dict[str, Any] = field(default_factory=dict)
    schema: int = SCHEMA_VERSION

    # ------------------------------------------------------- serialization

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": self.schema,
            "peers": self.peers,
            "links": self.links,
            "default_link": self.default_link,
            "workload": self.workload,
            "observed": self.observed,
            "coverage": self.coverage,
        }

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "TwinModel":
        if not isinstance(raw, dict) or "peers" not in raw:
            raise ValueError("not a TwinModel dict (no 'peers')")
        schema = int(raw.get("schema", SCHEMA_VERSION))
        if schema > SCHEMA_VERSION:
            logger.warning(
                f"TwinModel schema {schema} is newer than this build "
                f"({SCHEMA_VERSION}); reading what is understood"
            )
        return cls(
            peers=dict(raw.get("peers", {})),
            links=dict(raw.get("links", {})),
            default_link={**DEFAULT_LINK, **(raw.get("default_link") or {})},
            workload=dict(raw.get("workload", {})),
            observed=dict(raw.get("observed", {})),
            coverage=dict(raw.get("coverage", {})),
            schema=schema,
        )

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "TwinModel":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    # ------------------------------------------------------------- helpers

    def link_spec(self, src: str, dst: str) -> LinkSpec:
        raw = self.links.get(f"{src}{LINK_KEY_SEP}{dst}")
        if raw is None:
            return LinkSpec.from_dict(self.default_link)
        return LinkSpec.from_dict({**self.default_link, **raw})

    def describe(self) -> List[str]:
        """Human summary lines (the --twin header)."""
        cov = self.coverage
        out = [
            f"twin: {len(self.peers)} peer(s), "
            f"{len(self.links)} fitted directed link(s)",
            f"fit coverage: {cov.get('links_with_rtt', 0)} link(s) with "
            f"RTT, {cov.get('links_with_bandwidth', 0)} with bandwidth "
            f"({cov.get('links_with_uplink_estimate', 0)} from per-round "
            "uplink volume), "
            f"{cov.get('peers_with_compute', 0)}/"
            f"{len(self.peers)} peer(s) with measured compute",
        ]
        for warning in cov.get("warnings", []):
            out.append(f"coverage warning: {warning}")
        return out


class _LinkFit:
    """Accumulates every signal observed for one directed link."""

    __slots__ = (
        "rtt_s", "rtt_min_s", "rtt_jitter_s", "goodput_bps", "peak_bps",
        "transfers", "wire_bytes", "wire_send_s", "wire_chunks",
        "round_bytes", "conn_lost",
    )

    def __init__(self) -> None:
        self.rtt_s: Optional[float] = None
        self.rtt_min_s: Optional[float] = None
        self.rtt_jitter_s: Optional[float] = None
        self.goodput_bps: Optional[float] = None
        self.peak_bps: Optional[float] = None
        self.transfers = 0.0
        self.wire_bytes = 0.0
        self.wire_send_s = 0.0
        self.wire_chunks = 0.0
        self.round_bytes: List[float] = []
        self.conn_lost = 0.0


def _resolve_label(dst: str, labels: set, endpoint_map: Dict[str, str]):
    """Resolve a link destination ("host:port") to a peer label via
    endpoint self-identification events / the folded topology map, falling
    back to the host part when it IS a known label (simulator logs name
    hosts after peers)."""
    if dst in endpoint_map:
        return endpoint_map[dst]
    host = safe_label(dst.rsplit(":", 1)[0])
    if host in labels:
        return host
    return None


def fit_twin(rows: List[Dict[str, Any]],
             defaults: Optional[Dict[str, float]] = None) -> TwinModel:
    """Fit a TwinModel from loaded JSONL rows (event logs and/or a
    coordinator metrics JSONL — pass everything through the shared
    ``load_jsonl_rows`` loader first; it already survives jammed and
    truncated files).

    Raises ``ValueError`` only when NO peer is identifiable at all;
    anything less degrades to defaults with the gap named in
    ``coverage``."""
    defaults = {**DEFAULT_LINK, **(defaults or {})}
    events = [
        r for r in rows
        if isinstance(r, dict) and isinstance(r.get("event"), str)
    ]
    # sanitize peer labels at the door (see safe_label); shallow-copy only
    # the rare offending rows so callers' lists stay untouched
    events = [
        {**r, "peer": safe_label(r["peer"])}
        if LINK_KEY_SEP in str(r.get("peer", "")) else r
        for r in events
    ]
    health_rows = [
        r for r in rows
        if isinstance(r, dict) and isinstance(r.get("swarm_health"), dict)
    ]
    healths = [r["swarm_health"] for r in health_rows]
    warnings: List[str] = []

    # a coordinator JSONL whose folds carry recent-round summaries (the
    # in-process/simulator fold does; the flat production metrics bus
    # cannot) fits round walls and workload shape from the coordinator's
    # own log — the watchdog's self-retune path. Adopted as avg.round rows
    # ONLY when no per-peer event log contributed real ones, so feeding
    # both never double-counts a round.
    rounds_from_folds = 0
    if not any(r.get("event") == ev.AVG_ROUND for r in events):
        for row in health_rows:
            fold_t = row.get("time")
            for rd in row["swarm_health"].get("rounds") or []:
                if not isinstance(rd, dict) or rd.get("dur_s") is None:
                    continue
                synthetic = {
                    "event": ev.AVG_ROUND,
                    "peer": safe_label(rd.get("peer", "?")),
                    "round_id": rd.get("round_id"),
                    "dur_s": float(rd["dur_s"]),
                    "ok": rd.get("ok", True),
                    # the fold stamps its time at the round's tail — the
                    # same span-exit convention real avg.round events use
                    "t": float(fold_t) if fold_t is not None else 0.0,
                }
                if rd.get("group_size") is not None:
                    synthetic["group_size"] = rd["group_size"]
                events.append(synthetic)
                rounds_from_folds += 1

    # ---------------------------------------------------------- peer roster
    labels = {
        str(r["peer"]) for r in events if r.get("peer")
    }
    for health in healths:
        for p in health.get("peers", []):
            if isinstance(p, dict) and p.get("peer"):
                labels.add(safe_label(p["peer"]))
    if not labels:
        raise ValueError(
            "no peers identifiable in the given rows — need per-peer event "
            "logs (with 'peer' fields) or a coordinator JSONL with "
            "swarm_health records"
        )

    endpoint_map: Dict[str, str] = {}
    for r in events:
        if r.get("event") == ev.PEER_ENDPOINT and r.get("endpoint"):
            endpoint_map[str(r["endpoint"])] = safe_label(r.get("peer", "?"))
    for health in healths:
        topo = health.get("topology") or {}
        for label, endpoint in (topo.get("peers") or {}).items():
            if endpoint:
                endpoint_map.setdefault(str(endpoint), safe_label(label))

    # ------------------------------------------------------------ link fits
    fits: Dict[Tuple[str, str], _LinkFit] = {}

    def fit_for(src: str, dst_label: str) -> _LinkFit:
        return fits.setdefault((src, dst_label), _LinkFit())

    unresolved_dsts = 0
    # newest link.stats per (peer, dst) wins: they are cumulative estimates
    latest_stats: Dict[Tuple[str, str], Dict[str, Any]] = {}
    # per (src, round_id): scatter bytes/chunks/fan-out this member pushed
    # — the uplink estimator's inputs
    sent_by_src_round: Dict[Tuple[str, str], Dict[str, float]] = {}
    for r in events:
        name = r.get("event")
        src = str(r.get("peer", "?"))
        if name == ev.LINK_STATS and r.get("dst"):
            latest_stats[(src, str(r["dst"]))] = r
        elif name == ev.ALLREDUCE_LINK and r.get("dst"):
            dst_label = _resolve_label(str(r["dst"]), labels, endpoint_map)
            if dst_label is None:
                unresolved_dsts += 1
                continue
            f = fit_for(src, dst_label)
            sent = float(r.get("sent_bytes", 0.0))
            f.wire_bytes += sent
            f.wire_send_s += float(r.get("send_s", 0.0))
            f.wire_chunks += float(r.get("chunks_sent", 0.0))
            if sent > 0:
                f.round_bytes.append(sent)
                if r.get("round_id"):
                    key = (src, str(r["round_id"]))
                    acc = sent_by_src_round.setdefault(
                        key, {"sent": 0.0, "chunks": 0.0, "dsts": 0.0}
                    )
                    acc["sent"] += sent
                    acc["chunks"] += float(r.get("chunks_sent", 0.0))
                    acc["dsts"] += 1.0
        elif name == ev.RPC_CONN_LOST and r.get("endpoint"):
            dst_label = _resolve_label(
                str(r["endpoint"]), labels, endpoint_map
            )
            if dst_label is not None:
                fit_for(src, dst_label).conn_lost += 1.0
    for (src, dst), r in latest_stats.items():
        dst_label = _resolve_label(dst, labels, endpoint_map)
        if dst_label is None:
            unresolved_dsts += 1
            continue
        f = fit_for(src, dst_label)
        if r.get("rtt_s") is not None:
            f.rtt_s = float(r["rtt_s"])
        if r.get("rtt_min_s") is not None:
            f.rtt_min_s = float(r["rtt_min_s"])
        if r.get("rtt_jitter_s") is not None:
            f.rtt_jitter_s = float(r["rtt_jitter_s"])
        if r.get("goodput_bps") is not None:
            f.goodput_bps = float(r["goodput_bps"])
        if r.get("peak_bps") is not None:
            f.peak_bps = float(r["peak_bps"])
        f.transfers = max(f.transfers, float(r.get("transfers", 0.0)))
    # coordinator fold: the newest topology record's links
    for health in healths:
        topo = health.get("topology") or {}
        for link in topo.get("links", []):
            if not isinstance(link, dict):
                continue
            src = safe_label(link.get("src", "?"))
            dst_label = _resolve_label(
                str(link.get("dst_endpoint", link.get("dst", ""))),
                labels, endpoint_map,
            ) or (
                safe_label(link["dst"])
                if safe_label(link.get("dst")) in labels else None
            )
            if dst_label is None:
                unresolved_dsts += 1
                continue
            f = fit_for(src, dst_label)
            if link.get("rtt_s") is not None:
                f.rtt_s = float(link["rtt_s"])
            if link.get("rtt_min_s") is not None:
                f.rtt_min_s = float(link["rtt_min_s"])
            if link.get("rtt_jitter_s") is not None:
                f.rtt_jitter_s = float(link["rtt_jitter_s"])
            if link.get("goodput_bps") is not None:
                f.goodput_bps = float(link["goodput_bps"])
            if link.get("peak_bps") is not None:
                f.peak_bps = float(link["peak_bps"])
            f.transfers = max(f.transfers, float(link.get("transfers", 0.0)))
    if unresolved_dsts:
        warnings.append(
            f"{unresolved_dsts} link record(s) pointed at endpoints no "
            "peer label resolves — those links were skipped"
        )

    # per-peer loss fallback (coordinator fold: conns_lost / rpc_calls)
    peer_loss: Dict[str, float] = {}
    for health in healths:
        for p in health.get("peers", []):
            if not isinstance(p, dict):
                continue
            calls = float(p.get("rpc_calls", 0.0))
            lost = float(p.get("conns_lost", 0.0))
            if calls > 0 and lost > 0:
                peer_loss[safe_label(p.get("peer", "?"))] = min(
                    0.5, lost / calls
                )

    # ---------------------------------------------------- rounds (early:
    # the uplink estimator needs per-member round walls)
    rounds_by_id: Dict[str, List[Dict[str, Any]]] = {}
    round_dur: Dict[Tuple[str, str], float] = {}
    for r in events:
        if r.get("event") == ev.AVG_ROUND and r.get("round_id"):
            rid = str(r["round_id"])
            rounds_by_id.setdefault(rid, []).append(r)
            if r.get("dur_s") is not None and r.get("ok") is not False:
                round_dur[(str(r.get("peer", "?")), rid)] = float(
                    r["dur_s"]
                )
    group_sizes = [
        float(r["group_size"])
        for rs in rounds_by_id.values() for r in rs
        if r.get("group_size") is not None
    ]
    # a member's round pushes its scatter bytes plus (serving the gather's
    # reduced chunks to its partners) roughly the same volume again through
    # its uplink, all inside its round wall — per-round volume over wall is
    # the serialized-uplink rate the simulator's bandwidth model wants,
    # already free of the per-flow contention that biases goodput EWMAs.
    # The BEST round per source wins: a fast peer grouped with a straggler
    # spends its wall WAITING, not transmitting, so its blocked rounds
    # read far below its real uplink — the least-blocked round is the
    # honest sample (and for a peer that is itself the bottleneck, every
    # round reads the same, so the max changes nothing).
    # base RTT per link/source: prefer the MINIMUM connect sample — the
    # EWMA carries the caller's event-loop scheduling noise, which a
    # replay would then pay a second time on top of its own
    def base_rtt(f: _LinkFit) -> Optional[float]:
        return f.rtt_min_s if f.rtt_min_s is not None else f.rtt_s

    rtt_by_src: Dict[str, float] = {}
    for src in {s for (s, _d) in fits}:
        rtts = [
            base_rtt(f) for (s, _d), f in fits.items()
            if s == src and base_rtt(f) is not None
        ]
        if rtts:
            rtt_by_src[src] = _median(rtts)
    uplink_samples: Dict[str, List[float]] = {}
    for (src, rid), acc in sent_by_src_round.items():
        dur = round_dur.get((src, rid))
        if not dur or dur <= 0 or acc["dsts"] <= 0:
            continue
        # the request/ack round trips EXPOSED in the member's wall: each
        # destination's chunk chain is sequential, but with D destinations
        # in flight the uplink keeps transmitting other chunks while one
        # chain waits on its ack — so of the chunks/D per-destination
        # round trips, only ~1/D of each is actually exposed wall
        # (chunks/D² total), plus one tail round trip. That is LATENCY,
        # already fitted separately; leaving it in the denominator would
        # bill it a second time as low bandwidth on replay.
        rtt_chain = (
            (acc["chunks"] / (acc["dsts"] ** 2) + 1.0)
            * rtt_by_src.get(src, 0.0)
        )
        transmit = max(dur - rtt_chain, dur * 0.2)
        uplink_samples.setdefault(src, []).append(
            2.0 * acc["sent"] / transmit
        )
    uplink_bps: Dict[str, float] = {
        src: max(samples) for src, samples in uplink_samples.items()
    }
    # per-flow lower bounds: a fast peer that spent every recorded round
    # grouped with a straggler never shows its uplink in round volume
    # (its wall is wait, not transmission). Two rescues, both taken as a
    # MAX (an uplink is at least as fast as any flow it carried):
    # - the best single-transfer peak;
    # - the latency-CORRECTED wire rate: per-chunk timings include a full
    #   request/ack round trip, so on a fast link the RTT — not the
    #   bandwidth — dominates every sample and raw rates saturate around
    #   chunk_bytes/rtt. Subtracting the known per-chunk RTT cost recovers
    #   the transmit time. The subtraction is ill-conditioned exactly when
    #   latency dominates — which is when the resulting (possibly huge)
    #   estimate is also harmless, because the replayed wall is set by the
    #   latency either way; when queueing dominates (a genuinely thin
    #   uplink) the correction is negligible and the volume estimate wins.
    for (src, dst_label), f in fits.items():
        if src not in uplink_bps:
            continue
        floor = f.peak_bps or 0.0
        rtt0 = base_rtt(f)
        if f.wire_bytes > 0 and f.wire_send_s > 0 and rtt0 is not None:
            # floor at 20% of the raw wall: the correction is only exact
            # for uncontended flows (where it can win the max); on
            # contended flows the round trips overlapped the queueing and
            # a full subtraction would manufacture bandwidth
            adjusted = max(
                f.wire_send_s - f.wire_chunks * rtt0,
                f.wire_send_s * 0.2,
            )
            floor = max(floor, f.wire_bytes / adjusted)
        # the floor is a RESCUE, not a refinement: the structural volume
        # estimate wins unless per-flow evidence contradicts it decisively
        # (a peer whose every recorded round was blocked behind a
        # straggler reads catastrophically low on volume — 2x is well
        # past any per-flow estimator's own bias)
        if floor > 2.0 * uplink_bps[src]:
            uplink_bps[src] = floor
    # per-flow fallbacks are contended across the round's partners: scale
    # them back up by the recorded concurrency
    concurrency = max(1.0, _median(group_sizes, 2.0) - 1.0)

    links: Dict[str, Dict[str, float]] = {}
    links_with_rtt = links_with_bw = links_with_uplink = 0
    links_with_loss = links_from_wire = 0
    for (src, dst_label), f in sorted(fits.items()):
        bandwidth: Optional[float] = None
        if src in uplink_bps:
            bandwidth = uplink_bps[src]
            links_with_uplink += 1
        elif f.peak_bps is not None:
            bandwidth = f.peak_bps * concurrency
        elif f.goodput_bps is not None:
            bandwidth = f.goodput_bps * concurrency
        elif f.wire_bytes > 0 and f.wire_send_s > 0:
            bandwidth = (f.wire_bytes / f.wire_send_s) * concurrency
            links_from_wire += 1
        loss: Optional[float] = None
        transfers = max(f.transfers, f.wire_chunks)
        if f.conn_lost > 0 and transfers > 0:
            loss = min(0.5, f.conn_lost / transfers)
            links_with_loss += 1
        elif src in peer_loss:
            loss = peer_loss[src]
        spec = LinkSpec.from_estimate(
            rtt_s=base_rtt(f),
            rtt_jitter_s=f.rtt_jitter_s,  # from_estimate halves RTT terms
            goodput_bps=bandwidth,
            loss=loss,
            default=LinkSpec.from_dict(defaults),
        )
        if f.rtt_s is not None:
            links_with_rtt += 1
        if bandwidth is not None:
            links_with_bw += 1
        entry: Dict[str, float] = {
            "latency_s": round(spec.latency_s, 6),
            "jitter_s": round(spec.jitter_s, 6),
            "bandwidth_bps": round(spec.bandwidth_bps, 1),
            "loss": round(spec.loss, 5),
        }
        links[f"{src}{LINK_KEY_SEP}{dst_label}"] = entry
    # links that carried wire traffic but never got an RTT sample (the
    # per-peer link.stats emission is top-K bounded on real fleets)
    # inherit the MEASURED median latency/jitter, not the global constant
    # — same swarm-is-its-own-prior rule as the default link below
    measured_lat = [
        links[key]["latency_s"] for key in links
        if base_rtt(fits[tuple(key.split(LINK_KEY_SEP, 1))]) is not None
    ]
    links_rtt_backfilled = 0
    if measured_lat:
        med_lat = _median(measured_lat)
        med_jit = _median([
            links[key]["jitter_s"] for key in links
            if base_rtt(fits[tuple(key.split(LINK_KEY_SEP, 1))]) is not None
        ])
        for key, entry in links.items():
            if base_rtt(fits[tuple(key.split(LINK_KEY_SEP, 1))]) is None:
                entry["latency_s"] = med_lat
                entry["jitter_s"] = med_jit
                links_rtt_backfilled += 1
    if links:
        # pairs never observed together (they simply never shared a round)
        # replay as the TYPICAL fitted link, not as the global constant —
        # the swarm's own distribution is the best prior for its own
        # unobserved pairs
        defaults = {
            "latency_s": _median(
                [spec["latency_s"] for spec in links.values()]
            ),
            "jitter_s": _median(
                [spec["jitter_s"] for spec in links.values()]
            ),
            "bandwidth_bps": _median(
                [spec["bandwidth_bps"] for spec in links.values()]
            ),
            "loss": _median([spec["loss"] for spec in links.values()]),
        }
    else:
        warnings.append(
            "no link telemetry at all (pre-link-schema peers, or telemetry "
            "was off): every link replays with the default spec"
        )

    # ------------------------------------------------------- per-peer fits
    step_records: Dict[str, List[Dict[str, Any]]] = {}
    for r in events:
        if r.get("event") == ev.STEP_RECORD:
            step_records.setdefault(str(r.get("peer", "?")), []).append(r)
    health_phases: Dict[str, Dict[str, float]] = {}
    for health in healths:  # newest record wins per peer
        for p in health.get("peers", []):
            if isinstance(p, dict) and isinstance(p.get("phases"), dict):
                health_phases[safe_label(p.get("peer", "?"))] = p["phases"]

    peers: Dict[str, Dict[str, float]] = {}
    peers_with_compute = 0
    for label in sorted(labels):
        compute: Optional[float] = None
        samples = DEFAULT_SAMPLES_PER_BOUNDARY
        records = step_records.get(label, [])
        fwd = [
            float(r["phases"]["fwd_bwd"]) for r in records
            if isinstance(r.get("phases"), dict)
            and r["phases"].get("fwd_bwd") is not None
        ]
        if fwd:
            compute = sum(fwd) / len(fwd)
        elif label in health_phases and (
            health_phases[label].get("fwd_bwd") is not None
        ):
            compute = float(health_phases[label]["fwd_bwd"])
        sample_values = [
            float(r["samples"]) for r in records
            if r.get("samples") is not None
        ]
        if sample_values:
            samples = int(_median(sample_values))
        if compute is not None:
            peers_with_compute += 1
        outgoing = [
            spec["bandwidth_bps"]
            for key, spec in links.items()
            if key.split(LINK_KEY_SEP, 1)[0] == label
        ]
        entry: Dict[str, float] = {
            "compute_s": round(
                compute if compute is not None else DEFAULT_COMPUTE_S, 6
            ),
            "samples_per_boundary": samples,
        }
        if outgoing:
            entry["uplink_bps"] = round(max(outgoing), 1)
        peers[label] = entry
    if peers_with_compute == 0:
        warnings.append(
            "no step-phase telemetry (pre-recorder peers?): per-peer "
            f"compute defaults to {DEFAULT_COMPUTE_S}s per boundary"
        )

    # --------------------------------------------------- workload + observed
    # round-wall percentiles over every MEMBER's span of every round —
    # the same statistic the replay report computes, and far more stable
    # than per-round maxima on short recordings
    round_walls = [
        float(r["dur_s"])
        for rs in rounds_by_id.values() for r in rs
        if r.get("dur_s") is not None and r.get("ok") is not False
    ]
    formation = [
        float(r["dur_s"]) for r in events
        if r.get("event") == ev.MM_FORM_GROUP
        and r.get("dur_s") is not None and r.get("ok") is not False
    ]
    span_bytes = _median(
        [b for f in fits.values() for b in f.round_bytes], 0.0
    )
    chunk_candidates = [
        f.wire_bytes / f.wire_chunks
        for f in fits.values() if f.wire_chunks > 0
    ]
    boundaries = 0.0
    if rounds_by_id and step_records:
        n_rounds = len(rounds_by_id)
        boundaries = _median(
            [len(records) / n_rounds for records in step_records.values()],
            0.0,
        )
    ledgers = [
        r for r in events if r.get("event") == ev.OPT_OVERLAP_LEDGER
    ]
    hidden = sum(float(r.get("hidden_s", 0.0)) for r in ledgers)
    exposed = sum(float(r.get("exposed_s", 0.0)) for r in ledgers)
    restores = [
        r for r in events
        if r.get("event") == ev.CKPT_RESTORE and r.get("ok")
    ]
    # round cadence: gaps between successive round STARTS (event t stamps
    # are span exits; subtract the duration)
    starts = sorted(
        min(
            float(r.get("t", 0.0)) - float(r.get("dur_s", 0.0))
            for r in rs
        )
        for rs in rounds_by_id.values()
    )
    gaps = [b - a for a, b in zip(starts, starts[1:]) if b > a]

    workload: Dict[str, Any] = {
        "rounds": len(rounds_by_id),
        "group_size": int(_median(group_sizes, 0.0)) or None,
        "span_bytes": int(span_bytes) or None,
        "chunk_bytes": int(_median(chunk_candidates, 0.0)) or None,
        "boundaries": int(round(boundaries)) or None,
        "round_cadence_s": round(_median(gaps, 0.0), 4) or None,
        "overlap": any(r.get("mode") == "overlap" for r in ledgers),
        "restores": len(restores),
    }
    # a recorded run config (the driver's run.config event; a real fleet's
    # logged flags) beats inference — config is KNOWN, only physics needs
    # fitting. The newest record wins; estimator values above fill gaps.
    config_events = [r for r in events if r.get("event") == ev.RUN_CONFIG]
    config_fields = 0
    if config_events:
        newest = config_events[-1]
        for key in ("window_s", "group_size", "span_bytes", "chunk_bytes",
                    "boundaries", "samples_per_boundary", "overlap",
                    "compression"):
            if newest.get(key) is not None:
                workload[key] = newest[key]
                config_fields += 1
    if rounds_by_id and "compression" not in workload:
        # the wire-byte observations already bake in whatever codec the
        # run used; a sweep's compression axis is RELATIVE to that level,
        # so not knowing it makes that one axis untrustworthy — say so
        warnings.append(
            "recorded wire-compression level unknown (no run.config "
            "'compression' field): the replay treats recorded bytes as "
            "uncompressed, so sweep predictions across compression "
            "levels are relative to the run's actual level, not to none"
        )
    if restores:
        workload["restore_bytes"] = int(_median(
            [float(r.get("bytes", 0.0)) for r in restores], 0.0
        ))
        workload["restore_providers"] = int(_median(
            [float(r.get("providers", 1.0)) for r in restores], 1.0
        ))
    if workload.get("window_s") is None and workload["round_cadence_s"]:
        # no recorded config: recover the matchmaking window from the
        # cadence: cadence ≈ compute + formation + round wall + (window+1)
        # idle (the workload driver's round spacing). Weakly identified —
        # prefer logs that carry run.config.
        compute_med = _median(
            [p["compute_s"] for p in peers.values()], DEFAULT_COMPUTE_S
        )
        # the cadence is measured between the EARLIEST member's round
        # starts, so the formation term on its critical path is the fast
        # tail of the formation distribution, not its median
        est = (
            workload["round_cadence_s"]
            - percentile(formation, 0.25)
            - percentile(round_walls, 0.50)
            - compute_med * (workload["boundaries"] or 1)
            - 1.0
        )
        workload["window_s"] = round(max(1.0, est), 2)
    if not rounds_by_id:
        warnings.append(
            "no avg.round spans: workload shape is unknown — replay needs "
            "explicit overrides (rounds/group_size/span_bytes)"
        )

    per_peer_wall: Dict[str, List[float]] = {}
    for rs in rounds_by_id.values():
        for r in rs:
            if r.get("dur_s") is not None and r.get("ok") is not False:
                per_peer_wall.setdefault(
                    str(r.get("peer", "?")), []
                ).append(float(r["dur_s"]))
    step_ts = [
        float(r.get("t", 0.0))
        for records in step_records.values() for r in records
    ]
    total_samples = sum(
        float(r.get("samples", 0.0))
        for records in step_records.values() for r in records
    )
    samples_per_sec = None
    if len(step_ts) >= 2 and max(step_ts) > min(step_ts):
        samples_per_sec = round(
            total_samples / (max(step_ts) - min(step_ts)), 3
        )
    def _pct(values: List[float], q: float) -> Optional[float]:
        # None, not 0.0: an unmeasured metric must stay distinguishable
        # from an instant one in the archived model and fidelity table
        return round(percentile(values, q), 4) if values else None

    observed: Dict[str, Any] = {
        "round_wall_p50_s": _pct(round_walls, 0.50),
        "round_wall_p95_s": _pct(round_walls, 0.95),
        "formation_p50_s": _pct(formation, 0.50),
        "formation_p95_s": _pct(formation, 0.95),
        "samples_per_sec": samples_per_sec,
        "overlap_efficiency": (
            round(hidden / (hidden + exposed), 4)
            if (hidden + exposed) > 0 else None
        ),
        "per_peer_round_wall_s": {
            label: round(sum(walls) / len(walls), 4)
            for label, walls in sorted(per_peer_wall.items())
        },
    }
    # worst-first directed links by their OBSERVED contended send rate
    # (wire bytes over send wall — the same observable the replay's report
    # ranks by, so the fidelity comparison is like-for-like); links that
    # never carried round traffic rank by fitted bandwidth estimates
    measured_links: List[List[Any]] = []
    for key, spec in links.items():
        src, dst_label = key.split(LINK_KEY_SEP, 1)
        f = fits[(src, dst_label)]
        if f.wire_bytes > 0 and f.wire_send_s > 0:
            measured_links.append(
                [src, dst_label, round(f.wire_bytes / f.wire_send_s, 1)]
            )
        elif f.peak_bps is not None or f.goodput_bps is not None:
            measured_links.append([src, dst_label, spec["bandwidth_bps"]])
    measured_links.sort(key=lambda item: item[2])
    observed["worst_links"] = measured_links[:10]

    coverage: Dict[str, Any] = {
        "event_rows": len(events),
        "health_records": len(healths),
        "peers_total": len(peers),
        "peers_with_compute": peers_with_compute,
        "links_fitted": len(links),
        "links_with_rtt": links_with_rtt,
        "links_rtt_backfilled_from_median": links_rtt_backfilled,
        "links_with_bandwidth": links_with_bw,
        "links_with_uplink_estimate": links_with_uplink,
        "links_from_wire_aggregates": links_from_wire,
        "links_with_loss": links_with_loss,
        "rounds_from_health_folds": rounds_from_folds,
        "workload_from_config_fields": config_fields,
        "defaults_used": sorted(
            ({"links"} if not links else set())
            | ({"compute"} if peers_with_compute == 0 else set())
            | ({"workload"} if not rounds_by_id and not config_fields
               else set())
        ),
        "warnings": warnings,
    }
    for warning in warnings:
        logger.warning(f"twin fit: {warning}")
    return TwinModel(
        peers=peers,
        links=links,
        default_link={k: float(v) for k, v in defaults.items()},
        workload=workload,
        observed=observed,
        coverage=coverage,
    )
