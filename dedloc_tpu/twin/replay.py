"""Replay a fitted TwinModel in the simulator and score its fidelity.

``replay_twin`` re-instantiates the recorded swarm on the discrete-event
engine: one simulated peer per fitted peer, every fitted directed link
installed as a ``LinkSpec``, and the RECORDED workload shape (rounds,
group size, span/chunk bytes, boundaries, per-peer compute, restores)
driven by the SAME averaging-workload generator the source scenarios use
(``simulator/scenarios.run_averaging_workload``). Everything runs in
virtual time — a fleet-day of rounds costs seconds of wall.

``fidelity_report`` is the observability heart: it replays the model
against its OWN recorded workload and compares twin-predicted vs observed
metrics — round-wall p50/p95, formation latency, samples/sec, overlap
efficiency, per-peer round walls, and the worst-link ranking — emitting a
machine-readable report (rendered by ``runlog_summary --twin``) so model
drift is itself observable. The report's ``max_abs_error`` is the
fidelity bound ``tools/twin_sweep.py`` turns into a confidence interval
around every prediction: a sweep is only as trustworthy as the twin, and
the twin SAYS how trustworthy it is.

Workload overrides (the sweep's knobs) map onto the recorded shape:

- ``chunk_size`` (fp32 elements, the ``--averager.chunk_size`` knob) or
  ``chunk_bytes`` directly;
- ``compression``: none | float16 | uint8 — scales wire span bytes by the
  codec ratio relative to the recorded level;
- ``overlap``: accumulate during the round instead of before it;
- ``group_size``: re-partitions the SAME total vector — per-link span
  scales by recorded_group/new_group, partners by (new_group - 1);
- ``fetch_parallelism`` / ``restore_bytes``: the checkpoint-restore leg.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from dedloc_tpu.simulator.engine import SimEngine
from dedloc_tpu.simulator.network import LinkSpec, SimNetwork
from dedloc_tpu.simulator.swarm import SimSwarm
from dedloc_tpu.simulator.scenarios import run_averaging_workload
from dedloc_tpu.twin.fit import LINK_KEY_SEP, TwinModel
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# wire bytes per fp32 element under each codec level (core/serialization)
COMPRESSION_RATIO = {"none": 1.0, "float16": 0.5, "uint8": 0.25}

# replay cost guard: enough rounds for a p50/p95, cheap enough to sweep
DEFAULT_REPLAY_ROUNDS = 4

# the fidelity pass replays the recorded workload, but a fleet-day
# recording must not turn every --twin / sweep startup into thousands of
# replayed rounds: the round-wall percentiles are statistically settled
# long before this many rounds
FIDELITY_REPLAY_ROUNDS_CAP = 12


def _workload_spec(model: TwinModel,
                   overrides: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The averaging-workload spec for this model + overrides. Overrides
    win over the recorded workload; recorded gaps fall back to driver
    defaults (the fit's coverage already warned about them)."""
    overrides = dict(overrides or {})
    recorded = model.workload
    group_rec = int(recorded.get("group_size") or 8)
    group = int(overrides.get("group_size", group_rec))
    span_rec = int(recorded.get("span_bytes") or 98304)
    # the same total vector re-partitioned across a different group width:
    # V = span_rec * group_rec hosts a span of V/group per link
    span = int(overrides.get(
        "span_bytes", max(1024, span_rec * group_rec // max(1, group))
    ))
    compression = str(overrides.get("compression", "none")).lower()
    recorded_level = str(recorded.get("compression", "none")).lower()
    ratio = (
        COMPRESSION_RATIO.get(compression, 1.0)
        / COMPRESSION_RATIO.get(recorded_level, 1.0)
    )
    span = max(1024, int(span * ratio))
    if "chunk_size" in overrides:  # fp32 elements, the averager's knob
        chunk_bytes = max(1024, int(overrides["chunk_size"]) * 4)
    else:
        chunk_bytes = int(overrides.get(
            "chunk_bytes", recorded.get("chunk_bytes") or 24576
        ))
    rounds = overrides.get("rounds")
    if rounds is None:  # an explicit None means "pick for me" too
        rounds = min(
            DEFAULT_REPLAY_ROUNDS,
            recorded.get("rounds") or DEFAULT_REPLAY_ROUNDS,
        )
    spec: Dict[str, Any] = {
        "avg_rounds": max(1, int(rounds)),
        "group_size": group,
        "span_bytes": span,
        "chunk_bytes": min(chunk_bytes, span),
        "boundaries": int(overrides.get(
            "boundaries", recorded.get("boundaries") or 2
        )),
        "overlap": bool(overrides.get(
            "overlap", recorded.get("overlap", False)
        )),
        "window_s": float(overrides.get(
            "window_s", recorded.get("window_s") or 5.0
        )),
        "prefix": "twinreplay",
        # recorded by the replay's own run.config: a re-fit of the replay's
        # dump keeps the right compression baseline
        "compression": compression,
    }
    samples = overrides.get(
        "samples_per_boundary", recorded.get("samples_per_boundary")
    )
    if samples is not None:  # else: replay_twin's per-peer median fallback
        spec["samples_per_boundary"] = int(samples)
    if int(overrides.get(
        "restore_bytes", recorded.get("restore_bytes") or 0
    )) > 0 and (recorded.get("restores") or overrides.get("restore_bytes")):
        spec["restore_bytes"] = int(overrides.get(
            "restore_bytes", recorded.get("restore_bytes") or 0
        ))
        spec["restore_providers"] = int(overrides.get(
            "restore_providers", recorded.get("restore_providers") or 4
        ))
        spec["fetch_parallelism"] = int(
            overrides.get("fetch_parallelism", 4)
        )
    return spec


def replay_twin(
    model: TwinModel,
    overrides: Optional[Dict[str, Any]] = None,
    seed: int = 0,
    out_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Run the model's workload (with ``overrides``) on a simulated swarm
    built ENTIRELY from the fitted numbers; returns the predicted report
    (the ``run_averaging_workload`` section plus timing and the config it
    ran). ``out_dir`` dumps the replay's own per-peer JSONL — a twin run
    is itself observable by the same tools."""
    labels = sorted(model.peers)
    if len(labels) < 2:
        raise ValueError(
            f"twin has {len(labels)} peer(s); replay needs at least 2"
        )
    # fitted peer -> simulated host, in sorted-label order (sim hosts are
    # peer-0000... by spawn index; a sim-sourced twin maps onto itself)
    host_of = {label: f"peer-{i:04d}" for i, label in enumerate(labels)}
    engine = SimEngine(seed=seed)
    default_spec = LinkSpec.from_dict(model.default_link)
    link_table = {}
    for key in model.links:
        src, dst = key.split(LINK_KEY_SEP, 1)
        if src in host_of and dst in host_of:
            link_table[(host_of[src], host_of[dst])] = model.link_spec(
                src, dst
            )
    network = SimNetwork(
        seed=seed, default_link=default_spec, links=link_table
    )
    swarm = SimSwarm(network, seed=seed)
    spec = _workload_spec(model, overrides)
    spec["compute_s"] = {
        host_of[label]: float(model.peers[label].get(
            "compute_s", 0.05
        ))
        for label in labels
    }
    if "samples_per_boundary" not in spec:
        # no recorded config and no override: median of the per-peer
        # step.record fits
        spec["samples_per_boundary"] = int(
            sorted(
                float(p.get("samples_per_boundary", 16))
                for p in model.peers.values()
            )[len(labels) // 2]
        )
    wall0 = time.perf_counter()
    try:
        with engine:
            engine.run(swarm.spawn(len(labels)))
            report = engine.run(
                run_averaging_workload(swarm, spec),
                timeout=float(spec.get("virtual_timeout_s", 36000.0)),
            )
            if out_dir is not None:
                report["event_logs"] = swarm.dump_event_logs(out_dir)
            engine.run(swarm.shutdown())
    finally:
        engine.close()
    report["wall_s"] = round(time.perf_counter() - wall0, 3)
    report["seed"] = seed
    report["peers"] = len(labels)
    # predictions are keyed back to the FITTED peer labels
    unhost = {host: label for label, host in host_of.items()}
    report["per_peer_round_wall_s"] = {
        unhost.get(host, host): wall
        for host, wall in report.get("per_peer_round_wall_s", {}).items()
    }
    report["worst_links"] = [
        [unhost.get(src, src), unhost.get(dst, dst), bps]
        for src, dst, bps in report.get("worst_links", [])
    ]
    return report


def _error(observed: Optional[float],
           predicted: Optional[float]) -> Optional[float]:
    if observed is None or predicted is None or observed <= 0:
        return None
    return (predicted - observed) / observed


def fidelity_report(
    model: TwinModel,
    replay: Optional[Dict[str, Any]] = None,
    seed: int = 0,
) -> Dict[str, Any]:
    """Twin-predicted vs observed, per metric, per peer and swarm-wide —
    THE observability artifact of the twin pipeline. ``replay`` defaults
    to replaying the model's own recorded workload at full recorded round
    count (prediction and observation must describe the same workload)."""
    if replay is None:
        recorded_rounds = model.workload.get("rounds")
        replay = replay_twin(
            model,
            overrides={
                "rounds": (
                    min(int(recorded_rounds), FIDELITY_REPLAY_ROUNDS_CAP)
                    if recorded_rounds else None
                )
            },
            seed=seed,
        )
    observed = model.observed
    metrics: Dict[str, Dict[str, Optional[float]]] = {}
    for name in (
        "round_wall_p50_s", "round_wall_p95_s",
        "formation_p50_s", "formation_p95_s",
        "samples_per_sec", "overlap_efficiency",
    ):
        o = observed.get(name)
        p = replay.get(name)
        o = float(o) if o is not None else None
        p = float(p) if p is not None else None
        if o is None and p is None:
            continue
        err = _error(o, p)
        metrics[name] = {
            "observed": o,
            "predicted": p,
            "error": round(err, 4) if err is not None else None,
        }

    per_peer: Dict[str, Dict[str, Optional[float]]] = {}
    observed_walls = observed.get("per_peer_round_wall_s") or {}
    predicted_walls = replay.get("per_peer_round_wall_s") or {}
    for label in sorted(set(observed_walls) | set(predicted_walls)):
        o = observed_walls.get(label)
        p = predicted_walls.get(label)
        err = _error(o, p)
        per_peer[label] = {
            "observed_round_wall_s": o,
            "predicted_round_wall_s": p,
            "error": round(err, 4) if err is not None else None,
        }

    # worst-link ranking agreement: does the twin still point at the same
    # bottleneck links? (top-1 match + top-3 set overlap)
    obs_rank = [
        (src, dst) for src, dst, _bps in observed.get("worst_links") or []
    ]
    pred_rank = [
        (src, dst) for src, dst, _bps in replay.get("worst_links") or []
    ]
    worst_links: Dict[str, Any] = {
        "observed": [list(pair) for pair in obs_rank[:3]],
        "predicted": [list(pair) for pair in pred_rank[:3]],
    }
    def bottleneck(rank: List[tuple]) -> Optional[str]:
        """The peer most entangled in the worst links — the 'who do I
        upgrade first' answer, robust to which exact directed pair tops
        the list on a given seed."""
        counts: Dict[str, int] = {}
        for src, dst in rank[:3]:
            counts[src] = counts.get(src, 0) + 1
            counts[dst] = counts.get(dst, 0) + 1
        if not counts:
            return None
        return max(sorted(counts), key=lambda p: counts[p])

    if obs_rank and pred_rank:
        worst_links["top1_match"] = obs_rank[0] == pred_rank[0]
        k = min(3, len(obs_rank), len(pred_rank))
        worst_links["top3_overlap"] = (
            len(set(obs_rank[:k]) & set(pred_rank[:k])) / k
        )
        worst_links["bottleneck_observed"] = bottleneck(obs_rank)
        worst_links["bottleneck_predicted"] = bottleneck(pred_rank)
        worst_links["bottleneck_match"] = (
            worst_links["bottleneck_observed"]
            == worst_links["bottleneck_predicted"]
        )

    errors = [
        abs(m["error"]) for m in metrics.values()
        if m.get("error") is not None
    ]
    # the bound the sweep turns into a CI: only the metrics a sweep
    # actually predicts (throughput and round wall) — formation tails are
    # matchmaking-dynamics noise and would inflate the interval into
    # uselessness without making the throughput prediction any worse
    sweep_errors = [
        abs(metrics[name]["error"])
        for name in ("round_wall_p50_s", "samples_per_sec")
        if name in metrics and metrics[name].get("error") is not None
    ]
    report = {
        "view": "twin",
        "peers": len(model.peers),
        "links_fitted": len(model.links),
        "workload": model.workload,
        "metrics": metrics,
        "per_peer": per_peer,
        "worst_links": worst_links,
        "max_abs_error": round(max(errors), 4) if errors else None,
        "sweep_error_bound": (
            round(max(sweep_errors), 4) if sweep_errors
            else (round(max(errors), 4) if errors else None)
        ),
        "coverage": model.coverage,
        "replay_wall_s": replay.get("wall_s"),
    }
    return report
