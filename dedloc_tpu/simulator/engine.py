"""The discrete-event engine: a virtual-time asyncio event loop.

The whole point of the simulator is that the code above the transport seam —
``dht/node.py`` lookups, ``averaging/matchmaking.py`` windows,
``checkpointing/fetcher.py`` backoff ladders — runs UNMODIFIED. All of that
code waits with ``asyncio.sleep`` / ``wait_for`` and reads deadlines off
``get_dht_time()``, so the engine virtualizes exactly those two clocks:

- ``SimLoop`` subclasses the stock selector event loop but reports
  ``time()`` from a frozen, seeded ``FakeClock``. Whenever the loop would
  BLOCK in ``select(timeout)`` waiting for the next timer, the wrapped
  selector instead polls ready I/O (there is none in a pure simulation —
  the simulated transport is queue-based) and JUMPS the clock forward by
  ``timeout``. A scenario that spans hours of straggler windows and DHT
  expirations executes in however long its Python takes, with zero real
  sleeping.
- Every timer deadline gets a strictly-positive seeded epsilon
  (``FakeClock.tiebreak_epsilon``) so no two timers are ever exactly equal:
  same-timestamp ordering is a pure function of the clock seed, not of
  timer-heap internals that vary across Python versions. One seed therefore
  reproduces one global event order, bit for bit.
- ``get_dht_time()`` is overridden at the source (``FakeClock(frozen=True)``)
  so real seconds spent executing scenario Python never leak into the
  simulated timeline.
- ``run_in_executor`` executes inline: a worker thread finishing "whenever
  the OS scheduler felt like it" is exactly the nondeterminism the engine
  exists to remove.

Determinism contract: same engine seed + same scenario code => identical
event sequence, including every telemetry event each simulated peer logs
(modulo wall-clock ``t`` stamps and random span ids; within one process —
dict/set iteration order also depends on the interpreter's hash seed).
"""
from __future__ import annotations

import asyncio
import contextlib
import heapq
import selectors
from typing import Any, Awaitable, Optional

from dedloc_tpu.testing.faults import FakeClock
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# virtual absolute epoch: scenario timestamps must be absolute (records
# compare expirations) but must not depend on when the host runs the
# scenario, or two same-seed runs would diverge. Deliberately SMALL: at a
# unix-scale epoch (1.6e9) a float's resolution is ~2.4e-7 s, swallowing
# the engine's sub-microsecond timer tie-breaks; at 1e6 it is ~1.2e-10 s.
SIM_EPOCH = 1_000_000.0

# a pure simulation that selects with nothing ready, nothing scheduled and
# no main-future callback pending is deadlocked — fail loudly instead of
# spinning forever (real harm: a wedged CI box with zero diagnostics)
_IDLE_POLLS_BEFORE_DEADLOCK = 400
_IDLE_POLL_REAL_S = 0.005

# real-readiness polling cadence: in a pure simulation the only registered
# fd is the loop's self-pipe, whose sole job is waking a BLOCKED select —
# and this selector never blocks. ``call_soon_threadsafe`` appends its
# handle to ``_ready`` regardless, so skipping the poll can never lose a
# callback; the pipe is drained every Nth tick (and on every idle tick) so
# its buffer stays bounded. One real ``select(0)`` per event was ~15% of a
# large scenario's wall time.
_REAL_POLL_EVERY = 64

# same-instant timer batching: every ``call_at`` deadline carries a seeded
# tie-break epsilon in (0, ~2.002e-6] (FakeClock.tiebreak_epsilon at the
# default 1e-6 scale), so timers for one MODELED instant are spread over a
# ~2 µs band and, at asyncio's default nanosecond clock resolution, each
# cost a full loop iteration. Widening the loop's ``_clock_resolution`` to
# cover the whole band pops the batch in ONE iteration — still in heap
# (= seeded tie-break) order. Safe because distinct modeled instants are
# always >= _STREAM_STEP_S (1e-5 s) apart at the network layer and >= ms
# in scenario code, both far above this window.
_BATCH_RESOLUTION_S = 2.5e-6


class _JumpingSelector:
    """Selector proxy: polls real readiness (the loop's self-pipe, mostly)
    and converts every would-be blocking wait into a clock jump."""

    def __init__(self, inner: selectors.BaseSelector, loop: "SimLoop"):
        self._inner = inner
        self._loop = loop
        self._idle_polls = 0
        self._ticks_since_real_poll = 0

    def select(self, timeout: Optional[float] = None):
        # throttled real poll (see _REAL_POLL_EVERY): a simulation tick
        # normally skips the syscall entirely. Any extra registered fd
        # (beyond the loop's own self-pipe) disables the throttle — real
        # I/O readiness must not be deferred by up to N virtual events.
        self._ticks_since_real_poll += 1
        if (
            self._ticks_since_real_poll >= _REAL_POLL_EVERY
            or len(self._inner.get_map()) > 1
        ):
            self._ticks_since_real_poll = 0
            events = self._inner.select(0)
            if events:
                self._idle_polls = 0
                return events
        if timeout is not None and timeout > 0:
            # nothing ready, next loop timer is ``timeout`` virtual seconds
            # out: this is the discrete-event jump. Land EXACTLY on the
            # earlier of the next timer deadline and the next FakeClock
            # ``wake_at`` sleeper — jumping by the float difference instead
            # can fall short by one ulp and spin the loop (offset + tiny ==
            # offset near large offsets), and overjumping a sleeper would
            # run its continuations at the wrong virtual time.
            self._idle_polls = 0
            loop = self._loop
            target = loop.time() + timeout
            sched = loop._scheduled
            if sched and sched[0]._when <= target + 1e-6:
                target = max(loop.time(), sched[0]._when)
            wake = loop.clock.next_wake()
            if wake is not None and wake < target:
                target = max(loop.time(), wake)
            loop.clock.advance_to(target)
            return []
        if timeout is None:
            # no ready callbacks AND no loop timers. A pending FakeClock
            # sleeper can still drive the simulation forward (its callback
            # may resolve whatever the scenario awaits); otherwise only
            # cross-thread wakeups could unblock us — poll briefly
            # (executors are inlined, but a user's thread may still
            # call_soon_threadsafe), and treat a long silence as a
            # simulation deadlock.
            wake = self._loop.clock.next_wake()
            if wake is not None:
                self._idle_polls = 0
                self._loop.clock.advance_to(max(self._loop.time(), wake))
                return []
            self._idle_polls += 1
            if self._idle_polls >= _IDLE_POLLS_BEFORE_DEADLOCK:
                raise RuntimeError(self._deadlock_message())
            self._ticks_since_real_poll = 0
            return self._inner.select(_IDLE_POLL_REAL_S)
        return []

    def _deadlock_message(self) -> str:
        """A deadlock report a wedged 10k-peer CI run is debuggable from:
        how many sleepers are pending-but-unreachable (nothing left that
        could ever advance the clock to them), and which stalled task is
        the oldest (lowest creation sequence — usually the one everybody
        else transitively awaits)."""
        loop = self._loop
        stats = loop.clock.sleeper_stats()
        tasks = [t for t in asyncio.all_tasks(loop) if not t.done()]

        def _task_age(task: "asyncio.Task") -> tuple:
            name = task.get_name()
            digits = name.rsplit("-", 1)[-1]
            return (0, int(digits)) if digits.isdigit() else (1, 0)

        oldest = min(tasks, key=_task_age) if tasks else None
        oldest_desc = "none"
        if oldest is not None:
            coro = oldest.get_coro()
            coro_name = getattr(coro, "__qualname__", repr(coro))
            oldest_desc = f"{oldest.get_name()!r} ({coro_name})"
        return (
            "simulation deadlocked: no ready callbacks, no timers, and "
            "nothing external to wait for "
            f"(unreachable sleepers: {stats['live']} live + "
            f"{stats['cancelled_resident']} cancelled-resident; "
            f"stalled tasks: {len(tasks)}, oldest: {oldest_desc})"
        )

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


class SimLoop(asyncio.SelectorEventLoop):
    """Virtual-time event loop over a frozen seeded FakeClock."""

    def __init__(self, clock: FakeClock):
        super().__init__()
        self.clock = clock
        self._selector = _JumpingSelector(self._selector, self)
        # batch the per-instant epsilon spread into one loop iteration
        # (see _BATCH_RESOLUTION_S): heap order within the batch is the
        # seeded tie-break order, so determinism is unchanged
        self._clock_resolution = _BATCH_RESOLUTION_S

    def time(self) -> float:
        return self.clock.offset

    def call_at(self, when, callback, *args, context=None):
        # the seeded tie-break (see FakeClock.tiebreak_epsilon): distinct
        # deadlines make same-timestamp ordering a function of the seed,
        # and the microsecond-scale magnitude can never move a deadline
        # across any boundary a scenario models (latencies are >= ms).
        # Inlined TimerHandle construction (the non-debug body of
        # BaseEventLoop.call_at): this is the hottest call site of a large
        # scenario — several hundred thousand timers — and the base-class
        # wrapper's debug/closed checks measurably add up.
        timer = asyncio.TimerHandle(
            when + self.clock.tiebreak_epsilon(), callback, args, self,
            context,
        )
        heapq.heappush(self._scheduled, timer)
        timer._scheduled = True
        return timer

    def run_in_executor(self, executor, func, *args):
        # inline for determinism: thread completion order is real-time
        fut = self.create_future()
        try:
            fut.set_result(func(*args))
        except Exception as e:  # noqa: BLE001 — mirror executor semantics
            fut.set_exception(e)
        return fut


class SimEngine:
    """Owns the virtual loop + clock and runs scenario coroutines.

    Usage::

        engine = SimEngine(seed=0)
        result = engine.run(scenario())   # drives to completion, no sleeps
        engine.close()

    or as a context manager. ``engine.clock`` is the shared FakeClock
    (frozen: ``get_dht_time()`` IS virtual time while the engine runs).
    """

    def __init__(self, seed: int = 0, start: float = SIM_EPOCH):
        self.seed = int(seed)
        self.clock = FakeClock(start=start, seed=seed, frozen=True)
        self.loop = SimLoop(self.clock)
        self._entered = False

    # ------------------------------------------------------------ lifecycle

    def __enter__(self) -> "SimEngine":
        self.clock.__enter__()
        self._entered = True
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def run(self, coro: Awaitable[Any], timeout: Optional[float] = None) -> Any:
        """Drive ``coro`` to completion at fake-clock speed. ``timeout`` is
        VIRTUAL seconds (a scenario guard, not a wall limit)."""
        # (re-)install THIS engine's clock every run — like the event loop,
        # the dht-time source is process-global, and another engine created
        # or closed in between (the sim_swarm fixture keeps several) would
        # otherwise leave its clock (or the wall clock) installed
        self.clock.__enter__()
        self._entered = True
        asyncio.set_event_loop(self.loop)
        if timeout is not None:
            coro = asyncio.wait_for(coro, timeout=timeout)
        try:
            return self.loop.run_until_complete(coro)
        finally:
            asyncio.set_event_loop(None)

    def close(self) -> None:
        # drain BEFORE restoring the wall clock: cancelling stragglers
        # (maintenance loops, parked reads) still ticks the virtual loop,
        # and every tick re-installs the fake offset process-globally — a
        # drain after clock.__exit__ would leave it installed forever
        if not self.loop.is_closed():
            pending = asyncio.all_tasks(self.loop)
            for task in pending:
                task.cancel()
            if pending:
                with contextlib.suppress(Exception):
                    self.loop.run_until_complete(
                        asyncio.gather(*pending, return_exceptions=True)
                    )
            self.loop.close()
        if self._entered:
            self.clock.__exit__()
            self._entered = False
