"""Named simulator scenarios + the sizing report.

A scenario is a JSON-able spec dict run to a REPORT dict — the numbers an
operator (or a test) needs before renting a fleet: how wide DHT records
actually replicate at N peers, how contended matchmaking leadership gets at
J concurrent joiners, how round-formation latency distributes, how big the
checkpoint catalog record grows per announcer. ``tools/swarm_sim.py`` is
the CLI face; ``tests/test_simulator.py`` asserts the report numbers
against scenario-level bounds.

Spec schema (docs/simulator.md):

    {
      "scenario": "mixed",          # dht_churn | matchmaking | catalog | mixed
      "seed": 0,                     # engine + network + churn seed
      "peers": 1000,                 # swarm size
      "link": {"latency_s": 0.02, "bandwidth_bps": 12500000.0,
               "loss": 0.0, "jitter_s": 0.0},
      "bucket_size": 8, "num_replicas": 5, "parallel_rpc": 3,
      ...scenario-specific keys (each runner documents its own)
    }

Every runner is deterministic for a fixed spec: scenario randomness comes
from ``random.Random(seed)``, peer ids/bootstrap choices hash off the same
seed, and the engine freezes scenario time.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import os
import random
import time
from types import SimpleNamespace
from typing import Any, Callable, Dict, List, Optional

from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.simulator.engine import SIM_EPOCH, SimEngine
from dedloc_tpu.simulator.network import LinkSpec, SimNetwork
from dedloc_tpu.simulator.swarm import SimSwarm
from dedloc_tpu.telemetry.links import endpoint_key
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# the one shared nearest-rank percentile (utils/stats.py) — the twin
# fitter computes the identical statistic from dumped logs, and the two
# must never drift
from dedloc_tpu.utils.stats import percentile  # noqa: F401 (re-export)


def _span_durations(swarm: SimSwarm, name: str,
                    ok_only: bool = True) -> List[float]:
    out = []
    for peer in swarm.peers:
        for record in peer.telemetry.events:
            if record.get("event") != name:
                continue
            if ok_only and record.get("ok") is not True:
                continue
            out.append(float(record.get("dur_s", 0.0)))
    return out


def record_fanout(swarm: SimSwarm, key: bytes) -> int:
    """How many live peers hold ``key`` in primary storage — the measured
    replica fan-out a sizing decision needs vs the configured
    ``num_replicas`` bound."""
    count = 0
    for peer in swarm.alive_peers():
        if peer.node.storage.get(key) is not None:
            count += 1
    return count


# --------------------------------------------------------------- harness


# scenarios whose subject is swarm-scale behavior (lookup fan-out, group
# contention, catalog load) — these spawn warm by default. Everything else
# (averaging fidelity, closed-loop adaptation, twin fitting, watchdog) is
# measuring signals the eager join protocol itself produces.
_WARM_BY_DEFAULT = frozenset(
    {"dht_churn", "matchmaking", "catalog", "mixed", "diurnal", "serving"}
)


class ScenarioRun:
    """Everything a scenario phase needs in one handle."""

    def __init__(self, spec: Dict[str, Any]):
        self.spec = dict(spec)
        self.seed = int(spec.get("seed", 0))
        self.rng = random.Random(self.seed ^ 0xC0FFEE)
        self.engine = SimEngine(seed=self.seed)
        self.network = SimNetwork(
            seed=self.seed, default_link=LinkSpec.from_dict(spec.get("link"))
        )
        self.swarm = SimSwarm(
            self.network,
            seed=self.seed,
            bucket_size=int(spec.get("bucket_size", 8)),
            num_replicas=int(spec.get("num_replicas", 5)),
            parallel_rpc=int(spec.get("parallel_rpc", 3)),
            request_timeout=float(spec.get("request_timeout", 5.0)),
            # swarm-scale scenarios hydrate warm by default: routing
            # tables are injected from the known topology instead of
            # paying per-peer bootstrap RPC storms. Fidelity/adaptation
            # scenarios keep the eager join protocol — their link tables
            # and re-plan triggers are FED by bootstrap-era traffic, so
            # skipping it would change the very signal they measure.
            # Spec {"warm_spawn": ...} overrides either default.
            warm_spawn=bool(spec.get(
                "warm_spawn", spec.get("scenario") in _WARM_BY_DEFAULT
            )),
        )
        self.report: Dict[str, Any] = {
            "scenario": spec.get("scenario"),
            "seed": self.seed,
            "peers": int(spec.get("peers", 100)),
        }


# --------------------------------------------------------------- phases
#
# Phases are composable coroutine builders: each takes (run, spec) and
# fills a section of run.report. The mixed scenario chains them over ONE
# swarm — churn from the DHT phase is still in effect when matchmaking
# starts, which is the point.


async def phase_spawn(run: ScenarioRun) -> None:
    n = int(run.spec.get("peers", 100))
    # real wall on purpose (the sizing report contrasts wall vs virtual)
    t0 = time.perf_counter()  # dedlint: disable=clock-monotonic
    v0 = run.engine.clock.offset
    await run.swarm.spawn(n, bootstrap_fanout=int(
        run.spec.get("bootstrap_fanout", 2)
    ))
    run.report["spawn"] = {
        "peers": n,
        "wall_s": round(  # real wall on purpose (wall vs virtual)
            time.perf_counter() - t0, 3  # dedlint: disable=clock-monotonic
        ),
        "virtual_s": round(run.engine.clock.offset - v0, 3),
    }


async def phase_dht(run: ScenarioRun) -> None:
    """Puts from scattered writers, churn a fraction of the swarm, then
    reads — measuring replica fan-out vs the ``num_replicas`` bound and
    get success under churn."""
    spec = run.spec
    puts = int(spec.get("puts", 40))
    churn_fraction = float(spec.get("churn_fraction", 0.2))
    swarm, rng = run.swarm, run.rng
    keys = [f"sim-record-{i:03d}".encode() for i in range(puts)]
    now = get_dht_time()
    stored = 0
    for i, key in enumerate(keys):
        writer = swarm.alive_peers()[
            rng.randrange(len(swarm.alive_peers()))
        ]
        if await writer.node.store(key, b"v-%d" % i, now + 3600.0):
            stored += 1
    fanout = [record_fanout(swarm, key) for key in keys]
    # churn: kill a seeded sample, all at once (mass-disconnect shape)
    victims = rng.sample(
        swarm.alive_peers(), int(len(swarm.alive_peers()) * churn_fraction)
    )
    for victim in victims:
        await swarm.kill(victim)
    await asyncio.sleep(1.0)  # virtual settling time
    hits = 0
    for i, key in enumerate(keys):
        reader = swarm.alive_peers()[
            rng.randrange(len(swarm.alive_peers()))
        ]
        entry = await reader.node.get(key, latest=True)
        if entry is not None and entry.value == b"v-%d" % i:
            hits += 1
    run.report["dht"] = {
        "puts": puts,
        "stored": stored,
        "replica_bound": swarm.num_replicas + 1,  # nearest set + self-store
        "fanout_mean": round(sum(fanout) / max(1, len(fanout)), 2),
        "fanout_max": max(fanout) if fanout else 0,
        "churned": len(victims),
        "get_hits": hits,
        "get_success": round(hits / max(1, puts), 3),
    }


async def phase_matchmaking(run: ScenarioRun) -> None:
    """R rounds of J concurrent joiners targeting ``group_size`` — the
    leader-contention measurement: do groups form without livelock, how
    many leaders fight per round, and the round-formation latency
    distribution."""
    spec = run.spec
    joiners = int(spec.get("joiners", 50))
    rounds = int(spec.get("rounds", 5))
    group_size = int(spec.get("group_size", 16))
    window = float(spec.get("window_s", 5.0))
    prefix = str(spec.get("prefix", "simexp"))
    swarm, rng = run.swarm, run.rng
    pool = [p for p in swarm.alive_peers()]
    participants = (
        pool if joiners >= len(pool) else rng.sample(pool, joiners)
    )
    for peer in participants:
        if peer.matchmaking is None:
            peer.attach_matchmaking(
                prefix, bandwidth=50.0 + (peer.index % 7) * 25.0,
                target_group_size=group_size,
                averaging_expiration=window,
            )
    formed: Dict[str, List[int]] = {}
    failures = 0
    for r in range(rounds):
        round_id = f"round-{r:04d}"
        active = [p for p in participants if p.alive]

        async def one(peer):
            try:
                return await peer.matchmaking.form_group(round_id)
            except Exception:  # noqa: BLE001 — counted, scenario continues
                return None

        groups = await asyncio.gather(*(one(p) for p in active))
        sizes = []
        seen_nonces = set()
        for g in groups:
            if g is None:
                failures += 1
            elif g.nonce not in seen_nonces:
                seen_nonces.add(g.nonce)
                sizes.append(len(g.members))
        formed[round_id] = sizes
        # advance past the leader-entry expirations so rounds stay disjoint
        await asyncio.sleep(window + 1.0)
    durs = _span_durations(swarm, "mm.form_group")
    all_sizes = [s for sizes in formed.values() for s in sizes]
    run.report["matchmaking"] = {
        "joiners": len(participants),
        "rounds": rounds,
        "groups_formed": len(all_sizes),
        "mean_group_size": round(
            sum(all_sizes) / max(1, len(all_sizes)), 2
        ),
        "full_groups": sum(1 for s in all_sizes if s >= group_size),
        "singletons": sum(1 for s in all_sizes if s == 1),
        "join_failures": int(swarm.counters_total("mm.join_failures")),
        "leader_changes": int(swarm.counters_total("mm.leader_changes")),
        "form_failures": failures,
        "formation_p50_s": round(percentile(durs, 0.50), 3),
        "formation_p95_s": round(percentile(durs, 0.95), 3),
    }


async def phase_catalog(run: ScenarioRun) -> None:
    """Announcers publish (some divergent) checkpoint manifests; a restorer
    must select the majority digest and complete a sharded multi-provider
    restore over the simulated links."""
    spec = run.spec
    announcers = int(spec.get("announcers", 8))
    divergent = int(spec.get("divergent", 2))
    step = int(spec.get("ckpt_step", 100))
    total_size = int(spec.get("ckpt_total_size", 4096))
    shard_size = int(spec.get("ckpt_shard_size", 512))
    prefix = str(spec.get("prefix", "simexp"))
    swarm, rng = run.swarm, run.rng
    alive = swarm.alive_peers()
    if len(alive) < 2:
        raise ValueError(
            f"catalog phase needs >= 2 live peers (an announcer and a "
            f"restorer); {len(alive)} alive — raise 'peers' or lower churn"
        )
    # clamp: at least one non-provider must remain to play the restorer
    # (reachable from the CLI with e.g. peers=8, announcers=8)
    announcers = min(announcers, len(alive) - 1)
    providers = rng.sample(alive, announcers)
    majority_digest = None
    for i, peer in enumerate(providers):
        variant = 1 if i < divergent else 0  # minority forks first
        digest = peer.serve_checkpoint(
            step, total_size=total_size, shard_size=shard_size,
            variant=variant,
        )
        if variant == 0:
            majority_digest = digest
        ok = await peer.announce_checkpoint(prefix)
        if not ok:
            logger.warning(f"catalog announce failed for {peer.label}")
    from dedloc_tpu.checkpointing.catalog import (
        catalog_key,
        parse_announcements,
        select_target,
    )
    from dedloc_tpu.checkpointing.fetcher import sharded_restore

    reader = rng.choice(
        [p for p in swarm.alive_peers() if p not in providers]
    )
    entry = await reader.node.get(catalog_key(prefix).encode(), latest=True)
    items = (
        [(sk, v.value) for sk, v in entry.value.items()]
        if entry is not None and hasattr(entry.value, "items")
        else []
    )
    announcements = parse_announcements(items)
    # sizing: the ACTUAL stored/wire size — the same msgpack codec the DHT
    # store path uses, not a Python repr approximation
    from dedloc_tpu.core.serialization import pack_obj

    catalog_bytes = sum(
        len(pack_obj(a.model_dump())) for a in announcements
    )
    target = select_target(announcements)
    restored_ok = False
    providers_used = 0
    if target is not None:
        stats: Dict[str, Any] = {}
        try:
            _meta, tree, manifest = await sharded_restore(
                reader.node.client,
                announcements,
                parallelism=int(spec.get("fetch_parallelism", 4)),
                telemetry_registry=reader.telemetry,
                stats=stats,
            )
            restored_ok = (
                manifest.digest() == majority_digest
                and "sim_state" in tree
            )
            providers_used = int(stats.get("providers", 0))
        except Exception as e:  # noqa: BLE001 — reported, not raised
            logger.warning(f"sim restore failed: {e!r}")
    run.report["catalog"] = {
        "announcers": announcers,
        "divergent": divergent,
        "parsed_announcements": len(announcements),
        "selected_majority": bool(
            target is not None and target[1] == majority_digest
        ),
        "restore_ok": restored_ok,
        "providers_used": providers_used,
        "catalog_record_bytes": catalog_bytes,
        "bytes_per_announcer": (
            round(catalog_bytes / max(1, len(announcements)))
        ),
    }


# --------------------------------------------------- averaging workload
#
# The synthetic averaging-round traffic generator: real matchmaking over
# the simulated transport, then real chunked wire exchanges between group
# members — scatter chunks serialized per destination, reduced-chunk
# gather replies pipelined behind them (the PR 3 wire shape) — emitting
# the PRODUCTION telemetry schema (mm.form_group / avg.round /
# allreduce.link / link.stats / step.record / opt.overlap_ledger). It is
# both a sizing scenario in its own right (round-wall numbers for a
# hypothetical network) and the workload the telemetry-fitted digital
# twin (dedloc_tpu/twin) replays: the same driver runs the SOURCE
# scenario and the twin's prediction, so twin fidelity measures the
# quality of the telemetry -> model fit, not a modeling shortcut.


def apply_link_overrides(network: SimNetwork, hosts: List[str],
                         overrides) -> int:
    """Apply spec-level per-directed-link overrides (the ``links`` spec
    key): ``[{"src": host|"*", "dst": host|"*", latency_s?, bandwidth_bps?,
    loss?, jitter_s?}, ...]``. ``"*"`` spans every host; omitted fields
    inherit the network DEFAULT link (not the LinkSpec defaults). Returns
    how many directed links were configured."""
    count = 0
    base = network.default_link
    for raw in overrides or []:
        raw = dict(raw)
        src = str(raw.pop("src", "*"))
        dst = str(raw.pop("dst", "*"))
        spec = LinkSpec(
            latency_s=float(raw.get("latency_s", base.latency_s)),
            bandwidth_bps=float(raw.get("bandwidth_bps", base.bandwidth_bps)),
            loss=float(raw.get("loss", base.loss)),
            jitter_s=float(raw.get("jitter_s", base.jitter_s)),
        )
        for s in (hosts if src == "*" else [src]):
            for d in (hosts if dst == "*" else [dst]):
                if s != d:
                    network.set_link(s, d, spec)
                    count += 1
    return count


def _compute_for(spec: Dict[str, Any], peer) -> float:
    """Per-peer fwd+bwd seconds per boundary. ``compute_s`` is a float (a
    homogeneous swarm, optionally skewed deterministically per peer via
    ``compute_skew``) or a ``{label: seconds}`` map (a twin replay's fitted
    per-peer compute)."""
    compute = spec.get("compute_s", 0.05)
    if isinstance(compute, dict):
        values = [float(v) for v in compute.values()] or [0.05]
        return float(compute.get(peer.label, sum(values) / len(values)))
    return float(compute) * (
        1.0 + float(spec.get("compute_skew", 0.0)) * (peer.index % 4)
    )


async def run_averaging_workload(swarm: SimSwarm,
                                 spec: Dict[str, Any],
                                 on_round: Optional[Callable] = None,
                                 control: Optional[Dict[str, Any]] = None,
                                 ) -> Dict[str, Any]:
    """Drive ``avg_rounds`` averaging rounds over ``swarm`` and return the
    measured report section. Spec keys (all optional)::

        avg_rounds: 4          # rounds to run
        group_size: 8          # matchmaking target
        span_bytes: 98304      # wire payload per DIRECTED link per round
        chunk_bytes: 24576     # scatter/gather chunk size
        boundaries: 2          # accumulation boundaries per round
        samples_per_boundary: 16
        compute_s: 0.05        # float (+compute_skew) or {label: seconds}
        overlap: false         # accumulate DURING the round vs before it
        window_s: 5.0          # averaging_expiration for matchmaking
        rpc_timeout_s: 120.0
        restore_bytes: 0       # >0: one sharded catalog restore at the end
        restore_providers: 4
        fetch_parallelism: 4
        faults: []             # scripted mid-run faults, each fired at the
                               # START of its round:
                               #   {"kind": "link", "at_round": r,
                               #    "src": host, "dst": host,
                               #    bandwidth_bps/latency_s/loss/jitter_s}
                               #     (omitted fields inherit the network
                               #      default; a second fault with healthy
                               #      numbers restores the link;
                               #      "reset_connections": true also kills
                               #      the pair's pooled flows — the
                               #      route-flap shape whose reconnects
                               #      re-sample the link RTT)
                               #   {"kind": "straggler", "at_round": r,
                               #    "peer": label, "factor": 8.0}
                               #   {"kind": "churn", "at_round": r,
                               #    "peers": [labels] | "count": n}

    Every member's exchange opens an ``avg.round`` span, feeds the link
    estimator per scatter chunk, and emits one ``allreduce.link`` event
    per remote hop — the event-log schema production peers write, so the
    twin fitter (and --topology/--steps) consume the dump unchanged.
    ``on_round(r)`` (optional coroutine) runs after each round completes —
    the watchdog scenario's coordinator-fold hook.

    ``control`` (optional) is the LIVE control surface for the closed-loop
    scenario: a mutable dict the ``on_round`` hook may update between
    rounds — ``plan`` (a label-keyed ``TopologyPlan`` or None), ``enabled``
    (run the plan vs account-only), ``chunk_bytes``. Each round re-reads it
    before forming groups, mirroring the runtime averager's between-rounds
    plan adoption (``maybe_refresh_plan``): a plan swap is just a new
    matchmaking scope on the next round, no barrier. The initial values
    seed from the spec, so a plain workload behaves exactly as before."""
    rounds = int(spec.get("avg_rounds", 4))
    group_size = int(spec.get("group_size", 8))
    span_bytes = max(1024, int(spec.get("span_bytes", 98304)))
    chunk_bytes = max(1024, int(spec.get("chunk_bytes", 24576)))
    boundaries = max(1, int(spec.get("boundaries", 2)))
    samples_per_boundary = int(spec.get("samples_per_boundary", 16))
    overlap = bool(spec.get("overlap", False))
    window = float(spec.get("window_s", 5.0))
    rpc_timeout = float(spec.get("rpc_timeout_s", 120.0))
    prefix = str(spec.get("prefix", "twinexp"))
    participants = swarm.alive_peers()
    if len(participants) < 2:
        raise ValueError("averaging workload needs >= 2 live peers")

    # two-level (hierarchical) topology — the ``topology`` spec key::
    #
    #     topology:
    #       cliques: [[label, ...], ...]   # explicit member groups, or
    #       clique_size: 16                # auto-chunk the roster
    #       enabled: true                  # false = run FLAT but keep the
    #                                      #   plan for WAN-byte accounting
    #
    # The plan comes from the SAME planner the runtime averager installs
    # (averaging/topology.plan_from_groups), so the simulator sizes exactly
    # the hierarchy production would run. With ``enabled`` the round shape
    # becomes: clique members exchange over their (cheap) local links under
    # a clique-scoped matchmaking group, delegates carry one span over the
    # WAN among themselves, then members pull the fanned-out result from
    # their delegate. ``enabled: false`` classifies the flat run's bytes
    # against the same partition — the WAN-savings baseline.
    from dedloc_tpu.averaging.topology import plan_from_groups

    topo_spec = spec.get("topology") or None
    plan = None
    hier_enabled = False
    if topo_spec:
        labels = [p.label for p in participants]
        if topo_spec.get("cliques"):
            groups = [list(g) for g in topo_spec["cliques"]]
        else:
            size = max(1, int(topo_spec.get("clique_size", 16)))
            groups = [labels[i:i + size] for i in range(0, len(labels), size)]
        plan = plan_from_groups(groups, reason="simulator spec")
        hier_enabled = (
            bool(topo_spec.get("enabled", True))
            and plan.mode == "hierarchical"
        )
    peer_by_label = {p.label: p for p in participants}

    # live control surface (see docstring): one_round re-reads this dict,
    # so the closed-loop controller can swap the plan / retune chunk_bytes
    # between rounds exactly like runtime peers adopting a new plan record
    live = control if control is not None else {}
    live.setdefault("plan", plan)
    live.setdefault(
        "enabled",
        bool(topo_spec.get("enabled", True)) if topo_spec else True,
    )
    live.setdefault("chunk_bytes", chunk_bytes)
    live.setdefault("overlap", overlap)

    # scripted mid-run faults (the watchdog scenario's levers): applied at
    # the START of their round, so detection-latency assertions can count
    # folds from a known onset
    faults = [dict(f) for f in (spec.get("faults") or [])]
    compute_scale: Dict[str, float] = {}

    def _scaled_compute(peer) -> float:
        return _compute_for(spec, peer) * compute_scale.get(
            peer.label, 1.0
        )

    async def apply_faults(r: int) -> None:
        base = swarm.network.default_link
        for f in faults:
            if int(f.get("at_round", -1)) != r:
                continue
            kind = str(f.get("kind", ""))
            if kind == "link":
                swarm.network.set_link(
                    str(f["src"]), str(f["dst"]),
                    LinkSpec(
                        latency_s=float(
                            f.get("latency_s", base.latency_s)
                        ),
                        bandwidth_bps=float(
                            f.get("bandwidth_bps", base.bandwidth_bps)
                        ),
                        loss=float(f.get("loss", base.loss)),
                        jitter_s=float(f.get("jitter_s", base.jitter_s)),
                    ),
                )
                if f.get("reset_connections"):
                    # route-flap flavor: the latency change also kills the
                    # pooled flows on the pair, so reconnects RE-SAMPLE the
                    # link RTT — without this, connect-time RTT estimates
                    # (and the re-planner's clique detection reading them)
                    # stay blind to the change, exactly as in production
                    swarm.network.reset_links(str(f["src"]), str(f["dst"]))
            elif kind == "straggler":
                compute_scale[str(f["peer"])] = float(
                    f.get("factor", 4.0)
                )
            elif kind == "churn":
                named = set(f.get("peers") or [])
                victims = [
                    p for p in participants if p.alive and p.label in named
                ]
                if not victims and f.get("count"):
                    # deterministic: the highest-indexed alive peers die
                    victims = [p for p in participants if p.alive][
                        -int(f["count"]):
                    ]
                for victim in victims:
                    await swarm.kill(victim)
            else:
                raise ValueError(f"unknown fault kind {kind!r}")

    async def _part(_peer, _args):
        return {"ok": True}

    async def _reduced(_peer, args):
        # the reduced-chunk gather reply: a chunk-sized payload riding the
        # server's uplink back to the requester (the pipelined gather leg)
        return {"data": b"\x00" * int(args["size"])}

    async def _final(_peer, args):
        # hierarchical fan-out: a clique member pulls the round's final
        # vector from its delegate (the averager's avg.final contract)
        return {"data": b"\x00" * int(args["size"])}

    for peer in participants:
        if peer.matchmaking is None or (
            peer.matchmaking.target_group_size != group_size
        ):
            peer.attach_matchmaking(
                prefix, bandwidth=100.0, target_group_size=group_size,
                averaging_expiration=window,
            )
        peer.node.server.register("avg.part", _part)
        peer.node.server.register("avg.get_reduced", _reduced)
        peer.node.server.register("avg.final", _final)
        # endpoint self-identification, same as production logs: lets any
        # consumer (twin fitter, --topology) resolve link dst -> label
        peer.telemetry.event(
            "peer.endpoint", endpoint=endpoint_key(peer.endpoint)
        )
        # the run's CONFIG, recorded like a production role logs its
        # flags: a twin fitted from these logs reads the workload shape
        # exactly instead of inferring it (every peer carries a copy so
        # any log subset suffices)
        peer.telemetry.event(
            "run.config", window_s=window, group_size=group_size,
            span_bytes=span_bytes, chunk_bytes=chunk_bytes,
            boundaries=boundaries,
            samples_per_boundary=samples_per_boundary, overlap=overlap,
            # the wire payloads above are raw bytes: a twin fitted from
            # this run knows its compression baseline instead of assuming
            compression=str(spec.get("compression", "none")),
        )

    loop = asyncio.get_event_loop()
    link_acc: Dict[Any, Dict[str, float]] = {}  # (src, dst_host) -> sums
    member_walls: List[float] = []  # every member's wall, every round
    round_walls: List[float] = []  # per-round slowest member (the ledger)
    per_peer_walls: Dict[str, List[float]] = {}
    ledger = {"hidden": 0.0, "exposed": 0.0}
    groups_formed = 0
    formed_sizes: List[int] = []  # every formed group's size (unique nonce)
    exchange_failures = 0
    round_modes: List[str] = []  # per-round topology mode actually run

    async def one_link(peer, endpoint, round_id) -> None:
        """One directed link's chunked scatter + pipelined gather — the
        flat member exchange and both hierarchical legs all ride this."""
        tele = peer.telemetry
        # chunk geometry re-read per link so a mid-run retune (the
        # closed-loop controller's chunk_bytes actuation) takes effect on
        # the next round, like the averager re-reading self.chunk_size
        cb = max(1024, int(live["chunk_bytes"]))
        acc = {"sent_bytes": 0.0, "recv_bytes": 0.0, "chunks_sent": 0.0,
               "chunks_recv": 0.0, "send_s": 0.0, "wait_s": 0.0,
               "max_chunk_s": 0.0}
        gathers = []

        async def gather_chunk(c: int, size: int) -> None:
            g0 = loop.time()
            reply = await peer.node.client.call(
                endpoint, "avg.get_reduced",
                {"round_id": round_id, "chunk": c, "size": size},
                timeout=rpc_timeout,
            )
            dt = loop.time() - g0
            acc["recv_bytes"] += len(reply["data"])
            acc["chunks_recv"] += 1
            acc["wait_s"] += dt
            acc["max_chunk_s"] = max(acc["max_chunk_s"], dt)

        try:
            for c, off in enumerate(range(0, span_bytes, cb)):
                size = min(cb, span_bytes - off)
                s0 = loop.time()
                await peer.node.client.call(
                    endpoint, "avg.part",
                    {"round_id": round_id, "sender": peer.label,
                     "chunk": c, "data": b"\x00" * size},
                    timeout=rpc_timeout,
                )
                dt = max(loop.time() - s0, 1e-9)
                # the persistent estimator eats the scatter timing, the
                # same seam production allreduce feeds
                tele.links().observe_transfer(endpoint, size, dt)
                acc["sent_bytes"] += size
                acc["chunks_sent"] += 1
                acc["send_s"] += dt
                acc["max_chunk_s"] = max(acc["max_chunk_s"], dt)
                # the reduced chunk streams back while later chunks are
                # still being scattered — the pipelined gather
                gathers.append(
                    asyncio.ensure_future(gather_chunk(c, size))
                )
            await asyncio.gather(*gathers)
        finally:
            # a scatter failure leaves gather tasks in flight: cancel
            # and DRAIN them, or their connection-reset exceptions land
            # as "never retrieved" warnings on the loop
            for g in gathers:
                g.cancel()
            if gathers:
                await asyncio.gather(*gathers, return_exceptions=True)
            key = (peer.label, str(endpoint[0]))
            swarm_acc = link_acc.setdefault(
                key, {"bytes": 0.0, "send_s": 0.0}
            )
            swarm_acc["bytes"] += acc["sent_bytes"]
            swarm_acc["send_s"] += acc["send_s"]
            tele.event(
                "allreduce.link", round_id=round_id,
                dst=endpoint_key(endpoint),
                sent_bytes=int(acc["sent_bytes"]),
                recv_bytes=int(acc["recv_bytes"]),
                chunks_sent=int(acc["chunks_sent"]),
                chunks_recv=int(acc["chunks_recv"]),
                send_s=round(acc["send_s"], 6),
                wait_s=round(acc["wait_s"], 6),
                max_chunk_s=round(acc["max_chunk_s"], 6),
            )

    def _record_wall(peer, wall: float) -> None:
        member_walls.append(wall)
        per_peer_walls.setdefault(peer.label, []).append(wall)
        # the member's wire wall IS its avg_wire step phase: the snapshot
        # then carries step.phase.avg_wire.mean/count next to fwd_bwd, so
        # a health fold over sim peers attributes wire-bound vs
        # compute-bound exactly like a production flight-recorder peer
        peer.telemetry.histogram("step.phase.avg_wire").observe(wall)

    async def member_exchange(peer, others, round_id) -> Optional[float]:
        """One member's wire work for one flat round. Returns the member's
        exchange wall in virtual seconds, or None when a link failed."""
        nonlocal exchange_failures
        tele = peer.telemetry
        # walls on the peer telemetry's own clock (virtual under the sim
        # engine) — the report's round walls and the dumped avg.round
        # spans a fitter reads must agree
        t0 = tele.clock()
        with tele.span(
            "avg.round", trace_seed=round_id, round_id=round_id,
            group_size=len(others) + 1,
        ) as ctx:
            results = await asyncio.gather(
                *(one_link(peer, ep, round_id) for _label, ep in others),
                return_exceptions=True,
            )
            failures = [r for r in results if isinstance(r, Exception)]
            ctx["ok"] = not failures
            if failures:
                ctx["error"] = type(failures[0]).__name__
                exchange_failures += len(failures)
                return None
        wall = tele.clock() - t0
        _record_wall(peer, wall)
        return wall

    async def hier_exchange(peer, asn, cg, wg, clique_done,
                            round_id) -> Optional[float]:
        """One peer's TWO-LEVEL wire work: the clique leg over (cheap)
        local links, then either the WAN leg among delegates (delegate
        role) or the fan-out pull from the delegate (member role — waits
        for its clique's WAN leg to land first, the real serialization).
        Emits the same avg.round / allreduce.link telemetry schema as the
        flat exchange, plus avg.topology.round, so --topology and the twin
        fitter consume the dump unchanged."""
        nonlocal exchange_failures
        tele = peer.telemetry
        clique = asn.clique
        is_delegate = peer.label == clique.delegate
        my_ep = tuple(peer.endpoint)
        done = clique_done.setdefault(clique.key(), asyncio.Event())
        t0 = tele.clock()
        with tele.span(
            "avg.round", trace_seed=round_id, round_id=round_id,
            group_size=len(cg.members) if cg is not None else 1,
        ) as ctx:
            try:
                if cg is not None and len(cg.members) > 1:
                    others = [
                        tuple(m.endpoint) for m in cg.members
                        if m.endpoint is not None
                        and tuple(m.endpoint) != my_ep
                    ]
                    await asyncio.gather(
                        *(one_link(peer, ep, round_id) for ep in others)
                    )
                if is_delegate:
                    if wg is not None and len(wg.members) > 1:
                        others = [
                            tuple(m.endpoint) for m in wg.members
                            if m.endpoint is not None
                            and tuple(m.endpoint) != my_ep
                        ]
                        await asyncio.gather(
                            *(one_link(peer, ep, round_id) for ep in others)
                        )
                    done.set()
                else:
                    # the fan-out is data-dependent on the WAN leg: wait
                    # for the delegate to land, then pull the final vector
                    await asyncio.wait_for(done.wait(), timeout=rpc_timeout)
                    delegate_peer = peer_by_label.get(clique.delegate)
                    if delegate_peer is None or not delegate_peer.alive:
                        raise ConnectionResetError("delegate dead")
                    g0 = loop.time()
                    reply = await peer.node.client.call(
                        tuple(delegate_peer.endpoint), "avg.final",
                        {"round_id": round_id, "size": span_bytes},
                        timeout=rpc_timeout,
                    )
                    # the fan-out payload rides the delegate->member link
                    acc = link_acc.setdefault(
                        (clique.delegate, str(peer.host)),
                        {"bytes": 0.0, "send_s": 0.0},
                    )
                    acc["bytes"] += len(reply["data"])
                    acc["send_s"] += max(loop.time() - g0, 1e-9)
                ctx["ok"] = True
                tele.event(
                    "avg.topology.round", round_id=round_id,
                    role="delegate" if is_delegate else "member",
                    clique_size=len(cg.members) if cg is not None else 1,
                    wan_size=len(wg.members) if wg is not None else 0,
                    ok=True,
                )
            except Exception as e:  # noqa: BLE001 — counted, round goes on
                ctx["ok"] = False
                ctx["error"] = type(e).__name__
                exchange_failures += 1
                if is_delegate:
                    done.set()  # a dead WAN leg must not park the clique
                return None
        wall = tele.clock() - t0
        _record_wall(peer, wall)
        return wall

    # first/last boundary stamps: the samples/sec window. Defined over the
    # boundary RECORDS (not the phase's whole duration) so a fitter reading
    # only the dumped step.record events computes the identical rate.
    stamps = {"first": None, "last": None, "samples": 0.0}

    async def accumulate(peer, r: int) -> None:
        tele = peer.telemetry
        compute = _scaled_compute(peer)
        for b in range(boundaries):
            await asyncio.sleep(compute)
            tele.histogram("step.phase.fwd_bwd").observe(compute)
            tele.event(
                "step.record", step=r * boundaries + b,
                dur_s=round(compute, 6), samples=samples_per_boundary,
                phases={"fwd_bwd": round(compute, 6)}, untimed_s=0.0,
            )
            now = get_dht_time()
            if stamps["first"] is None:
                stamps["first"] = now
            stamps["last"] = now
            stamps["samples"] += samples_per_boundary

    async def one_round(r: int) -> None:
        round_id = f"avground-{r:04d}"
        await apply_faults(r)
        alive = [p for p in participants if p.alive]
        # the round's topology comes from the LIVE control dict: the
        # closed-loop controller may have re-planned since last round
        plan_r = live.get("plan")
        mode_r = "flat"
        if plan_r is not None and live.get("enabled", True):
            if plan_r.mode in ("hierarchical", "gossip"):
                mode_r = plan_r.mode
        round_modes.append(mode_r)
        # overlap is an actuation knob (ACTUATION_KEYS): a retune may flip
        # it mid-run, so the round reads the live value, not the spec's
        ov_r = bool(live.get("overlap", overlap))
        acc_task = asyncio.gather(*(accumulate(p, r) for p in alive))
        if not ov_r:
            # synchronous boundary: accumulate, THEN average on the
            # critical path
            await acc_task

        exchanges = []
        seen_nonces = set()

        def _count_group(group) -> None:
            nonlocal groups_formed
            if group is not None and group.nonce not in seen_nonces:
                seen_nonces.add(group.nonce)
                formed_sizes.append(len(group.members))
                if len(group.members) >= 2:
                    groups_formed += 1

        if mode_r == "hierarchical":
            # two-level round: clique-scoped groups assemble concurrently
            # with (and invisible to) the delegates' WAN group, so 200
            # concurrent joiners contend inside bounded cliques instead of
            # one flat all-pairs melee. Scopes are epoch-qualified via the
            # plan (TopologyPlan.clique_scope/wan_scope), mirroring the
            # runtime averager's mixed-version rollout isolation.
            alive_labels = {p.label for p in alive}
            n_cliques = len(plan_r.cliques)
            clique_done: Dict[str, asyncio.Event] = {}

            async def form_hier(peer):
                asn = plan_r.assignment(peer.label)
                clique = asn.clique
                cg = wg = None
                local = sum(
                    1 for label in clique.members if label in alive_labels
                )
                try:
                    # both rosters are known from the PLAN (not from each
                    # other), so a delegate rendezvouses in its clique
                    # scope and the WAN scope concurrently — the leader
                    # handshake latency is paid once, not twice
                    joins = []
                    if local > 1:
                        joins.append(peer.matchmaking.form_group(
                            round_id, expected_size=local,
                            scope=plan_r.clique_scope(clique),
                        ))
                    if peer.label == clique.delegate:
                        joins.append(peer.matchmaking.form_group(
                            round_id, expected_size=n_cliques,
                            scope=plan_r.wan_scope(),
                        ))
                    groups = await asyncio.gather(*joins)
                    if local > 1:
                        cg = groups[0]
                    if peer.label == clique.delegate:
                        wg = groups[-1]
                except Exception:  # noqa: BLE001 — skipped this round
                    return peer, asn, None, None, True
                return peer, asn, cg, wg, False

            formed = await asyncio.gather(*(form_hier(p) for p in alive))
            for peer, asn, cg, wg, failed in formed:
                _count_group(cg)
                _count_group(wg)
                if failed:
                    continue
                exchanges.append(
                    hier_exchange(peer, asn, cg, wg, clique_done, round_id)
                )
        elif mode_r == "gossip":
            # gossip round: every peer averages inside its deterministic
            # neighbor group (TopologyPlan.gossip_groups — derived from
            # the shared round id, no coordination message), under the
            # group's own matchmaking scope. A group whose partner died is
            # skipped — that locality is gossip's whole point: one flaky
            # peer costs its pair a round, never the swarm's round.
            alive_labels = {p.label for p in alive}

            async def form_gossip(peer, expected, scope):
                try:
                    return peer, await peer.matchmaking.form_group(
                        round_id, expected_size=expected, scope=scope,
                    )
                except Exception:  # noqa: BLE001 — skipped this round
                    return peer, None

            joins = []
            for members in plan_r.gossip_groups(round_id):
                present = [m for m in members if m in alive_labels]
                if len(present) < 2:
                    continue
                scope = plan_r.gossip_scope(members)
                joins.extend(
                    form_gossip(peer_by_label[m], len(present), scope)
                    for m in present
                )
            formed = await asyncio.gather(*joins)
            for peer, group in formed:
                if group is None:
                    continue
                _count_group(group)
                if len(group.members) < 2 or peer.endpoint is None:
                    continue
                my_ep = tuple(peer.endpoint)
                others = [
                    (m.peer_id, tuple(m.endpoint)) for m in group.members
                    if m.endpoint is not None and tuple(m.endpoint) != my_ep
                ]
                if others:
                    exchanges.append(
                        member_exchange(peer, others, round_id)
                    )
        else:
            async def form(peer):
                try:
                    return peer, await peer.matchmaking.form_group(round_id)
                except Exception:  # noqa: BLE001 — counted via group=None
                    return peer, None

            formed = await asyncio.gather(*(form(p) for p in alive))
            for peer, group in formed:
                if group is None:
                    continue
                _count_group(group)
                if len(group.members) < 2 or peer.endpoint is None:
                    continue
                my_ep = tuple(peer.endpoint)
                others = [
                    (m.peer_id, tuple(m.endpoint)) for m in group.members
                    if m.endpoint is not None and tuple(m.endpoint) != my_ep
                ]
                if not others:
                    continue
                exchanges.append(member_exchange(peer, others, round_id))
        walls = [w for w in await asyncio.gather(*exchanges)
                 if w is not None]
        if ov_r:
            await acc_task
        if walls:
            round_wall = max(walls)
            round_walls.append(round_wall)
            accum_wall = max(
                _scaled_compute(p) * boundaries for p in alive
            )
            hidden = min(round_wall, accum_wall) if ov_r else 0.0
            exposed = round_wall - hidden
            ledger["hidden"] += hidden
            ledger["exposed"] += exposed
            alive[0].telemetry.event(
                "opt.overlap_ledger", round_id=round_id,
                mode="overlap" if ov_r else "sync",
                hidden_s=round(hidden, 6), exposed_s=round(exposed, 6),
                efficiency=round(hidden / max(round_wall, 1e-9), 4),
            )
        if on_round is not None:
            # the coordinator-fold hook (watchdog scenario): runs while
            # the round's telemetry is fresh, before the window idles
            await on_round(r)
        # let leader-entry expirations clear so rounds stay disjoint
        await asyncio.sleep(window + 1.0)

    t_start = get_dht_time()
    for r in range(rounds):
        await one_round(r)
    report: Dict[str, Any] = {
        "rounds": rounds,
        "group_size": group_size,
        "span_bytes": span_bytes,
        "chunk_bytes": chunk_bytes,
        "boundaries": boundaries,
        "samples_per_boundary": samples_per_boundary,
        "overlap": overlap,
        "groups_formed": groups_formed,
        "exchange_failures": exchange_failures,
        # per-round topology mode actually run (the closed-loop scenario's
        # re-plan timeline evidence; constant for plain workloads)
        "round_modes": round_modes,
        # every formed group's size (unique nonce, singletons INCLUDED —
        # the flat-collapse signal is exactly the singleton flood)
        "groups_total": len(formed_sizes),
        "singleton_groups": sum(1 for s in formed_sizes if s == 1),
        "group_size_median": (
            percentile([float(s) for s in formed_sizes], 0.50)
            if formed_sizes else 0.0
        ),
    }
    duration = max(get_dht_time() - t_start, 1e-9)
    report["duration_s"] = round(duration, 3)
    # percentiles over every MEMBER's wall of every round (not per-round
    # maxima): ~group_size more samples, so the estimate does not swing on
    # which group happened to draw the slowest peer in a short run — and
    # the twin fitter computes the identical statistic from avg.round spans
    report["round_wall_p50_s"] = round(percentile(member_walls, 0.50), 4)
    report["round_wall_p95_s"] = round(percentile(member_walls, 0.95), 4)
    report["per_peer_round_wall_s"] = {
        label: round(sum(walls) / len(walls), 4)
        for label, walls in sorted(per_peer_walls.items())
    }
    durs = _span_durations(swarm, "mm.form_group")
    report["formation_p50_s"] = round(percentile(durs, 0.50), 4)
    report["formation_p95_s"] = round(percentile(durs, 0.95), 4)
    # swarm-wide samples/sec over the first->last boundary-record window —
    # the SAME definition the twin fitter computes from dumped step.record
    # events, so observed and predicted rates are like-for-like. A window
    # under 1 ms (a 1-round x 1-boundary workload whose stamps differ only
    # by engine tie-break epsilons) is below the stamp resolution: report
    # None, not a ~1e8 garbage rate a sweep would happily rank by.
    if (
        stamps["first"] is not None
        and stamps["last"] - stamps["first"] > 1e-3
    ):
        report["samples_per_sec"] = round(
            stamps["samples"] / (stamps["last"] - stamps["first"]), 3
        )
    else:
        report["samples_per_sec"] = None
    total_ledger = ledger["hidden"] + ledger["exposed"]
    report["overlap_efficiency"] = (
        round(ledger["hidden"] / total_ledger, 4) if total_ledger else None
    )
    # observed per-link wire rate, worst first — the ranking a fitted twin
    # must reproduce (src/dst are host labels)
    worst = sorted(
        (
            (src, dst, acc["bytes"] / max(acc["send_s"], 1e-9))
            for (src, dst), acc in link_acc.items()
            if acc["bytes"] > 0
        ),
        key=lambda item: item[2],
    )
    report["worst_links"] = [
        [src, dst, round(bps, 1)] for src, dst, bps in worst[:10]
    ]
    if plan is not None:
        # WAN-vs-local byte split against the plan's partition — computed
        # for the hierarchical run AND (enabled: false) the flat baseline
        # of the same spec, so the savings ratio compares like for like
        wan = local = 0.0
        wan_by_src: Dict[str, float] = {}
        for (src, dst), acc in link_acc.items():
            b = float(acc["bytes"])
            if plan.same_clique(str(src), str(dst)):
                local += b
            else:
                wan += b
                wan_by_src[str(src)] = wan_by_src.get(str(src), 0.0) + b
        delegates = set(plan.delegates)
        nondelegates = [
            p.label for p in participants if p.label not in delegates
        ]
        report["topology"] = {
            "mode": "hierarchical" if hier_enabled else "flat",
            "cliques": len(plan.cliques),
            "delegates": sorted(delegates),
            "wan_bytes_total": int(wan),
            "local_bytes_total": int(local),
            "wan_bytes_per_nondelegate": round(
                sum(wan_by_src.get(label, 0.0) for label in nondelegates)
                / max(1, len(nondelegates)),
                1,
            ),
            "wan_bytes_per_delegate": round(
                sum(wan_by_src.get(label, 0.0) for label in delegates)
                / max(1, len(delegates)),
                1,
            ),
        }
    if int(spec.get("restore_bytes", 0)) > 0:
        report["restore"] = await _workload_restore(swarm, spec, prefix)
    return report


async def _workload_restore(swarm: SimSwarm, spec: Dict[str, Any],
                            prefix: str) -> Dict[str, Any]:
    """One sharded catalog restore over the workload's links: providers
    serve a synthetic checkpoint of ``restore_bytes``, a non-provider
    restores it with ``fetch_parallelism`` — the fetch-sizing leg of a
    twin replay (and the source of ``ckpt.provider_goodput`` telemetry)."""
    from dedloc_tpu.checkpointing.catalog import parse_announcements
    from dedloc_tpu.checkpointing.fetcher import sharded_restore
    from dedloc_tpu.checkpointing.catalog import catalog_key

    restore_bytes = int(spec.get("restore_bytes", 0))
    total_size = max(256, restore_bytes // 4)  # fp32 elements
    shard_size = max(64, total_size // 8)
    alive = swarm.alive_peers()
    n_providers = max(1, min(int(spec.get("restore_providers", 4)),
                             len(alive) - 1))
    providers = alive[:n_providers]
    loop = asyncio.get_event_loop()
    for peer in providers:
        peer.serve_checkpoint(
            step=1, total_size=total_size, shard_size=shard_size
        )
        await peer.announce_checkpoint(f"{prefix}-restore")
    reader = alive[n_providers]
    entry = await reader.node.get(
        catalog_key(f"{prefix}-restore").encode(), latest=True
    )
    items = (
        [(sk, v.value) for sk, v in entry.value.items()]
        if entry is not None and hasattr(entry.value, "items")
        else []
    )
    announcements = parse_announcements(items)
    t0 = loop.time()
    ok = False
    stats: Dict[str, Any] = {}
    # the ckpt.restore span production's load_state_from_peers opens
    # around its sharded path — the fitter reads restore shape from it
    with reader.telemetry.span(
        "ckpt.restore", mode="sharded", bytes=total_size * 4
    ) as ctx:
        try:
            await sharded_restore(
                reader.node.client, announcements,
                parallelism=int(spec.get("fetch_parallelism", 4)),
                telemetry_registry=reader.telemetry, stats=stats,
            )
            ok = True
        except Exception as e:  # noqa: BLE001 — reported, not raised
            logger.warning(f"workload restore failed: {e!r}")
        ctx["ok"] = ok
        ctx["providers"] = int(stats.get("providers", 0))
    return {
        "ok": ok,
        "restore_s": round(loop.time() - t0, 4),
        "bytes": total_size * 4,
        "providers": n_providers,
        "providers_used": int(stats.get("providers", 0)),
        "fetch_parallelism": int(spec.get("fetch_parallelism", 4)),
    }


async def phase_averaging(run: ScenarioRun) -> None:
    run.report["averaging"] = await run_averaging_workload(
        run.swarm, run.spec
    )


# ----------------------------------------------------- watchdog scenario
#
# The live-watchdog proving ground: the averaging workload runs with
# scripted mid-run faults while a simulated coordinator FOLDS swarm-health
# records after every round (the production fold shape, built by the same
# telemetry/health.build_swarm_health) and streams them through a
# SwarmWatch inline — exactly the coordinator's live loop, in virtual
# time. The folds dump to a coordinator-style JSONL so a post-hoc replay
# (tools/swarm_watch.py) must reproduce the identical incident timeline.


def fold_swarm_health(swarm: SimSwarm, step: int,
                      state: Dict[str, Any]) -> Dict[str, Any]:
    """One coordinator fold over the sim swarm: per-peer LocalMetrics-shaped
    records built from each alive peer's telemetry snapshot (cumulative
    counters + link table, exactly what the signed metrics bus carries),
    plus the recent ``avg.round`` summaries a production bus cannot carry
    (flat floats only) but the in-process fold can — the watchdog's
    representative-trace attribution reads them. ``state`` is the fold's
    mutable memory ({} on the first call)."""
    now = get_dht_time()
    last_t = state.get("t")
    dt = (now - last_t) if last_t is not None else None
    records = []
    rounds: List[Dict[str, Any]] = []
    for peer in swarm.alive_peers():
        samples = 0.0
        for r in peer.telemetry.events:
            if last_t is not None and float(r.get("t", 0.0)) <= last_t:
                continue
            name = r.get("event")
            if name == "step.record":
                samples += float(r.get("samples", 0.0))
            elif name == "avg.round":
                rounds.append({
                    "round_id": r.get("round_id"), "peer": peer.label,
                    "dur_s": r.get("dur_s"), "ok": r.get("ok"),
                    "group_size": r.get("group_size"),
                    "trace": r.get("trace"),
                })
        records.append(SimpleNamespace(
            peer=peer.label,
            step=int(step),
            samples_per_second=(
                round(samples / dt, 3) if dt and dt > 0 else 0.0
            ),
            step_time_ms=None,
            telemetry=peer.telemetry.snapshot(),
            endpoint=endpoint_key(peer.endpoint),
        ))
    from dedloc_tpu.telemetry.health import build_swarm_health

    health = build_swarm_health(
        records, rounds=rounds, prev=state.get("health"), dt_s=dt
    )
    state["t"] = now
    state["health"] = health
    return {"step": int(step), "time": now, "swarm_health": health}


def _watch_config(spec: Dict[str, Any]):
    from dedloc_tpu.telemetry.watch import WatchConfig

    cfg = WatchConfig()
    for key, value in (spec.get("watch") or {}).items():
        if not hasattr(cfg, key):
            raise ValueError(f"unknown watch config key {key!r}")
        setattr(cfg, key, type(getattr(cfg, key))(value))
    return cfg


async def _scenario_watchdog(run: ScenarioRun) -> None:
    from dedloc_tpu.telemetry.watch import SwarmWatch

    await phase_spawn(run)
    run.report["link_overrides"] = apply_link_overrides(
        run.network,
        [p.host for p in run.swarm.peers],
        run.spec.get("links"),
    )
    watch = SwarmWatch(_watch_config(run.spec))
    fold_state: Dict[str, Any] = {}
    folds: List[Dict[str, Any]] = []
    transitions: List[Dict[str, Any]] = []

    async def on_round(r: int) -> None:
        row = fold_swarm_health(run.swarm, r, fold_state)
        folds.append(row)
        if row["swarm_health"] is None:
            # a scripted churn wave can wipe out EVERY peer: the fold is
            # kept as evidence in the dump, but there is nothing to
            # observe — watch_rows skips null health rows the same way,
            # so live and replay stay identical
            return
        for tr in watch.observe_health(
            row["swarm_health"], t=row["time"], step=r
        ):
            transitions.append({
                "fold": watch.fold,
                "transition": tr["transition"],
                "incident": tr["incident"]["id"],
                "kind": tr["incident"]["kind"],
                "subject": tr["incident"]["subject"],
            })

    run.report["averaging"] = await run_averaging_workload(
        run.swarm, run.spec, on_round=on_round
    )
    run.report["watch"] = watch.summary()
    run.report["transitions"] = transitions
    run.report["health_folds"] = folds


# --------------------------------------------------- closed-loop scenario
#
# The ISSUE 16 acceptance scenario: detect -> re-plan -> retune -> recover,
# with zero operator input, entirely in virtual time. The averaging
# workload runs with scripted mid-run faults while a coordinator-shaped
# controller runs after every round: health fold -> SwarmWatch -> the REAL
# ``_Replanner`` (roles/coordinator.py) deriving epoch-versioned topology
# plans from the fold's live link table -> the REAL ``ActuationGuard``
# (telemetry/watch.py) applying scripted retune recommendations under the
# guard rail and rolling harmful ones back. The workload re-reads its live
# control dict each round, so an adopted plan (or chunk retune) reshapes
# the NEXT round with no barrier — the runtime adoption contract. The DHT
# wire machinery itself (publish/fetch backoff, fault ladder, mixed-epoch
# scope isolation) is proven separately by the loopback tests in
# tests/test_closed_loop.py against real DHT nodes.


async def _scenario_closed_loop(run: ScenarioRun) -> None:
    """Spec section ``control`` (all keys optional)::

        control:
          replan: true                # run the live replanner
          replan_min_interval_s: 0.0  # epoch-bump hysteresis (virtual s)
          adopt_delay_rounds: 0       # publish -> peer-adoption lag
          settle_folds: 1             # ActuationConfig knobs...
          observe_folds: 3
          rollback_margin: 0.1
          cooldown_folds: 1
          max_actuations_per_epoch: 4
          max_change_factor: 4.0
          recommendations:            # scripted twin recommendations,
            - at_fold: 8              # attached to the newest open
              config: {chunk_size: 2048}   # incident from this fold on

    Report adds ``replans`` (the epoch timeline), ``actuations`` (the
    guard's full history incl. verdicts), ``sps_by_fold``, ``final_config``
    and ``incident_rows`` — the coordinator-style incident JSONL rows,
    dumped to ``incidents.jsonl`` for ``runlog_summary --incidents``."""
    from dedloc_tpu.averaging.topology import TopologyPlan
    from dedloc_tpu.roles.coordinator import _Replanner
    from dedloc_tpu.telemetry.watch import (
        ActuationConfig,
        ActuationGuard,
        SwarmWatch,
        rollback_effect,
    )

    await phase_spawn(run)
    run.report["link_overrides"] = apply_link_overrides(
        run.network,
        [p.host for p in run.swarm.peers],
        run.spec.get("links"),
    )
    spec = run.spec
    ctl = dict(spec.get("control") or {})

    watch = SwarmWatch(_watch_config(spec))
    fold_state: Dict[str, Any] = {}
    folds: List[Dict[str, Any]] = []
    transitions: List[Dict[str, Any]] = []
    incident_rows: List[Dict[str, Any]] = []

    def record_incident(t, step, transition, incident) -> None:
        # the coordinator's incident-JSONL row shape (_append_incident):
        # deep JSON copy, because the live dict keeps mutating
        incident_rows.append({
            "t": t, "step": step, "watch": "incident",
            "transition": transition,
            "incident": json.loads(json.dumps(incident, default=str)),
        })

    class _MemDHT:
        """In-memory plan-record store: the replanner's publish target.
        The wire itself (real DHT store/fetch, retries, fault points) is
        proven by the loopback tests; here the records are evidence."""

        def __init__(self):
            self.stored: List[Any] = []

        def store(self, key, value, expiration_time, subkey=None, **_kw):
            self.stored.append({"key": key, "subkey": subkey,
                                "value": value})
            return True

    replanner = None
    if bool(ctl.get("replan", True)):
        replanner = _Replanner(
            _MemDHT(), str(spec.get("prefix", "twinexp")),
            SimpleNamespace(replan_min_interval_s=float(
                ctl.get("replan_min_interval_s", 0.0)
            )),
        )
    guard = ActuationGuard(ActuationConfig(
        max_change_factor=float(ctl.get("max_change_factor", 4.0)),
        settle_folds=int(ctl.get("settle_folds", 1)),
        observe_folds=int(ctl.get("observe_folds", 3)),
        rollback_margin=float(ctl.get("rollback_margin", 0.1)),
        cooldown_folds=int(ctl.get("cooldown_folds", 1)),
        max_actuations_per_epoch=int(
            ctl.get("max_actuations_per_epoch", 4)
        ),
    ))
    # the actuated config in averager terms: chunk_size is ELEMENTS (fp32,
    # 4 bytes each) exactly like --averager.chunk_size, mapped onto the
    # workload's chunk_bytes on apply
    current_config: Dict[str, Any] = {
        "chunk_size": max(1024, int(spec.get("chunk_bytes", 24576))) // 4,
        "overlap": bool(spec.get("overlap", False)),
    }
    scripted = [dict(rec) for rec in (ctl.get("recommendations") or [])]
    adopt_delay = int(ctl.get("adopt_delay_rounds", 0))
    pending_plans: List[Any] = []  # (adopt_at_round, label-keyed plan)
    control: Dict[str, Any] = {}
    label_by_endpoint = {
        endpoint_key(p.endpoint): p.label for p in run.swarm.peers
    }
    replans: List[Dict[str, Any]] = []
    actuation_events: List[Dict[str, Any]] = []
    sps_by_fold: List[Optional[float]] = []

    def _label_plan(plan: TopologyPlan) -> TopologyPlan:
        """The replanner's plans key members by ENDPOINT (what runtime
        matchmaking advertises); the sim workload matches by label —
        re-key through the fold's own peers map."""
        lp = TopologyPlan.from_dict(plan.to_dict())
        lp.peers = sorted(
            label_by_endpoint.get(p, p) for p in lp.peers
        )
        for c in lp.cliques:
            c.members = sorted(
                label_by_endpoint.get(m, m) for m in c.members
            )
            c.delegate = label_by_endpoint.get(c.delegate, c.delegate)
        return lp

    def _apply_config(delta: Dict[str, Any]) -> None:
        current_config.update(delta)
        control["chunk_bytes"] = max(
            1024, int(current_config["chunk_size"]) * 4
        )
        control["overlap"] = bool(current_config.get("overlap", False))

    async def on_round(r: int) -> None:
        row = fold_swarm_health(run.swarm, r, fold_state)
        folds.append(row)
        health = row["swarm_health"]
        if health is None:
            return
        t = row["time"]
        # this fold's swarm throughput — the same sum the watch derives,
        # and what the guard judges an in-flight actuation by
        reported = [
            float(p["samples_per_second"])
            for p in health.get("peers", [])
            if isinstance(p, dict)
            and p.get("samples_per_second") is not None
        ]
        sps = sum(reported) if reported else None
        sps_by_fold.append(sps)
        for tr in watch.observe_health(
            health, t=t, step=r, samples_per_sec=sps
        ):
            transitions.append({
                "fold": watch.fold,
                "transition": tr["transition"],
                "incident": tr["incident"]["id"],
                "kind": tr["incident"]["kind"],
                "subject": tr["incident"]["subject"],
            })
            record_incident(t, r, tr["transition"], tr["incident"])

        # ---- live re-planning off the fold (the production code path)
        if replanner is not None:
            published = replanner.fold(health, t)
            if published is not None:
                replans.append({
                    "fold": watch.fold, "round": r,
                    "epoch": int(published.epoch),
                    "mode": published.mode,
                    "reason": published.reason,
                    "cliques": [sorted(c.members)
                                for c in published.cliques],
                })
                pending_plans.append(
                    (r + 1 + adopt_delay, _label_plan(published))
                )
        epoch = replanner.epoch if replanner is not None else 0

        # ---- judge the in-flight actuation against this fold first
        verdict = guard.observe(sps, fold=watch.fold)
        if verdict is not None:
            incident = next(
                (i for i in watch.incidents
                 if i["id"] == verdict.get("incident")), None,
            )
            if verdict["verdict"] == "rollback":
                _apply_config(verdict["revert"])
                if incident is not None:
                    rollback_effect(incident, verdict)
                    record_incident(t, r, "rollback", incident)
            elif incident is not None:
                for effect in incident.get("effects", []):
                    if (
                        effect.get("metric") == "actuation"
                        and effect.get("applied") == verdict["applied"]
                    ):
                        effect["verdict"] = "kept"
                record_incident(t, r, "actuation", incident)
            actuation_events.append({
                "fold": watch.fold, "round": r,
                "verdict": verdict["verdict"],
                "applied": dict(verdict["applied"]),
            })

        # ---- scripted recommendations: the twin fit, pre-computed by the
        # spec (twin_recommendation itself is proven by twin_replay tests)
        open_inc = watch.open_incidents()
        for rec in scripted:
            if rec.get("_attached") or watch.fold < int(
                rec.get("at_fold", 0)
            ):
                continue
            if not open_inc:
                continue
            target = open_inc[-1]
            target["recommendation"] = {
                "config": dict(rec.get("config") or {}),
                "predicted_samples_per_sec": rec.get(
                    "predicted_samples_per_sec"
                ),
            }
            rec["_attached"] = True
            record_incident(t, r, "recommendation", target)

        # ---- apply at most one eligible recommendation under the rail
        for incident in open_inc:
            recommendation = incident.get("recommendation")
            if not recommendation or incident.get("actuated"):
                continue
            result = guard.consider(
                recommendation, current_config,
                fold=watch.fold, epoch=epoch,
            )
            if "refused" in result:
                incident["actuation_refused"] = result["refused"]
                continue
            incident.pop("actuation_refused", None)
            _apply_config(result["apply"])
            incident["actuated"] = True
            guard.actuate(
                incident, result["apply"], result["revert"],
                fold=watch.fold, baseline_samples_per_sec=sps,
                epoch=epoch, clamped=tuple(result["clamped"]),
            )
            actuation_events.append({
                "fold": watch.fold, "round": r,
                "verdict": "applied", "applied": dict(result["apply"]),
            })
            record_incident(t, r, "actuation", incident)
            break  # one actuation per fold; the guard serializes the rest

        # ---- adoption: plans whose publish->fetch lag expired reshape
        # the NEXT round (peers poll between rounds; no barrier)
        while pending_plans and pending_plans[0][0] <= r + 1:
            _at, label_plan = pending_plans.pop(0)
            control["plan"] = label_plan
            control["enabled"] = True

    run.report["averaging"] = await run_averaging_workload(
        run.swarm, spec, on_round=on_round, control=control
    )
    run.report["watch"] = watch.summary()
    run.report["transitions"] = transitions
    run.report["health_folds"] = folds
    run.report["replans"] = replans
    run.report["plan_epoch"] = (
        replanner.epoch if replanner is not None else 0
    )
    run.report["actuations"] = guard.history
    run.report["actuation_events"] = actuation_events
    run.report["sps_by_fold"] = sps_by_fold
    run.report["final_config"] = dict(current_config)
    run.report["incident_rows"] = incident_rows


# -------------------------------------------------------------- scenarios


async def _scenario_dht_churn(run: ScenarioRun) -> None:
    await phase_spawn(run)
    await phase_dht(run)


async def _scenario_matchmaking(run: ScenarioRun) -> None:
    await phase_spawn(run)
    await phase_matchmaking(run)


async def _scenario_catalog(run: ScenarioRun) -> None:
    await phase_spawn(run)
    await phase_catalog(run)


async def _scenario_mixed(run: ScenarioRun) -> None:
    """The acceptance scenario: DHT churn + matchmaking rounds + catalog
    announcements over ONE swarm, in that order, so each phase inherits
    the previous one's damage."""
    await phase_spawn(run)
    await phase_dht(run)
    await phase_matchmaking(run)
    await phase_catalog(run)


async def _scenario_averaging(run: ScenarioRun) -> None:
    """The digital-twin source scenario: spawn, apply the spec's per-link
    overrides (the known asymmetric network a twin must rediscover from
    telemetry alone), then run averaging rounds to a round-wall report."""
    await phase_spawn(run)
    run.report["link_overrides"] = apply_link_overrides(
        run.network,
        [p.host for p in run.swarm.peers],
        run.spec.get("links"),
    )
    await phase_averaging(run)


async def _scenario_hierarchical(run: ScenarioRun) -> None:
    """Two-level adaptive averaging, sized against its own flat baseline
    (docs/simulator.md): ONE swarm, the spec's ``topology`` partition and
    per-link overrides (the 2-clique asymmetric-WAN shape), and the
    averaging workload run TWICE — flat first (``topology.enabled: false``
    keeps the plan for WAN-byte accounting), then hierarchical. The
    ``comparison`` section is what the acceptance bounds read: WAN bytes
    per non-delegate peer, round-wall p50, and the formed-group-size
    medians the 200-joiner collapse case is judged on."""
    await phase_spawn(run)
    run.report["link_overrides"] = apply_link_overrides(
        run.network,
        [p.host for p in run.swarm.peers],
        run.spec.get("links"),
    )
    topo = dict(run.spec.get("topology") or {})
    flat_spec = {**run.spec, "topology": {**topo, "enabled": False}}
    hier_spec = {**run.spec, "topology": {**topo, "enabled": True}}
    run.report["flat"] = await run_averaging_workload(run.swarm, flat_spec)
    run.report["hierarchical"] = await run_averaging_workload(
        run.swarm, hier_spec
    )
    flat, hier = run.report["flat"], run.report["hierarchical"]

    def _ratio(a: float, b: float) -> Optional[float]:
        return round(a / b, 3) if b else None

    flat_topo = flat.get("topology") or {}
    hier_topo = hier.get("topology") or {}
    run.report["comparison"] = {
        "wan_bytes_total_ratio": _ratio(
            flat_topo.get("wan_bytes_total", 0.0),
            hier_topo.get("wan_bytes_total", 0.0),
        ),
        # the acceptance bar reads nondelegate_wan_bytes: two-level
        # reduction must at least halve what a non-delegate pays WAN-side
        # (it typically zeroes it — only delegates cross the WAN)
        "nondelegate_wan_bytes": {
            "flat": flat_topo.get("wan_bytes_per_nondelegate"),
            "hierarchical": hier_topo.get("wan_bytes_per_nondelegate"),
        },
        "round_wall_p50_ratio": _ratio(
            flat.get("round_wall_p50_s", 0.0),
            hier.get("round_wall_p50_s", 0.0),
        ),
        "group_size_median": {
            "flat": flat.get("group_size_median"),
            "hierarchical": hier.get("group_size_median"),
        },
    }


# ----------------------------------------------------- ledger scenario
#
# The contribution-ledger acceptance scenario: receipt-backed swarm
# accounting, entirely in virtual time. Peers form REAL matchmaking groups
# each round with their declared per-round weight riding the signed join
# envelope (Member.weight over the sim DHT wire), countersign receipts
# with the REAL receipt_from_group, and publish schema-checked claims as
# DHT records — one peer INFLATES its cumulative claim, one serves most
# checkpoint bytes. A coordinator-shaped fold runs the REAL fold_ledger
# after every round; the dumped ledger JSONL must replay bit-identically
# (tests/test_ledger.py), honest peers land within 5% of scripted ground
# truth, and the inflator is capped at its receipt-supported total with a
# named discrepancy.


async def _scenario_ledger(run: ScenarioRun) -> None:
    """Spec section ``ledger`` (all keys optional)::

        ledger:
          inflate_peer: peer-0001   # claims inflate_factor x its true work
          inflate_factor: 10.0
          serve_peer: peer-0002     # scripted checkpoint-serving bytes
          serve_bytes: 67108864
          slack: 1.25
    """
    from dedloc_tpu.telemetry.ledger import (
        ContributionClaim,
        fold_ledger,
        leaderboard,
        ledger_key,
        parse_claims,
        parse_receipts,
        receipt_from_group,
        receipts_key,
    )

    await phase_spawn(run)
    spec = run.spec
    lspec = dict(spec.get("ledger") or {})
    rounds = int(spec.get("avg_rounds", 6))
    window = float(spec.get("window_s", 5.0))
    samples_per_round = (
        int(spec.get("boundaries", 2)) * int(spec.get("samples_per_boundary", 16))
    )
    prefix = str(spec.get("prefix", "simexp"))
    slack = float(lspec.get("slack", 1.25))
    inflate_factor = float(lspec.get("inflate_factor", 10.0))
    participants = run.swarm.alive_peers()
    if len(participants) < 3:
        raise ValueError("ledger scenario needs >= 3 live peers")
    # one group spanning the whole swarm per round: every mate's witness
    # table then covers every round, so HONEST credit is exact and the 5%
    # acceptance bar measures only the fold, not matchmaking splits
    group_size = int(spec.get("group_size", len(participants)))
    labels = [p.label for p in participants]
    inflate_peer = str(lspec.get("inflate_peer", labels[1]))
    serve_peer = str(lspec.get("serve_peer", labels[2]))
    serve_bytes = int(lspec.get("serve_bytes", 64 * 1024 * 1024))

    for peer in participants:
        peer.attach_matchmaking(
            prefix, bandwidth=100.0, target_group_size=group_size,
            averaging_expiration=window,
        )
        # the declared per-round weight rides the signed join envelope
        peer.matchmaking.declared_weight = float(samples_per_round)

    hex_by_label = {
        p.label: p.node.node_id.to_bytes().hex() for p in participants
    }
    truth = {p.label: {"samples": 0, "rounds": 0} for p in participants}
    witnesses: Dict[str, Dict[str, Dict[str, float]]] = {
        p.label: {} for p in participants
    }
    ledger_rows: List[Dict[str, Any]] = []
    prev_fold = None
    t0 = get_dht_time()

    def _items(entry) -> list:
        return (
            [(sk, v.value) for sk, v in entry.value.items()]
            if entry is not None and hasattr(entry.value, "items")
            else []
        )

    for r in range(rounds):
        round_id = f"ledround-{r:04d}"
        alive = [p for p in participants if p.alive]

        async def one(peer):
            try:
                return peer, await peer.matchmaking.form_group(round_id)
            except Exception:  # noqa: BLE001 — skipped this round
                return peer, None

        formed = await asyncio.gather(*(one(p) for p in alive))
        for peer, group in formed:
            if group is None or len(group.members) < 2:
                continue
            # the receipt covers the envelope identities + declared
            # weights the signer verified at join — built by the SAME
            # helper the runtime averager calls at round finalization
            member_weights = [
                (m.peer_id.hex(), float(m.weight)) for m in group.members
            ]
            receipt = receipt_from_group(
                hex_by_label[peer.label], round_id, -1, "flat",
                member_weights, witnesses[peer.label],
            )
            truth[peer.label]["samples"] += samples_per_round
            truth[peer.label]["rounds"] += 1
            peer.telemetry.counter("ledger.receipts").inc()
            peer.telemetry.event(
                "ledger.receipt",
                signer=receipt.signer, round_id=receipt.round_id,
                step=receipt.step, leg=receipt.leg,
                members=list(receipt.members),
                weights=list(receipt.weights),
                witness={
                    p: {"samples": e.samples, "rounds": e.rounds}
                    for p, e in receipt.witness.items()
                },
            )
            # subkey = the peer id itself: the structural binding
            # parse_receipts enforces (a record's signer must be the
            # identity its slot speaks for, telemetry/ledger.py)
            await peer.node.store(
                receipts_key(prefix).encode(), receipt.model_dump(),
                get_dht_time() + 3600.0,
                subkey=peer.node.node_id.to_bytes(),
            )
        # cumulative claims (last-write-wins per peer, like the one signed
        # subkey slot production enforces); the inflator multiplies its
        # TRUE total — its per-round declared weights stayed honest, which
        # is exactly the attack receipts catch
        for peer in alive:
            tr = truth[peer.label]
            claimed = tr["samples"]
            if peer.label == inflate_peer:
                claimed = int(claimed * inflate_factor)
            bytes_served = (
                serve_bytes if peer.label == serve_peer
                else 1024 * (r + 1)
            )
            claim = ContributionClaim(
                peer=hex_by_label[peer.label],
                samples=int(claimed),
                rounds=int(tr["rounds"]),
                train_seconds=round(get_dht_time() - t0, 3),
                bytes_served=int(bytes_served),
                time=get_dht_time(),
            )
            peer.telemetry.counter("ledger.claims").inc()
            peer.telemetry.event(
                "ledger.claim", peer=claim.peer, samples=claim.samples,
                rounds=claim.rounds, train_seconds=claim.train_seconds,
                bytes_served=claim.bytes_served,
            )
            await peer.node.store(
                ledger_key(prefix).encode(), claim.model_dump(),
                get_dht_time() + 3600.0,
                subkey=peer.node.node_id.to_bytes(),
            )
        # coordinator-shaped fold off the live DHT view, through the SAME
        # parse + fold path roles/coordinator.py runs
        reader = alive[0]
        centry = await reader.node.get(ledger_key(prefix).encode(), latest=True)
        rentry = await reader.node.get(
            receipts_key(prefix).encode(), latest=True
        )
        folded = fold_ledger(
            prev_fold, parse_claims(_items(centry)),
            parse_receipts(_items(rentry)), slack=slack, now=get_dht_time(),
        )
        prev_fold = folded
        ledger_rows.append({"t": folded["t"], "step": r, "ledger": folded})
        # let leader-entry expirations clear so rounds stay disjoint
        await asyncio.sleep(window + 1.0)

    run.report["ledger_rows"] = ledger_rows
    run.report["ledger"] = prev_fold
    run.report["leaderboard"] = leaderboard(prev_fold) if prev_fold else []
    run.report["truth"] = {
        label: {**tr, "peer": hex_by_label[label]}
        for label, tr in truth.items()
    }
    run.report["samples_per_round"] = samples_per_round
    run.report["inflate"] = {
        "label": inflate_peer, "peer": hex_by_label.get(inflate_peer),
        "factor": inflate_factor,
    }
    run.report["serve"] = {
        "label": serve_peer, "peer": hex_by_label.get(serve_peer),
        "bytes": serve_bytes,
    }


async def _scenario_diurnal(run: ScenarioRun) -> None:
    """Planet-scale volunteer waves: a 10k-peer roster of which only each
    timezone's duty window is ever online. The whole roster starts as
    unhydrated SHELLS (no node, no telemetry, no sockets); each virtual
    hour the arriving wave is warm-hydrated in one batch and the departing
    wave is process-killed. Online volunteers heartbeat presence records
    into the DHT and read each other's — the workload that proves the
    swarm stays routable while most of its roster is asleep.

    This is the engine's scale acceptance: the wall cost must track the
    ACTIVE wave (hydrations + live traffic), not the roster size."""
    spec = run.spec
    roster_n = int(spec.get("peers", 10000))
    hours = int(spec.get("hours", 24))
    hour_s = float(spec.get("hour_s", 60.0))
    duty = int(spec.get("duty_hours", 8))
    ops = int(spec.get("ops_per_hour", 48))
    swarm, rng = run.swarm, run.rng
    shells = swarm.spawn_shells(roster_n)
    # each volunteer's home-timezone start hour, hash-derived (stable
    # across runs and independent of the shared rng stream)
    start_hour = [
        int.from_bytes(
            hashlib.sha256(
                f"{run.seed}:diurnal:{i}".encode()
            ).digest()[:2], "big"
        ) % 24
        for i in range(roster_n)
    ]

    def online_at(index: int, hour: int) -> bool:
        return (hour - start_hour[index]) % 24 < duty

    hydrations = departures = peak_online = 0
    put_attempts = puts_ok = get_attempts = get_hits = 0
    for hour in range(hours):
        h = hour % 24
        leaving = [p for p in shells if p.alive and not online_at(p.index, h)]
        for p in leaving:
            await swarm.kill(p)
        departures += len(leaving)
        arriving = [
            p for p in shells if not p.alive and online_at(p.index, h)
        ]
        await swarm.hydrate_batch(arriving)
        hydrations += len(arriving)
        online = swarm.alive_peers()
        peak_online = max(peak_online, len(online))
        if online:
            key = f"presence-{hour:04d}".encode()
            expiry = get_dht_time() + 2.0 * hour_s
            writers = rng.sample(online, min(ops, len(online)))
            stored = await asyncio.gather(*(
                w.node.store(key, w.label.encode(), expiry,
                             subkey=w.label.encode())
                for w in writers
            ))
            put_attempts += len(writers)
            puts_ok += sum(1 for s in stored if s)
            readers = rng.sample(online, min(ops, len(online)))
            entries = await asyncio.gather(*(
                r.node.get(key) for r in readers
            ))
            get_attempts += len(readers)
            get_hits += sum(1 for e in entries if e is not None)
        await asyncio.sleep(hour_s)
    run.report["diurnal"] = {
        "roster": roster_n,
        "hours": hours,
        "duty_hours": duty,
        "peak_online": peak_online,
        "hydrations": hydrations,
        "departures": departures,
        "puts": put_attempts,
        "puts_ok": puts_ok,
        "gets": get_attempts,
        "get_hits": get_hits,
        "get_success": round(get_hits / max(1, get_attempts), 3),
        "shells_never_online": sum(
            1 for p in shells if p.node is None
        ),
    }


def _zipf_weights(n: int, skew: float) -> List[float]:
    raw = [1.0 / (i + 1) ** skew for i in range(n)]
    total = sum(raw)
    return [w / total for w in raw]


def _weighted_index(rng: random.Random, weights: List[float]) -> int:
    x = rng.random()
    acc = 0.0
    for i, w in enumerate(weights):
        acc += w
        if x < acc:
            return i
    return len(weights) - 1


async def _scenario_serving(run: ScenarioRun) -> None:
    """The swarm-as-serving-fleet rehearsal (ROADMAP item 1): expert hosts
    announce signed ExpertRecords on the sim DHT wire, gateways route a
    bursty scripted request trace latency/load-aware over SimNetwork
    links, expert peers die mid-trace, and the ledger credits the serving
    work. Spec section (all keys optional)::

        scenario: serving
        peers: 1000
        experts: 16             # expert ids 0..E-1
        hosts_per_expert: 3
        gateways: 8
        requests: 400           # scripted request trace length
        burst: 8                # concurrent requests per burst
        burst_gap_s: 0.25       # virtual gap between bursts
        tokens: 16              # tokens per request
        hidden: 8               # token feature width
        skew: 1.1               # zipf exponent on expert popularity
        capacity: 512           # per-host tokens-per-window bound
        kill_hosts: 0           # expert hosts killed mid-trace
        kill_at_frac: 0.5       # kill point, fraction of the trace
        refresh_period_s: 2.0   # gateway discovery refresh
        announce_period_s: 2.0  # host record refresh (TTL = 2x this)
        deadline_s: 2.0         # per-request budget
        hedge_after_s: 0.3
        dispatch_rate: 0.0      # per-caller admission on hosts (0 = open)
        ledger_slack: 1.25
    """
    import numpy as np

    from dedloc_tpu.serving.admission import Admission
    from dedloc_tpu.serving.host import ExpertHost
    from dedloc_tpu.serving.router import ExpertRouter, RouterPolicy
    from dedloc_tpu.telemetry.ledger import (
        ContributionClaim,
        fold_ledger,
        leaderboard,
        ledger_key,
        parse_claims,
    )

    await phase_spawn(run)
    spec = run.spec
    prefix = str(spec.get("prefix", "simexp"))
    E = int(spec.get("experts", 16))
    H = int(spec.get("hosts_per_expert", 3))
    G = int(spec.get("gateways", 8))
    R = int(spec.get("requests", 400))
    burst = max(1, int(spec.get("burst", 8)))
    burst_gap = float(spec.get("burst_gap_s", 0.25))
    tokens = int(spec.get("tokens", 16))
    hidden = int(spec.get("hidden", 8))
    skew = float(spec.get("skew", 1.1))
    capacity = int(spec.get("capacity", 512))
    kill_hosts = int(spec.get("kill_hosts", 0))
    kill_at = int(R * float(spec.get("kill_at_frac", 0.5)))
    refresh_s = float(spec.get("refresh_period_s", 2.0))
    announce_s = float(spec.get("announce_period_s", 2.0))
    version = int(spec.get("version", 100))

    peers = run.swarm.alive_peers()
    if len(peers) < E * H + G:
        raise ValueError(
            f"serving scenario needs >= {E * H + G} peers, have {len(peers)}"
        )
    host_peers = peers[: E * H]
    gateway_peers = peers[E * H : E * H + G]

    # --- expert hosts: host i serves expert i % E (H replicas per expert)
    def _compute(expert_id: int, x):
        # deterministic synthetic expert: distinct affine map per expert,
        # so a reply proves WHICH expert computed it
        return (x * np.float32(1.0 + expert_id) + np.float32(expert_id))

    dispatch_rate = float(spec.get("dispatch_rate", 0.0))
    hosts: List = []
    for i, peer in enumerate(host_peers):
        admission = (
            Admission(rate=dispatch_rate, burst=dispatch_rate * 2.0)
            if dispatch_rate > 0 else None
        )
        hosts.append(ExpertHost(
            peer.node, prefix, [i % E], version,
            compute_fn=_compute, capacity=capacity, admission=admission,
            telemetry_registry=peer.telemetry,
        ))
    last_announce = [None] * len(hosts)

    async def announce_due() -> None:
        """Drive host record refreshes from the trace loop (no background
        tasks — deterministic, and a killed host simply stops refreshing
        so its record ages out within one TTL)."""
        now = get_dht_time()
        due = [
            k for k, peer in enumerate(host_peers)
            if peer.alive and (
                last_announce[k] is None
                or now - last_announce[k] >= announce_s
            )
        ]
        await asyncio.gather(
            *(hosts[k].announce(expiration=announce_s * 2.0) for k in due)
        )
        for k in due:
            last_announce[k] = now

    await announce_due()

    # --- gateways
    policy = RouterPolicy(
        deadline_s=float(spec.get("deadline_s", 2.0)),
        attempt_timeout_s=float(spec.get("attempt_timeout_s", 0.6)),
        retries=int(spec.get("retries", 2)),
        backoff_s=float(spec.get("backoff_s", 0.05)),
        hedge_after_s=float(spec.get("hedge_after_s", 0.3)),
        refresh_period_s=refresh_s,
    )
    routers = [
        ExpertRouter(
            peer.node, prefix, policy=policy,
            telemetry_registry=peer.telemetry, caller=peer.label,
        )
        for peer in gateway_peers
    ]
    for router in routers:
        await router.refresh(force=True)

    # --- the scripted bursty trace, fully precomputed (determinism)
    zipf = _zipf_weights(E, skew)
    trace = [
        (i, i % G, _weighted_index(run.rng, zipf)) for i in range(R)
    ]
    base_tokens = np.arange(tokens * hidden, dtype=np.float32).reshape(
        tokens, hidden
    ) / np.float32(tokens * hidden)

    killed_labels: List[str] = []
    killed_experts: List[int] = []
    kill_t: Optional[float] = None
    results: List[Dict[str, Any]] = []
    wedged = 0
    health_state: Dict[str, Any] = {}
    health_folds: List[Dict[str, Any]] = []
    fold_every = max(1, R // max(1, int(spec.get("health_folds", 3))))

    async def one_request(i: int, gw: int, expert: int) -> Dict[str, Any]:
        t0 = get_dht_time()
        x = base_tokens + np.float32(i % 7)
        y = await routers[gw].dispatch(expert, x, f"req-{i:04d}")
        ok = y is not None
        if ok:
            # the affine map proves the right expert answered
            expected = _compute(expert, x)
            if not np.allclose(y, expected, rtol=1e-5, atol=1e-5):
                raise AssertionError(
                    f"request {i}: expert {expert} returned wrong payload"
                )
        return {
            "i": i, "gateway": gw, "expert": expert, "ok": ok,
            "t0": round(t0 - SIM_EPOCH, 6),
            "dur_s": round(get_dht_time() - t0, 6),
        }

    for b0 in range(0, R, burst):
        if kill_hosts > 0 and kill_t is None and b0 >= kill_at:
            victims = host_peers[:kill_hosts]
            kill_t = get_dht_time()
            for victim in victims:
                killed_labels.append(victim.label)
                await run.swarm.kill(victim)
            killed_experts = sorted(
                {i % E for i in range(kill_hosts)}
            )
        await announce_due()
        batch = trace[b0 : b0 + burst]
        outs = await asyncio.gather(
            *(one_request(*req) for req in batch), return_exceptions=True
        )
        for out in outs:
            if isinstance(out, AssertionError):
                raise out
            if isinstance(out, BaseException):
                wedged += 1  # a request neither served nor fell through
            else:
                results.append(out)
        if (b0 // burst) % max(1, fold_every // burst) == 0:
            health_folds.append(
                fold_swarm_health(run.swarm, b0 // burst, health_state)
            )
        await asyncio.sleep(burst_gap)

    health_folds.append(
        fold_swarm_health(run.swarm, R // burst, health_state)
    )

    # --- the serving ledger: hosts claim their served bytes/requests and
    # the coordinator-shaped fold credits them on the leaderboard
    slack = float(spec.get("ledger_slack", 1.25))
    t_claim = get_dht_time()
    for k, peer in enumerate(host_peers):
        if not peer.alive:
            continue
        host = hosts[k]
        claim = ContributionClaim(
            peer=peer.node.node_id.to_bytes().hex(),
            samples=0, rounds=0, train_seconds=0.0,
            bytes_served=int(host.bytes_served),
            requests_served=int(host.requests_served),
            time=t_claim,
        )
        peer.telemetry.counter("ledger.claims").inc()
        peer.telemetry.event(
            "ledger.claim", peer=claim.peer, samples=0, rounds=0,
            train_seconds=0.0, bytes_served=claim.bytes_served,
            requests_served=claim.requests_served,
        )
        await peer.node.store(
            ledger_key(prefix).encode(), claim.model_dump(),
            get_dht_time() + 3600.0,
            subkey=peer.node.node_id.to_bytes(),
        )
    reader = gateway_peers[0]
    centry = await reader.node.get(ledger_key(prefix).encode(), latest=True)
    citems = (
        [(sk, v.value) for sk, v in centry.value.items()]
        if centry is not None and hasattr(centry.value, "items")
        else []
    )
    folded = fold_ledger(
        None, parse_claims(citems), [], slack=slack, now=get_dht_time()
    )
    run.report["ledger_rows"] = [
        {"t": folded["t"], "step": 0, "ledger": folded}
    ]
    run.report["ledger"] = folded
    run.report["leaderboard"] = leaderboard(folded)
    run.report["health_folds"] = health_folds

    # --- the sizing report
    durs_ok = [r["dur_s"] for r in results if r["ok"]]
    fall = [r for r in results if not r["ok"]]
    by_expert: Dict[int, int] = {}
    for r in results:
        by_expert[r["expert"]] = by_expert.get(r["expert"], 0) + 1
    loads = [by_expert.get(e, 0) for e in range(E)]
    mean_load = sum(loads) / max(1, len(loads))
    # fall-through AFTER the re-route bound: a request that STARTED one
    # full discovery refresh past the kill, on an expert that still has a
    # live replica, must be servable — this is the scenario's re-route
    # assertion surface
    fall_post_refresh = 0
    if kill_t is not None:
        rel_kill = kill_t - SIM_EPOCH
        survivors = {
            i % E for i in range(kill_hosts, E * H)
        }
        fall_post_refresh = sum(
            1 for r in fall
            if r["t0"] > rel_kill + refresh_s + announce_s * 2.0
            and r["expert"] in survivors
        )
    run.report["serving"] = {
        "experts": E,
        "hosts": len(host_peers),
        "gateways": G,
        "requests": R,
        "completed": len(results),
        "wedged": wedged,
        "served": len(durs_ok),
        "fall_through": len(fall),
        "fall_through_rate": round(len(fall) / max(1, R), 4),
        "fall_through_post_refresh": fall_post_refresh,
        "latency_p50_s": round(percentile(durs_ok, 0.50), 4),
        "latency_p99_s": round(percentile(durs_ok, 0.99), 4),
        "load_by_expert": loads,
        "load_skew": round(max(loads) / mean_load, 3) if mean_load else 0.0,
        "killed": killed_labels,
        "killed_experts": killed_experts,
        "kill_t": (
            round(kill_t - SIM_EPOCH, 3) if kill_t is not None else None
        ),
        "rejected": int(run.swarm.counters_total("serve.rejected")),
        "rerouted": int(run.swarm.counters_total("serve.rerouted")),
        "retries": int(run.swarm.counters_total("serve.retries")),
        "hedges": int(run.swarm.counters_total("serve.hedges")),
        "refreshes": int(run.swarm.counters_total("serve.refreshes")),
    }


SCENARIOS: Dict[str, Callable] = {
    "dht_churn": _scenario_dht_churn,
    "matchmaking": _scenario_matchmaking,
    "catalog": _scenario_catalog,
    "mixed": _scenario_mixed,
    "averaging": _scenario_averaging,
    "hierarchical": _scenario_hierarchical,
    "watchdog": _scenario_watchdog,
    "closed_loop": _scenario_closed_loop,
    "ledger": _scenario_ledger,
    "diurnal": _scenario_diurnal,
    "serving": _scenario_serving,
    # resolved specially by run_scenario: replays a fitted TwinModel
    # (dedloc_tpu/twin) instead of building a swarm from spec numbers
    "twin_replay": None,
}


def _run_twin_replay(spec: Dict[str, Any],
                     out_dir: Optional[str] = None) -> Dict[str, Any]:
    """The ``twin_replay`` scenario: spec carries a fitted TwinModel
    (``twin`` inline, or ``twin_path`` pointing at its JSON) plus optional
    workload ``overrides`` — the swarm, links and workload all come from
    the MODEL, not from scenario numbers."""
    from dedloc_tpu.twin.fit import TwinModel
    from dedloc_tpu.twin.replay import replay_twin

    if spec.get("twin") is not None:
        model = TwinModel.from_dict(spec["twin"])
    elif spec.get("twin_path"):
        model = TwinModel.load(str(spec["twin_path"]))
    else:
        raise ValueError(
            "twin_replay needs 'twin' (inline model dict) or 'twin_path'"
        )
    report = replay_twin(
        model,
        overrides=spec.get("overrides"),
        seed=int(spec.get("seed", 0)),
        out_dir=out_dir,
    )
    report["scenario"] = "twin_replay"
    return report


def run_scenario(
    spec: Dict[str, Any], out_dir: Optional[str] = None
) -> Dict[str, Any]:
    """Run one scenario spec to its sizing report (wall-clock bounded only
    by the Python it executes — scenario time is fake). When ``out_dir``
    is given, per-peer telemetry JSONL lands there for ``runlog_summary``.
    """
    name = str(spec.get("scenario", "mixed"))
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {name!r}; pick one of {sorted(SCENARIOS)}"
        )
    if name == "twin_replay":
        return _run_twin_replay(spec, out_dir=out_dir)
    run = ScenarioRun(spec)
    # real wall on purpose: the report's wall_s vs virtual_s contrast
    t0 = time.perf_counter()  # dedlint: disable=clock-monotonic
    try:
        with run.engine:
            run.engine.run(
                SCENARIOS[name](run),
                timeout=float(spec.get("virtual_timeout_s", 36000.0)),
            )
            run.engine.run(run.swarm.shutdown())
            run.report["virtual_s"] = round(
                run.engine.clock.offset - SIM_EPOCH, 3
            )
            run.report["wall_s"] = round(
                time.perf_counter() - t0,  # dedlint: disable=clock-monotonic
                3,
            )
            run.report["net"] = {
                "total_bytes": sum(run.network.stats["bytes"].values()),
                "total_flushes": sum(run.network.stats["flushes"].values()),
                "resets": run.network.stats["resets"],
                "loss_drops": run.network.stats["loss_drops"],
            }
            if out_dir is not None:
                run.report["event_logs"] = run.swarm.dump_event_logs(out_dir)
                if run.report.get("health_folds"):
                    # the coordinator-style JSONL (one row per fold, the
                    # production metrics-log shape): the post-hoc replay
                    # surface for tools/swarm_watch.py and
                    # runlog_summary --incidents
                    path = os.path.join(out_dir, "coordinator.jsonl")
                    with open(path, "w", encoding="utf-8") as f:
                        for row in run.report["health_folds"]:
                            f.write(json.dumps(row) + "\n")
                    run.report["coordinator_log"] = path
                if run.report.get("ledger_rows"):
                    # the coordinator's ledger-JSONL shape (one row per
                    # fold, last state wins) — what runlog_summary
                    # --contributions reads and replays bit-identically
                    path = os.path.join(out_dir, "ledger.jsonl")
                    with open(path, "w", encoding="utf-8") as f:
                        for row in run.report["ledger_rows"]:
                            f.write(json.dumps(row) + "\n")
                    run.report["ledger_log"] = path
                if run.report.get("incident_rows"):
                    # the coordinator's incident-JSONL shape (one row per
                    # transition, last state per id wins) — what
                    # runlog_summary --incidents and swarm_watch read
                    path = os.path.join(out_dir, "incidents.jsonl")
                    with open(path, "w", encoding="utf-8") as f:
                        for row in run.report["incident_rows"]:
                            f.write(json.dumps(row) + "\n")
                    run.report["incident_log"] = path
    finally:
        run.engine.close()
    return run.report
