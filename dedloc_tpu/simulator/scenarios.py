"""Named simulator scenarios + the sizing report.

A scenario is a JSON-able spec dict run to a REPORT dict — the numbers an
operator (or a test) needs before renting a fleet: how wide DHT records
actually replicate at N peers, how contended matchmaking leadership gets at
J concurrent joiners, how round-formation latency distributes, how big the
checkpoint catalog record grows per announcer. ``tools/swarm_sim.py`` is
the CLI face; ``tests/test_simulator.py`` asserts the report numbers
against scenario-level bounds.

Spec schema (docs/simulator.md):

    {
      "scenario": "mixed",          # dht_churn | matchmaking | catalog | mixed
      "seed": 0,                     # engine + network + churn seed
      "peers": 1000,                 # swarm size
      "link": {"latency_s": 0.02, "bandwidth_bps": 12500000.0,
               "loss": 0.0, "jitter_s": 0.0},
      "bucket_size": 8, "num_replicas": 5, "parallel_rpc": 3,
      ...scenario-specific keys (each runner documents its own)
    }

Every runner is deterministic for a fixed spec: scenario randomness comes
from ``random.Random(seed)``, peer ids/bootstrap choices hash off the same
seed, and the engine freezes scenario time.
"""
from __future__ import annotations

import asyncio
import random
import time
from typing import Any, Callable, Dict, List, Optional

from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.simulator.engine import SIM_EPOCH, SimEngine
from dedloc_tpu.simulator.network import LinkSpec, SimNetwork
from dedloc_tpu.simulator.swarm import SimSwarm
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation surprises
    across numpy versions); 0.0 on empty input."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return ordered[rank]


def _span_durations(swarm: SimSwarm, name: str,
                    ok_only: bool = True) -> List[float]:
    out = []
    for peer in swarm.peers:
        for record in peer.telemetry.events:
            if record.get("event") != name:
                continue
            if ok_only and record.get("ok") is not True:
                continue
            out.append(float(record.get("dur_s", 0.0)))
    return out


def record_fanout(swarm: SimSwarm, key: bytes) -> int:
    """How many live peers hold ``key`` in primary storage — the measured
    replica fan-out a sizing decision needs vs the configured
    ``num_replicas`` bound."""
    count = 0
    for peer in swarm.alive_peers():
        if peer.node.storage.get(key) is not None:
            count += 1
    return count


# --------------------------------------------------------------- harness


class ScenarioRun:
    """Everything a scenario phase needs in one handle."""

    def __init__(self, spec: Dict[str, Any]):
        self.spec = dict(spec)
        self.seed = int(spec.get("seed", 0))
        self.rng = random.Random(self.seed ^ 0xC0FFEE)
        self.engine = SimEngine(seed=self.seed)
        self.network = SimNetwork(
            seed=self.seed, default_link=LinkSpec.from_dict(spec.get("link"))
        )
        self.swarm = SimSwarm(
            self.network,
            seed=self.seed,
            bucket_size=int(spec.get("bucket_size", 8)),
            num_replicas=int(spec.get("num_replicas", 5)),
            parallel_rpc=int(spec.get("parallel_rpc", 3)),
            request_timeout=float(spec.get("request_timeout", 5.0)),
        )
        self.report: Dict[str, Any] = {
            "scenario": spec.get("scenario"),
            "seed": self.seed,
            "peers": int(spec.get("peers", 100)),
        }


# --------------------------------------------------------------- phases
#
# Phases are composable coroutine builders: each takes (run, spec) and
# fills a section of run.report. The mixed scenario chains them over ONE
# swarm — churn from the DHT phase is still in effect when matchmaking
# starts, which is the point.


async def phase_spawn(run: ScenarioRun) -> None:
    n = int(run.spec.get("peers", 100))
    t0 = time.perf_counter()
    v0 = run.engine.clock.offset
    await run.swarm.spawn(n, bootstrap_fanout=int(
        run.spec.get("bootstrap_fanout", 2)
    ))
    run.report["spawn"] = {
        "peers": n,
        "wall_s": round(time.perf_counter() - t0, 3),
        "virtual_s": round(run.engine.clock.offset - v0, 3),
    }


async def phase_dht(run: ScenarioRun) -> None:
    """Puts from scattered writers, churn a fraction of the swarm, then
    reads — measuring replica fan-out vs the ``num_replicas`` bound and
    get success under churn."""
    spec = run.spec
    puts = int(spec.get("puts", 40))
    churn_fraction = float(spec.get("churn_fraction", 0.2))
    swarm, rng = run.swarm, run.rng
    keys = [f"sim-record-{i:03d}".encode() for i in range(puts)]
    now = get_dht_time()
    stored = 0
    for i, key in enumerate(keys):
        writer = swarm.alive_peers()[
            rng.randrange(len(swarm.alive_peers()))
        ]
        if await writer.node.store(key, b"v-%d" % i, now + 3600.0):
            stored += 1
    fanout = [record_fanout(swarm, key) for key in keys]
    # churn: kill a seeded sample, all at once (mass-disconnect shape)
    victims = rng.sample(
        swarm.alive_peers(), int(len(swarm.alive_peers()) * churn_fraction)
    )
    for victim in victims:
        await swarm.kill(victim)
    await asyncio.sleep(1.0)  # virtual settling time
    hits = 0
    for i, key in enumerate(keys):
        reader = swarm.alive_peers()[
            rng.randrange(len(swarm.alive_peers()))
        ]
        entry = await reader.node.get(key, latest=True)
        if entry is not None and entry.value == b"v-%d" % i:
            hits += 1
    run.report["dht"] = {
        "puts": puts,
        "stored": stored,
        "replica_bound": swarm.num_replicas + 1,  # nearest set + self-store
        "fanout_mean": round(sum(fanout) / max(1, len(fanout)), 2),
        "fanout_max": max(fanout) if fanout else 0,
        "churned": len(victims),
        "get_hits": hits,
        "get_success": round(hits / max(1, puts), 3),
    }


async def phase_matchmaking(run: ScenarioRun) -> None:
    """R rounds of J concurrent joiners targeting ``group_size`` — the
    leader-contention measurement: do groups form without livelock, how
    many leaders fight per round, and the round-formation latency
    distribution."""
    spec = run.spec
    joiners = int(spec.get("joiners", 50))
    rounds = int(spec.get("rounds", 5))
    group_size = int(spec.get("group_size", 16))
    window = float(spec.get("window_s", 5.0))
    prefix = str(spec.get("prefix", "simexp"))
    swarm, rng = run.swarm, run.rng
    pool = [p for p in swarm.alive_peers()]
    participants = (
        pool if joiners >= len(pool) else rng.sample(pool, joiners)
    )
    for peer in participants:
        if peer.matchmaking is None:
            peer.attach_matchmaking(
                prefix, bandwidth=50.0 + (peer.index % 7) * 25.0,
                target_group_size=group_size,
                averaging_expiration=window,
            )
    formed: Dict[str, List[int]] = {}
    failures = 0
    for r in range(rounds):
        round_id = f"round-{r:04d}"
        active = [p for p in participants if p.alive]

        async def one(peer):
            try:
                return await peer.matchmaking.form_group(round_id)
            except Exception:  # noqa: BLE001 — counted, scenario continues
                return None

        groups = await asyncio.gather(*(one(p) for p in active))
        sizes = []
        seen_nonces = set()
        for g in groups:
            if g is None:
                failures += 1
            elif g.nonce not in seen_nonces:
                seen_nonces.add(g.nonce)
                sizes.append(len(g.members))
        formed[round_id] = sizes
        # advance past the leader-entry expirations so rounds stay disjoint
        await asyncio.sleep(window + 1.0)
    durs = _span_durations(swarm, "mm.form_group")
    all_sizes = [s for sizes in formed.values() for s in sizes]
    run.report["matchmaking"] = {
        "joiners": len(participants),
        "rounds": rounds,
        "groups_formed": len(all_sizes),
        "mean_group_size": round(
            sum(all_sizes) / max(1, len(all_sizes)), 2
        ),
        "full_groups": sum(1 for s in all_sizes if s >= group_size),
        "singletons": sum(1 for s in all_sizes if s == 1),
        "join_failures": int(swarm.counters_total("mm.join_failures")),
        "leader_changes": int(swarm.counters_total("mm.leader_changes")),
        "form_failures": failures,
        "formation_p50_s": round(percentile(durs, 0.50), 3),
        "formation_p95_s": round(percentile(durs, 0.95), 3),
    }


async def phase_catalog(run: ScenarioRun) -> None:
    """Announcers publish (some divergent) checkpoint manifests; a restorer
    must select the majority digest and complete a sharded multi-provider
    restore over the simulated links."""
    spec = run.spec
    announcers = int(spec.get("announcers", 8))
    divergent = int(spec.get("divergent", 2))
    step = int(spec.get("ckpt_step", 100))
    total_size = int(spec.get("ckpt_total_size", 4096))
    shard_size = int(spec.get("ckpt_shard_size", 512))
    prefix = str(spec.get("prefix", "simexp"))
    swarm, rng = run.swarm, run.rng
    alive = swarm.alive_peers()
    if len(alive) < 2:
        raise ValueError(
            f"catalog phase needs >= 2 live peers (an announcer and a "
            f"restorer); {len(alive)} alive — raise 'peers' or lower churn"
        )
    # clamp: at least one non-provider must remain to play the restorer
    # (reachable from the CLI with e.g. peers=8, announcers=8)
    announcers = min(announcers, len(alive) - 1)
    providers = rng.sample(alive, announcers)
    majority_digest = None
    for i, peer in enumerate(providers):
        variant = 1 if i < divergent else 0  # minority forks first
        digest = peer.serve_checkpoint(
            step, total_size=total_size, shard_size=shard_size,
            variant=variant,
        )
        if variant == 0:
            majority_digest = digest
        ok = await peer.announce_checkpoint(prefix)
        if not ok:
            logger.warning(f"catalog announce failed for {peer.label}")
    from dedloc_tpu.checkpointing.catalog import (
        catalog_key,
        parse_announcements,
        select_target,
    )
    from dedloc_tpu.checkpointing.fetcher import sharded_restore

    reader = rng.choice(
        [p for p in swarm.alive_peers() if p not in providers]
    )
    entry = await reader.node.get(catalog_key(prefix).encode(), latest=True)
    items = (
        [(sk, v.value) for sk, v in entry.value.items()]
        if entry is not None and hasattr(entry.value, "items")
        else []
    )
    announcements = parse_announcements(items)
    # sizing: the ACTUAL stored/wire size — the same msgpack codec the DHT
    # store path uses, not a Python repr approximation
    from dedloc_tpu.core.serialization import pack_obj

    catalog_bytes = sum(
        len(pack_obj(a.model_dump())) for a in announcements
    )
    target = select_target(announcements)
    restored_ok = False
    providers_used = 0
    if target is not None:
        stats: Dict[str, Any] = {}
        try:
            _meta, tree, manifest = await sharded_restore(
                reader.node.client,
                announcements,
                parallelism=int(spec.get("fetch_parallelism", 4)),
                telemetry_registry=reader.telemetry,
                stats=stats,
            )
            restored_ok = (
                manifest.digest() == majority_digest
                and "sim_state" in tree
            )
            providers_used = int(stats.get("providers", 0))
        except Exception as e:  # noqa: BLE001 — reported, not raised
            logger.warning(f"sim restore failed: {e!r}")
    run.report["catalog"] = {
        "announcers": announcers,
        "divergent": divergent,
        "parsed_announcements": len(announcements),
        "selected_majority": bool(
            target is not None and target[1] == majority_digest
        ),
        "restore_ok": restored_ok,
        "providers_used": providers_used,
        "catalog_record_bytes": catalog_bytes,
        "bytes_per_announcer": (
            round(catalog_bytes / max(1, len(announcements)))
        ),
    }


# -------------------------------------------------------------- scenarios


async def _scenario_dht_churn(run: ScenarioRun) -> None:
    await phase_spawn(run)
    await phase_dht(run)


async def _scenario_matchmaking(run: ScenarioRun) -> None:
    await phase_spawn(run)
    await phase_matchmaking(run)


async def _scenario_catalog(run: ScenarioRun) -> None:
    await phase_spawn(run)
    await phase_catalog(run)


async def _scenario_mixed(run: ScenarioRun) -> None:
    """The acceptance scenario: DHT churn + matchmaking rounds + catalog
    announcements over ONE swarm, in that order, so each phase inherits
    the previous one's damage."""
    await phase_spawn(run)
    await phase_dht(run)
    await phase_matchmaking(run)
    await phase_catalog(run)


SCENARIOS: Dict[str, Callable] = {
    "dht_churn": _scenario_dht_churn,
    "matchmaking": _scenario_matchmaking,
    "catalog": _scenario_catalog,
    "mixed": _scenario_mixed,
}


def run_scenario(
    spec: Dict[str, Any], out_dir: Optional[str] = None
) -> Dict[str, Any]:
    """Run one scenario spec to its sizing report (wall-clock bounded only
    by the Python it executes — scenario time is fake). When ``out_dir``
    is given, per-peer telemetry JSONL lands there for ``runlog_summary``.
    """
    name = str(spec.get("scenario", "mixed"))
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {name!r}; pick one of {sorted(SCENARIOS)}"
        )
    run = ScenarioRun(spec)
    t0 = time.perf_counter()
    try:
        with run.engine:
            run.engine.run(
                SCENARIOS[name](run),
                timeout=float(spec.get("virtual_timeout_s", 36000.0)),
            )
            run.engine.run(run.swarm.shutdown())
            run.report["virtual_s"] = round(
                run.engine.clock.offset - SIM_EPOCH, 3
            )
            run.report["wall_s"] = round(time.perf_counter() - t0, 3)
            run.report["net"] = {
                "total_bytes": sum(run.network.stats["bytes"].values()),
                "total_flushes": sum(run.network.stats["flushes"].values()),
                "resets": run.network.stats["resets"],
                "loss_drops": run.network.stats["loss_drops"],
            }
            if out_dir is not None:
                run.report["event_logs"] = run.swarm.dump_event_logs(out_dir)
    finally:
        run.engine.close()
    return run.report
