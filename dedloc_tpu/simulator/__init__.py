"""Discrete-event swarm simulator: 1,000-peer runs in one process.

Layers (docs/simulator.md):

- ``engine``    — a virtual-time asyncio event loop riding the seeded
  ``testing/faults.py`` FakeClock: no real sleeps, deterministic
  same-timestamp ordering.
- ``network``   — per-directed-link latency/bandwidth/loss models with
  serialized-uplink contention, behind the ``dht/transport.py`` seam.
- ``swarm``     — spawn N full peers (DHT node, matchmaker, optional
  checkpoint-catalog announcer) in one process with component-scoped
  telemetry registries.
- ``scenarios`` — named, JSON-configurable scenarios + the sizing report
  ``tools/swarm_sim.py`` emits.
"""
from dedloc_tpu.simulator.engine import SimEngine
from dedloc_tpu.simulator.network import LinkSpec, SimNetwork, SimTransport
from dedloc_tpu.simulator.swarm import SimPeer, SimSwarm

__all__ = [
    "SimEngine",
    "LinkSpec",
    "SimNetwork",
    "SimTransport",
    "SimPeer",
    "SimSwarm",
]
