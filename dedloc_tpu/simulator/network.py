"""In-process simulated network behind the ``dht/transport.py`` seam.

Models, per DIRECTED (src_host, dst_host) link:

- **latency**: fixed one-way delay per delivery (plus seeded jitter)
- **bandwidth**: bytes/second, charged against the SENDER's serialized
  uplink — one transmission at a time per source host, the same
  volunteer-link shape as bench.py's ``LinkSim`` (a 1 MB state blob parks
  the uplink for its full transmission time; everything else queues behind)
- **loss**: per-flush probability that the CONNECTION dies (streams are
  reliable — TCP loss past the retry budget surfaces as a reset, not a
  silently missing frame), drawn from the network's seeded RNG

Composability with ``testing/faults.py``: the RPC-level fault points
(``rpc.client.call``, ``rpc.server.dispatch``) sit ABOVE the seam and fire
unchanged on this transport; additionally every scheduled delivery consults
the ``sim.network.deliver`` fault point (context: ``src``, ``dst``,
``nbytes``) so a schedule can drop or delay one specific directed link —
that is how scenario tests build asymmetric partitions and slow links
without touching peer code.

Everything is scheduled on the current (virtual-time) event loop via
``call_at``; under ``simulator/engine.py`` a 10-second straggler window
costs zero wall time. The classes also work on a REAL event loop (then the
latencies are real waits) — handy for debugging a scenario interactively.
"""
from __future__ import annotations

import asyncio
import inspect
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from dedloc_tpu.dht.transport import Endpoint, Listener, Transport
from dedloc_tpu.testing import faults
from dedloc_tpu.utils.aio import keep_task
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# minimum spacing between consecutive deliveries on one stream direction:
# larger than any engine tie-break epsilon (~2e-6 max), far below any
# modeled latency
_STREAM_STEP_S = 1e-5

# frames at or below this ride PAST the uplink queue (they still pay their
# own transmit time and latency): a real network packetizes, so a 30-byte
# RPC ack interleaves after at most one MTU of a bulk transfer instead of
# waiting behind megabytes of queued frames. Strict whole-frame FIFO is
# not how TCP behaves across connections, and it would poison every
# RTT/goodput estimate measured over request/ack round trips (the
# telemetry a digital twin is fitted from). Their skipped queue time is
# bandwidth noise by construction (<= one MTU-ish frame).
_SMALL_FRAME_BYTES = 1024


@dataclass
class LinkSpec:
    """One directed link's behavior. ``bandwidth_bps`` is BYTES per second
    (0 or negative = infinite); ``loss`` is the per-flush connection-death
    probability; ``jitter_s`` adds seeded uniform [0, jitter_s) to each
    delivery's latency."""

    latency_s: float = 0.001
    bandwidth_bps: float = 0.0
    loss: float = 0.0
    jitter_s: float = 0.0

    @classmethod
    def from_dict(cls, raw: Optional[dict]) -> "LinkSpec":
        raw = dict(raw or {})
        return cls(
            latency_s=float(raw.get("latency_s", 0.001)),
            bandwidth_bps=float(raw.get("bandwidth_bps", 0.0)),
            loss=float(raw.get("loss", 0.0)),
            jitter_s=float(raw.get("jitter_s", 0.0)),
        )

    @classmethod
    def from_estimate(
        cls,
        rtt_s: Optional[float] = None,
        rtt_jitter_s: Optional[float] = None,
        goodput_bps: Optional[float] = None,
        loss: Optional[float] = None,
        default: Optional["LinkSpec"] = None,
    ) -> "LinkSpec":
        """A link spec from TELEMETRY estimates (telemetry/links.py fields,
        or a fitted TwinModel link) rather than hand-written scenario
        numbers: one-way latency is half the measured RTT and one-way
        jitter half the RTT-deviation EWMA (both inputs are ROUND-TRIP
        measurements), the serialized uplink rate is the measured goodput,
        and any missing estimate falls back to ``default``'s field (an
        unmeasured dimension keeps the fleet-default behavior instead of
        silently becoming ideal)."""
        default = default or cls()
        return cls(
            latency_s=(
                max(1e-6, float(rtt_s) / 2.0)
                if rtt_s is not None else default.latency_s
            ),
            bandwidth_bps=(
                max(1.0, float(goodput_bps))
                if goodput_bps is not None else default.bandwidth_bps
            ),
            loss=(
                min(0.5, max(0.0, float(loss)))
                if loss is not None else default.loss
            ),
            jitter_s=(
                max(0.0, float(rtt_jitter_s) / 2.0)
                if rtt_jitter_s is not None else default.jitter_s
            ),
        )


class SimStreamWriter:
    """Duck-typed ``asyncio.StreamWriter`` for one direction of a simulated
    connection. Implements exactly the surface the RPC layer touches:
    write / drain / close / is_closing / wait_closed / get_extra_info."""

    def __init__(self, conn: "_SimConnection", side: int):
        self._conn = conn
        self._side = side  # 0 = the connecting client, 1 = the acceptor
        self._buffer: List[bytes] = []
        self._closed = False

    def write(self, data: bytes) -> None:
        if self._closed or self._conn.dead:
            return  # writes on a dying socket vanish, like a real half-close
        self._buffer.append(bytes(data))

    async def drain(self) -> None:
        if self._closed or self._conn.dead:
            raise ConnectionResetError("simulated connection lost")
        if not self._buffer:
            return
        payload = b"".join(self._buffer)
        self._buffer.clear()
        self._conn.network._transmit(self._conn, self._side, payload)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._conn.close_from(self._side)

    def is_closing(self) -> bool:
        return self._closed or self._conn.dead

    async def wait_closed(self) -> None:
        return None

    def get_extra_info(self, name: str, default: Any = None) -> Any:
        if name == "peername":
            return self._conn.peername(self._side)
        if name == "sockname":
            return self._conn.sockname(self._side)
        return default  # "socket" -> None: _set_nodelay no-ops


class _SimConnection:
    """A bidirectional stream pair between two simulated hosts."""

    def __init__(
        self,
        network: "SimNetwork",
        client_addr: Endpoint,
        server_addr: Endpoint,
    ):
        self.network = network
        self.addrs = (client_addr, server_addr)
        self.readers = (asyncio.StreamReader(), asyncio.StreamReader())
        self.writers = (SimStreamWriter(self, 0), SimStreamWriter(self, 1))
        # per-direction last-arrival cursor: jitter must never reorder a
        # stream's bytes (TCP delivers in order or not at all)
        self.arrival_cursor = [0.0, 0.0]
        self.dead = False

    def host(self, side: int) -> str:
        return self.addrs[side][0]

    def peername(self, side: int) -> Endpoint:
        return self.addrs[1 - side]

    def sockname(self, side: int) -> Endpoint:
        return self.addrs[side]

    def close_from(self, side: int) -> None:
        """Graceful close by one side: the other side's reader sees EOF
        after the link latency (FIN in flight). Once BOTH sides have
        closed, the connection is forgotten quietly (not a reset) — a
        long sim must not accumulate every connection it ever opened."""
        if self.dead:
            return
        self.network._schedule_eof(self, 1 - side)
        if all(w._closed for w in self.writers):
            self.network._forget(self, reset=False)

    def reset(self) -> None:
        """Connection death (loss, peer kill): both readers fail NOW with
        ConnectionResetError — in-flight deliveries are discarded."""
        if self.dead:
            return
        self.dead = True
        for reader in self.readers:
            if reader.exception() is None and not reader.at_eof():
                reader.set_exception(
                    ConnectionResetError("simulated connection reset")
                )
        self.network._forget(self)


class _SimListener(Listener):
    def __init__(self, network: "SimNetwork", host: str, port: int,
                 on_connection):
        self.network = network
        self.host, self.port = host, port
        self.on_connection = on_connection
        self.closed = False

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.network._listeners.pop((self.host, self.port), None)

    async def wait_closed(self) -> None:
        return None


class SimNetwork:
    """The one shared network of a simulated swarm: listeners, links, and
    the seeded randomness for loss/jitter. ``stats`` accumulates wire-level
    totals for the sizing report (bytes/frames per directed host pair,
    drops)."""

    def __init__(
        self,
        seed: int = 0,
        default_link: Optional[LinkSpec] = None,
        links: Optional[Dict[Tuple[str, str], LinkSpec]] = None,
    ):
        self.rng = random.Random(seed ^ 0x5EED_0DE)
        self.default_link = default_link or LinkSpec()
        # per-directed-link overrides, e.g. a whole fitted TwinModel link
        # table ({(src_host, dst_host): LinkSpec}); set_link adds more
        self._links: Dict[Tuple[str, str], LinkSpec] = dict(links or {})
        self._listeners: Dict[Endpoint, _SimListener] = {}
        # live connections indexed by BOTH endpoints' hosts: kill_host at
        # 1,000 peers must not scan every connection ever opened
        self._conns_by_host: Dict[str, set] = {}
        self._uplink_busy_until: Dict[str, float] = {}
        self._next_port: Dict[str, int] = {}
        self._next_ephemeral = 30000
        self.stats: Dict[str, Any] = {
            "bytes": {},  # (src, dst) -> payload bytes delivered
            "flushes": {},  # (src, dst) -> flush count
            "resets": 0,
            "loss_drops": 0,
            "fault_drops": 0,
        }

    # ------------------------------------------------------------- topology

    def set_link(self, src_host: str, dst_host: str, spec: LinkSpec) -> None:
        """Configure one DIRECTED link (src -> dst). Unset pairs use the
        network default."""
        self._links[(src_host, dst_host)] = spec

    def link(self, src_host: str, dst_host: str) -> LinkSpec:
        return self._links.get((src_host, dst_host), self.default_link)

    def transport(self, host: str) -> "SimTransport":
        """The per-peer transport bound to ``host`` (the network needs the
        sender's identity for uplink contention and peername)."""
        return SimTransport(self, host)

    # ---------------------------------------------------------- connections

    def listen(self, host: str, port: int, on_connection) -> _SimListener:
        if port == 0:
            port = self._next_port.get(host, 40000)
            self._next_port[host] = port + 1
        key = (host, port)
        if key in self._listeners:
            raise OSError(f"simulated address already in use: {key}")
        listener = _SimListener(self, host, port, on_connection)
        self._listeners[key] = listener
        return listener

    async def connect(
        self, src_host: str, endpoint: Endpoint
    ) -> Tuple[asyncio.StreamReader, SimStreamWriter]:
        endpoint = (endpoint[0], int(endpoint[1]))
        listener = self._listeners.get(endpoint)
        if listener is None or listener.closed:
            raise ConnectionRefusedError(
                f"no simulated listener at {endpoint}"
            )
        spec = self.link(src_host, endpoint[0])
        # connection setup charges the full handshake in virtual time: the
        # SYN leg (src->dst latency) plus the SYN-ACK leg (dst->src) —
        # ``open_connection`` returning before the SYN-ACK would make the
        # RPC client's connect timing (the free RTT probe telemetry/links.py
        # feeds on) read HALF the real round trip, and a simulator model
        # fitted from that telemetry would come out twice as fast as the
        # network it mimics. The accept fires once the handshake wait
        # completes; the first data frame pays the src->dst latency again
        # on delivery.
        reverse = self.link(endpoint[0], src_host)
        await asyncio.sleep(spec.latency_s + reverse.latency_s)
        if listener.closed:  # raced a shutdown during the handshake
            raise ConnectionRefusedError(
                f"simulated listener at {endpoint} closed during connect"
            )
        client_addr = (src_host, self._next_ephemeral)
        self._next_ephemeral += 1
        conn = _SimConnection(self, client_addr, endpoint)
        self._conns_by_host.setdefault(src_host, set()).add(conn)
        self._conns_by_host.setdefault(endpoint[0], set()).add(conn)
        # the acceptor's callback runs as its own task, like
        # asyncio.start_server's protocol factory (retained +
        # exception-logged so a dead acceptor is visible, utils/aio)
        keep_task(
            listener.on_connection(conn.readers[1], conn.writers[1]),
            name="sim acceptor", log=logger,
        )
        return conn.readers[0], conn.writers[0]

    # ------------------------------------------------------------- delivery

    def _transmit(self, conn: _SimConnection, side: int, payload: bytes) -> None:
        # hot path: called once per flushed frame — a large scenario pushes
        # hundreds of thousands through here. get_running_loop (we are
        # always under a coroutine's drain) beats get_event_loop, and the
        # link-override dict is only consulted when overrides exist.
        loop = asyncio.get_running_loop()
        now = loop.time()
        src = conn.addrs[side][0]
        dst = conn.addrs[1 - side][0]
        spec = (
            self._links.get((src, dst), self.default_link)
            if self._links else self.default_link
        )
        # composable fault point: scenario schedules can drop, delay, error
        # or kill one directed link's deliveries without touching peer
        # code. Same action contract as apply_transport_fault: ``drop`` /
        # ``kill`` reset the connection (kill runs its callback first),
        # ``error`` raises an OSError into the SENDER's drain, ``delay``
        # holds the delivery.
        delay_extra = 0.0
        if faults._active is not None:
            fault = faults.fire(
                "sim.network.deliver", src=src, dst=dst, nbytes=len(payload)
            )
            if fault is not None:
                if fault.action == "error":
                    raise OSError(
                        f"fault injected: error delivering {src}->{dst}"
                    )
                if fault.action in ("drop", "kill"):
                    if fault.action == "kill" and fault.callback is not None:
                        result = fault.callback()
                        if inspect.isawaitable(result):
                            keep_task(result, name="kill-fault callback",
                                      log=logger)
                    self.stats["fault_drops"] += 1
                    loop.call_soon(conn.reset)
                    return
                if fault.action == "delay":
                    delay_extra = fault.delay
        if spec.loss > 0.0 and self.rng.random() < spec.loss:
            # reliable stream semantics: loss kills the connection after
            # the latency (the peer sees a reset, not a hole in the stream)
            self.stats["loss_drops"] += 1
            loop.call_at(now + spec.latency_s, conn.reset)
            return
        # serialized uplink: one transmission at a time per source host —
        # except sub-MTU control frames, which interleave (see
        # _SMALL_FRAME_BYTES above) and do not extend the busy window.
        # Uncontended fast path: the busy map is allocated lazily per host
        # (first rate-limited frame), and while NO host has ever contended
        # — the common pure-latency scenario — the math is branch-only.
        nbytes = len(payload)
        small = nbytes <= _SMALL_FRAME_BYTES
        if small:
            start = now
        else:
            prior = (
                self._uplink_busy_until.get(src, 0.0)
                if self._uplink_busy_until else 0.0
            )
            start = prior if prior > now else now
        if spec.bandwidth_bps > 0.0:
            done = start + nbytes / spec.bandwidth_bps
            if not small:
                self._uplink_busy_until[src] = done
        else:
            # infinite rate: the busy window is a point at ``start`` — an
            # entry would never delay anyone, so none is written
            done = start
        arrival = done + spec.latency_s + delay_extra
        if spec.jitter_s > 0.0:
            arrival += self.rng.uniform(0.0, spec.jitter_s)
        # FIFO per direction: jitter may not reorder stream bytes. Strictly
        # increasing (not merely non-decreasing): two same-instant arrivals
        # would each get an INDEPENDENT engine tie-break epsilon on their
        # timers and could fire in either order — a microsecond step keeps
        # the stream sequenced above any epsilon (engine scale: 1e-9).
        arrival = max(arrival, conn.arrival_cursor[side] + _STREAM_STEP_S)
        conn.arrival_cursor[side] = arrival
        key = (src, dst)
        stats = self.stats
        stats["bytes"][key] = stats["bytes"].get(key, 0) + nbytes
        stats["flushes"][key] = stats["flushes"].get(key, 0) + 1
        loop.call_at(arrival, self._deliver, conn, 1 - side, payload)

    def _deliver(self, conn: _SimConnection, to_side: int, payload: bytes) -> None:
        if conn.dead:
            return
        reader = conn.readers[to_side]
        if reader.exception() is None and not reader.at_eof():
            reader.feed_data(payload)

    def _schedule_eof(self, conn: _SimConnection, to_side: int) -> None:
        loop = asyncio.get_event_loop()
        spec = self.link(conn.host(1 - to_side), conn.host(to_side))
        # strictly after the direction's last data delivery: EOF overtaking
        # the final payload would drop it (a graceful close must never read
        # as a truncated stream)
        arrival = max(
            loop.time() + spec.latency_s,
            conn.arrival_cursor[1 - to_side] + _STREAM_STEP_S,
        )
        conn.arrival_cursor[1 - to_side] = arrival
        loop.call_at(arrival, self._feed_eof, conn, to_side)

    def _feed_eof(self, conn: _SimConnection, to_side: int) -> None:
        if conn.dead:
            return
        reader = conn.readers[to_side]
        if reader.exception() is None and not reader.at_eof():
            reader.feed_eof()

    def _forget(self, conn: _SimConnection, reset: bool = True) -> None:
        if reset:
            self.stats["resets"] += 1
        for side in (0, 1):
            bucket = self._conns_by_host.get(conn.host(side))
            if bucket is not None:
                bucket.discard(conn)

    # ---------------------------------------------------------------- churn

    def kill_host(self, host: str) -> int:
        """Process-death semantics for ``host``: every listener vanishes and
        every live connection touching the host resets (a killed peer's OS
        resets its sockets — same contract as the ``drop`` transport fault).
        Returns how many connections were reset."""
        for key in [k for k in self._listeners if k[0] == host]:
            self._listeners[key].close()
        victims = list(self._conns_by_host.get(host, ()))
        for conn in victims:
            conn.reset()
        self._conns_by_host.pop(host, None)
        self._uplink_busy_until.pop(host, None)
        return len(victims)

    def reset_links(self, src_host: str, dst_host: str) -> int:
        """Route-flap semantics for one host PAIR: every live connection
        between the two hosts resets (both directions — a rerouted path
        kills the TCP flows riding it), while both hosts stay alive and
        reconnect lazily. The reconnect matters beyond realism: the link
        RTT estimate samples on CONNECT (dht/protocol.py piggybacked
        ping), so a latency change on a pooled connection is invisible to
        telemetry until the flow re-opens — exactly as in production.
        Returns how many connections were reset."""
        victims = [
            conn for conn in self._conns_by_host.get(src_host, ())
            if dst_host in (conn.host(0), conn.host(1))
        ]
        for conn in victims:
            conn.reset()
        return len(victims)


class SimTransport(Transport):
    """The per-peer face of a SimNetwork behind the ``dht/transport.py``
    seam: same interface as TcpTransport, so RPCServer/RPCClient (and
    everything above them) cannot tell the difference."""

    def __init__(self, network: SimNetwork, host: str):
        self.network = network
        self.host = host

    async def start_server(
        self, host: str, port: int, on_connection
    ) -> Listener:
        # the peer's simulated identity wins over the bind-all host string
        return self.network.listen(self.host, port, on_connection)

    async def open_connection(
        self, endpoint: Endpoint, timeout: float
    ) -> Tuple[asyncio.StreamReader, Any]:
        return await asyncio.wait_for(
            self.network.connect(self.host, endpoint), timeout=timeout
        )
