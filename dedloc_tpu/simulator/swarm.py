"""Spawn N full peers — DHT node, matchmaker, optional checkpoint-catalog
announcer — in ONE process on the simulated transport.

Each peer gets:

- its own simulated host (``peer-0042``) and ``SimTransport`` bound to it,
  so the network can charge its serialized uplink and stamp peernames;
- a deterministic node id derived from (swarm seed, peer index) — two
  same-seed runs build the identical Kademlia topology;
- a component-scoped ``Telemetry`` registry (the PR 2 machinery for
  in-process multi-peer attribution), held in memory and dumped to
  per-peer JSONL by ``dump_event_logs`` so ``runlog_summary
  --health/--trace/--topology`` work on simulator output unchanged.

Everything the peer runs — ``DHTNode`` iterative lookups, ``Matchmaking``
leader election, ``checkpointing.fetcher`` restores — is the PRODUCTION
code, untouched, running above the transport seam.
"""
from __future__ import annotations

import hashlib
import json
import os
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from dedloc_tpu.averaging.matchmaking import Matchmaking
from dedloc_tpu.checkpointing.catalog import (
    CheckpointAnnouncement,
    catalog_key,
)
from dedloc_tpu.checkpointing.manifest import CheckpointManifest, shard_bytes
from dedloc_tpu.core.serialization import CompressionType, serialize_array
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.dht.node import DHTNode
from dedloc_tpu.dht.routing import DHTID, ID_BITS, NodeInfo
from dedloc_tpu.simulator.network import SimNetwork
from dedloc_tpu.telemetry.registry import Telemetry
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _synthetic_checkpoint(
    step: int, total_size: int, shard_size: int, variant: int = 0
):
    """A tiny deterministic (manifest, flat) pair for catalog scenarios:
    real manifests, real digests, real shard bytes — no model needed.
    ``variant`` perturbs the content, producing a DIVERGENT manifest at the
    same step (the catalog's majority-digest selection must reject it)."""
    flat = (
        np.arange(total_size, dtype=np.float32) + np.float32(variant * 1000)
    )
    digests = []
    for start in range(0, total_size, shard_size):
        chunk = np.ascontiguousarray(flat[start : start + shard_size])
        digests.append(hashlib.sha256(chunk.tobytes()).digest())
    manifest = CheckpointManifest(
        step=int(step),
        shard_size=int(shard_size),
        total_size=int(total_size),
        spec=(("sim_state", (total_size,), "<f4"),),
        shard_digests=tuple(digests),
        metadata={"sim": True, "variant": int(variant)},
    )
    return manifest, flat


class SimPeer:
    """One simulated peer. Built by ``SimSwarm.spawn`` — use the swarm."""

    # in-memory event bound per simulated peer: the scenario's telemetry is
    # read from MEMORY after the run (no per-peer JSONL sink while 1,000
    # peers share one process), so a busy leader must not evict its early
    # rounds before the dump
    MAX_EVENTS = 32768

    def __init__(self, index: int, label: str, host: str):
        self.index = index
        self.label = label
        self.host = host
        # telemetry is LAZY: a shell peer (spawn_shells) that never comes
        # online must cost ~nothing, and a 10k-peer diurnal swarm keeps
        # most of its roster offline at any instant. The registry is
        # created on first touch — which for a full peer is node
        # hydration, i.e. the moment it can first emit an event.
        self._telemetry: Optional[Telemetry] = None
        self.node: Optional[DHTNode] = None
        self.matchmaking: Optional[Matchmaking] = None
        self.alive = False
        # catalog-provider state (when announcing): (manifest, flat)
        self._checkpoint = None

    @property
    def telemetry(self) -> Telemetry:
        t = self._telemetry
        if t is None:
            # link_top_k raised to the LinkTable's own bound: the 8-link
            # cap protects the signed metrics-bus SNAPSHOT, but a simulated
            # peer dumps to JSONL post-run, and a twin fitted from that
            # dump needs every link's RTT, not just the 8 busiest.
            # clock: the VIRTUAL clock, not the fake-clock-aware monotonic
            # — span durations then measure only MODELED time, not the
            # real Python seconds the host happened to spend executing
            # the scenario. That noise was ±5-15% of a sub-second round
            # wall and varied run to run, which both blurred the
            # determinism story and put a floor under digital-twin
            # fidelity. (Outside the engine get_dht_time is the wall
            # clock — the interactive-debug case keeps real timings.)
            t = self._telemetry = Telemetry(
                peer=self.label, max_events=self.MAX_EVENTS, link_top_k=64,
                clock=get_dht_time,
            )
        return t

    @property
    def endpoint(self):
        return self.node.endpoint if self.node is not None else None

    # ------------------------------------------------------------ averaging

    def attach_matchmaking(self, prefix: str, bandwidth: float = 100.0,
                           target_group_size: int = 16,
                           averaging_expiration: float = 5.0) -> Matchmaking:
        """Attach the production matchmaker on the peer's existing RPC
        server/client (the averager's group-formation surface — the part of
        averaging that has to scale with the swarm)."""
        self.matchmaking = Matchmaking(
            node=self.node,
            client=self.node.client,
            server=self.node.server,
            prefix=prefix,
            peer_id=self.node.node_id.to_bytes(),
            endpoint=self.endpoint,
            bandwidth=bandwidth,
            target_group_size=target_group_size,
            averaging_expiration=averaging_expiration,
            telemetry_registry=self.telemetry,
        )
        return self.matchmaking

    # ---------------------------------------------------------- checkpoints

    def serve_checkpoint(self, step: int, total_size: int = 4096,
                         shard_size: int = 1024, variant: int = 0) -> bytes:
        """Become a checkpoint provider: serve ``ckpt.manifest`` /
        ``ckpt.shard`` (the averager's wire contract, byte-compatible with
        ``checkpointing/fetcher.py``) for a synthetic checkpoint. Returns
        the manifest digest for the announcement."""
        manifest, flat = _synthetic_checkpoint(
            step, total_size, shard_size, variant
        )
        self._checkpoint = (manifest, flat)

        async def _manifest(_peer, _args):
            return {"manifest": manifest.to_bytes()}

        async def _shard(_peer, args):
            index = int(args["index"])
            raw = shard_bytes(flat, manifest, index)
            return {
                "index": index,
                "data": serialize_array(
                    np.frombuffer(raw, dtype=np.float32),
                    CompressionType.NONE,
                ),
            }

        self.node.server.register("ckpt.manifest", _manifest)
        self.node.server.register("ckpt.shard", _shard)
        return manifest.digest()

    async def announce_checkpoint(self, prefix: str,
                                  expiration: float = 120.0) -> bool:
        """Publish this provider's catalog record (schema-checked by any
        validating node, same as production announcements)."""
        manifest, _flat = self._checkpoint
        ann = CheckpointAnnouncement(
            step=manifest.step,
            manifest_digest=manifest.digest(),
            num_shards=manifest.num_shards,
            endpoint=list(self.endpoint),
            shards=None,
        )
        return await self.node.store(
            catalog_key(prefix).encode(),
            ann.model_dump(),
            get_dht_time() + expiration,
            subkey=self.label.encode(),
        )


class SimSwarm:
    """A population of SimPeers over one SimNetwork. All coroutines must run
    inside the simulator engine (or any asyncio loop — then in real time).

    ``bucket_size``/``num_replicas``/``parallel_rpc`` default smaller than
    production: a 1,000-node scenario's wall cost is dominated by lookup
    fan-out, and the sizing report measures how these knobs trade off.
    """

    def __init__(
        self,
        network: SimNetwork,
        seed: int = 0,
        bucket_size: int = 8,
        num_replicas: int = 5,
        parallel_rpc: int = 3,
        request_timeout: float = 5.0,
        record_validators=(),
        warm_spawn: bool = False,
    ):
        self.network = network
        self.seed = int(seed)
        self.bucket_size = bucket_size
        self.num_replicas = num_replicas
        self.parallel_rpc = parallel_rpc
        self.request_timeout = request_timeout
        self.record_validators = record_validators
        # warm_spawn: hydrate new peers by INJECTING routing-table contacts
        # from the swarm's known deterministic topology instead of paying
        # per-peer bootstrap RPCs (ping fanout + self-lookup). The injected
        # table approximates a CONVERGED Kademlia network — k sorted-order
        # neighbors (the deep buckets) plus one contact per XOR-distance
        # level (the shallow ones) — which is what bootstrap-plus-
        # maintenance converges to anyway. Scenario campaigns opt in; unit
        # tests keep the eager RPC path so the bootstrap protocol itself
        # stays covered.
        self.warm_spawn = bool(warm_spawn)
        self.peers: List[SimPeer] = []
        # peers that are alive AND listening, in spawn order — maintained
        # incrementally so bootstrap-seed selection is O(fanout), not a
        # rescan of the whole roster per joining peer (O(n^2) at 1k peers)
        self._live_listening: List[SimPeer] = []

    # -------------------------------------------------------------- spawn

    def _node_id(self, index: int) -> DHTID:
        # deterministic ids: same seed => same Kademlia topology
        return DHTID.of_key(f"sim-swarm-{self.seed}-peer-{index}")

    async def spawn(
        self,
        n: int,
        bootstrap_fanout: int = 2,
        client_mode: bool = False,
        maintenance_interval: float = 0.0,
        warm: Optional[bool] = None,
    ) -> List[SimPeer]:
        """Create ``n`` peers. On the eager path each bootstraps off up to
        ``bootstrap_fanout`` already-live peers (deterministically chosen)
        with real ping + self-lookup RPCs; on the warm path (``warm=True``
        or the swarm's ``warm_spawn`` default) routing tables are injected
        directly from the known topology and no bootstrap traffic happens.
        Background maintenance defaults OFF — scenarios drive
        ``run_maintenance`` explicitly so every run replays identically."""
        warm = self.warm_spawn if warm is None else bool(warm)
        created: List[SimPeer] = []
        for i in range(n):
            index = len(self.peers)
            label = f"peer-{index:04d}"
            peer = SimPeer(index, label, host=label)
            seeds = () if warm else self._bootstrap_endpoints(
                index, bootstrap_fanout
            )
            await self._create_node(
                peer, seeds, client_mode, maintenance_interval
            )
            self.peers.append(peer)
            created.append(peer)
        if warm:
            # fill AFTER the whole batch is listening so the batch is
            # mutually visible (like a settled network), not a join chain
            self._warm_fill(created)
        return created

    async def _create_node(
        self, peer: SimPeer, seeds, client_mode: bool,
        maintenance_interval: float,
    ) -> None:
        peer.node = await DHTNode.create(
            listen_host=peer.host,
            initial_peers=seeds,
            node_id=self._node_id(peer.index),
            bucket_size=self.bucket_size,
            num_replicas=self.num_replicas,
            parallel_rpc=self.parallel_rpc,
            request_timeout=self.request_timeout,
            record_validators=[v() if callable(v) else v
                               for v in self.record_validators],
            client_mode=client_mode,
            advertised_host=peer.host,
            maintenance_interval=maintenance_interval,
            transport=self.network.transport(peer.host),
            telemetry_registry=peer.telemetry,
        )
        peer.alive = True
        if peer.endpoint is not None:
            self._live_listening.append(peer)

    # ------------------------------------------------------ lazy hydration

    def spawn_shells(self, n: int) -> List[SimPeer]:
        """Reserve ``n`` roster slots as cheap OFFLINE shells: deterministic
        index/label/host, no DHT node, no telemetry, no sockets. A shell
        costs a few object headers; ``hydrate`` brings one online when it
        is first touched. This is how the 10k-peer diurnal scenario affords
        a planet-size roster of which only a time-of-day wave is live."""
        created: List[SimPeer] = []
        for _ in range(n):
            index = len(self.peers)
            label = f"peer-{index:04d}"
            peer = SimPeer(index, label, host=label)
            self.peers.append(peer)
            created.append(peer)
        return created

    async def hydrate(
        self,
        peer: SimPeer,
        maintenance_interval: float = 0.0,
        warm: Optional[bool] = None,
    ) -> SimPeer:
        """Bring a shell — or a previously killed peer rejoining under the
        same identity — online. Idempotent for live peers. The node id is
        the peer's deterministic identity, so a rejoin reclaims its old
        place in the keyspace (its stored records died with it; its id did
        not)."""
        await self.hydrate_batch([peer], maintenance_interval, warm)
        return peer

    async def hydrate_batch(
        self,
        peers: Sequence[SimPeer],
        maintenance_interval: float = 0.0,
        warm: Optional[bool] = None,
    ) -> List[SimPeer]:
        """Hydrate a whole wave at once: the warm fill then sorts the live
        roster ONCE for the batch instead of once per peer — the diurnal
        scenario brings thousands online per virtual hour through this."""
        warm = self.warm_spawn if warm is None else bool(warm)
        fresh: List[SimPeer] = []
        for peer in peers:
            if peer.alive and peer.node is not None:
                continue
            seeds = () if warm else self._bootstrap_endpoints(peer.index, 2)
            await self._create_node(
                peer, seeds, client_mode=False,
                maintenance_interval=maintenance_interval,
            )
            fresh.append(peer)
        if warm and fresh:
            self._warm_fill(fresh)
        return fresh

    def _warm_fill(self, created: Sequence[SimPeer]) -> None:
        """Inject each created peer's routing table directly instead of
        bootstrapping over RPC. Contacts chosen to match what a CONVERGED
        table looks like: one peer per populated XOR-distance level (the
        shallow buckets — each halves the remaining lookup distance) plus
        ``bucket_size`` sorted-order neighbors on each side (adjacent ids
        share the longest prefixes, i.e. they are the deep buckets).
        Everything is derived from (seed, peer index), so two same-seed
        runs inject identical tables. Existing peers do NOT learn the
        newcomers here — exactly like a real join, they discover them when
        the newcomers first send traffic (``_register_sender``)."""
        roster = sorted(
            (int(p.node.node_id), p) for p in self._live_listening
        )
        ids = [node_id for node_id, _ in roster]
        if len(ids) <= 1:
            return
        k = self.bucket_size
        for peer in created:
            table = peer.node.routing_table
            own = int(peer.node.node_id)
            pos = bisect_left(ids, own)
            h = int.from_bytes(
                hashlib.sha256(
                    f"{self.seed}:warm:{peer.index}".encode()
                ).digest()[:8],
                "big",
            )
            # far-to-near: one contact from each non-empty sibling subtree
            # along our id's prefix path. The sibling subtree at depth L is
            # a CONTIGUOUS range of the sorted id list, so each level is
            # two bisects; levels go empty for good once subtrees shrink
            # below the roster's resolution, so bail after a run of them.
            empty_streak = 0
            for level in range(ID_BITS):
                shift = ID_BITS - 1 - level
                lo = (own ^ (1 << shift)) >> shift << shift
                i0 = bisect_left(ids, lo)
                i1 = bisect_left(ids, lo + (1 << shift))
                if i1 <= i0:
                    empty_streak += 1
                    if empty_streak >= 8:
                        break
                    continue
                empty_streak = 0
                _nid, contact = roster[i0 + (h + level * 7919) % (i1 - i0)]
                if contact is not peer:
                    table.add_or_update_node(
                        NodeInfo(contact.node.node_id, contact.endpoint)
                    )
            for j in range(max(0, pos - k), min(len(ids), pos + k + 1)):
                _nid, contact = roster[j]
                if contact is not peer:
                    table.add_or_update_node(
                        NodeInfo(contact.node.node_id, contact.endpoint)
                    )

    def _bootstrap_endpoints(self, index: int, fanout: int) -> List:
        alive = self._live_listening
        if not alive or fanout <= 0:
            return []
        # deterministic spread WITHOUT consuming shared RNG state: stride
        # through the live set by a hash of the joiner's index
        picks = []
        h = int.from_bytes(
            hashlib.sha256(f"{self.seed}:{index}".encode()).digest()[:8],
            "big",
        )
        for k in range(min(fanout, len(alive))):
            picks.append(alive[(h + k * 7919) % len(alive)].endpoint)
        return list(dict.fromkeys(picks))

    # -------------------------------------------------------------- churn

    async def kill(self, peer: SimPeer) -> None:
        """Process-death: sockets reset, listeners vanish, nothing graceful
        (the FaultSchedule ``drop`` contract, swarm-scale)."""
        if not peer.alive:
            return
        peer.alive = False
        try:
            self._live_listening.remove(peer)
        except ValueError:
            pass  # client-mode peer — was never listening
        self.network.kill_host(peer.host)
        if peer.node is not None:
            await peer.node.shutdown()

    def alive_peers(self) -> List[SimPeer]:
        return [p for p in self.peers if p.alive]

    async def shutdown(self) -> None:
        for peer in self.alive_peers():
            peer.alive = False
            self.network.kill_host(peer.host)
            await peer.node.shutdown()
        self._live_listening.clear()

    # ---------------------------------------------------------- telemetry

    def dump_event_logs(self, out_dir: str) -> List[str]:
        """Write each peer's in-memory event trace to
        ``<out_dir>/<label>.jsonl`` — the exact per-peer JSONL schema the
        observability tools consume (``runlog_summary --health/--trace/
        --topology``). Sequential open/write/close: a 1,000-peer swarm
        must not hold 1,000 descriptors."""
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        for peer in self.peers:
            if peer._telemetry is None:
                continue  # never hydrated — nothing was ever recorded
            links = peer.telemetry._links
            if links is not None:
                # the link.stats flush production peers do on snapshot /
                # close — without it ``--topology`` has nothing to read
                links.emit_events(peer.telemetry)
                peer.telemetry._links = None  # flush once, even if re-dumped
            if not peer.telemetry.events:
                continue
            if len(peer.telemetry.events) == peer.telemetry.events.maxlen:
                # full deque = almost certainly evicted its head: the
                # dumped log is a TAIL, and --trace on early rounds will
                # report orphans — say so instead of degrading silently
                logger.warning(
                    f"{peer.label}: event trace hit its in-memory bound "
                    f"({peer.telemetry.events.maxlen}); dumped log is "
                    "truncated at the front (raise SimPeer.MAX_EVENTS)"
                )
            path = os.path.join(out_dir, f"{peer.label}.jsonl")
            with open(path, "w", encoding="utf-8") as f:
                for record in peer.telemetry.events:
                    f.write(json.dumps(record) + "\n")
            paths.append(path)
        return paths

    def event_sequence(
        self, drop_keys: Sequence[str] = ("t", "dur_s", "span", "parent"),
    ) -> List[Dict[str, Any]]:
        """The swarm's telemetry events, per peer in spawn order, with the
        wall-dependent / randomly-identified fields stripped — the
        determinism fingerprint two same-seed runs must agree on."""
        out: List[Dict[str, Any]] = []
        for peer in self.peers:
            if peer._telemetry is None:
                continue  # unhydrated shell — no events by construction
            for record in peer.telemetry.events:
                out.append(
                    {k: v for k, v in record.items() if k not in drop_keys}
                )
        return out

    def counters_total(self, name: str) -> float:
        return sum(
            p._telemetry.counters[name].value
            for p in self.peers
            if p._telemetry is not None and name in p._telemetry.counters
        )
