"""RSA keys for signed DHT records.

Capability parity with hivemind.dht.crypto's RSASignatureValidator keys used
at albert/metrics_utils.py:21-24 and the local_public_key the trainers seed
their shuffling with (albert/run_trainer.py:266-270).
"""
from __future__ import annotations

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import padding, rsa

_PADDING = padding.PSS(
    mgf=padding.MGF1(hashes.SHA256()), salt_length=padding.PSS.DIGEST_LENGTH
)


class RSAPrivateKey:
    def __init__(self, key: rsa.RSAPrivateKey | None = None):
        self._key = key or rsa.generate_private_key(
            public_exponent=65537, key_size=2048
        )

    def sign(self, data: bytes) -> bytes:
        return self._key.sign(data, _PADDING, hashes.SHA256())

    def public_bytes(self) -> bytes:
        return self._key.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )

    def to_bytes(self) -> bytes:
        return self._key.private_bytes(
            serialization.Encoding.DER,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "RSAPrivateKey":
        return cls(serialization.load_der_private_key(data, password=None))


def verify_signature(public_key_bytes: bytes, data: bytes, signature: bytes) -> bool:
    try:
        pub = serialization.load_der_public_key(public_key_bytes)
        pub.verify(signature, data, _PADDING, hashes.SHA256())
        return True
    except (InvalidSignature, ValueError, TypeError):
        return False
