"""RSA keys for signed DHT records.

Capability parity with hivemind.dht.crypto's RSASignatureValidator keys used
at albert/metrics_utils.py:21-24 and the local_public_key the trainers seed
their shuffling with (albert/run_trainer.py:266-270).

Dependency gate: ``cryptography`` is the load-bearing implementation
(RSA-PSS). Some CI/dev containers ship without the wheel and have no
network to fetch it; rather than taking the whole DHT stack down with an
ImportError, this module degrades to a clearly-labelled, structurally
faithful stand-in (key identity, sign/verify pairing, tamper and
wrong-key rejection) that is NOT cryptographically secure — a signature
reveals the signing seed, so anyone who has SEEN one can forge. A loud
warning is emitted once at import; production deployments must install
``cryptography``.
"""
from __future__ import annotations

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # gated: see module docstring
    HAVE_CRYPTOGRAPHY = False

if HAVE_CRYPTOGRAPHY:
    _PADDING = padding.PSS(
        mgf=padding.MGF1(hashes.SHA256()),
        salt_length=padding.PSS.DIGEST_LENGTH,
    )

    class RSAPrivateKey:
        def __init__(self, key: rsa.RSAPrivateKey | None = None):
            self._key = key or rsa.generate_private_key(
                public_exponent=65537, key_size=2048
            )

        def sign(self, data: bytes) -> bytes:
            return self._key.sign(data, _PADDING, hashes.SHA256())

        def public_bytes(self) -> bytes:
            return self._key.public_key().public_bytes(
                serialization.Encoding.DER,
                serialization.PublicFormat.SubjectPublicKeyInfo,
            )

        def to_bytes(self) -> bytes:
            return self._key.private_bytes(
                serialization.Encoding.DER,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )

        @classmethod
        def from_bytes(cls, data: bytes) -> "RSAPrivateKey":
            return cls(serialization.load_der_private_key(data, password=None))

    def verify_signature(
        public_key_bytes: bytes, data: bytes, signature: bytes
    ) -> bool:
        try:
            pub = serialization.load_der_public_key(public_key_bytes)
            pub.verify(signature, data, _PADDING, hashes.SHA256())
            return True
        except (InvalidSignature, ValueError, TypeError):
            return False

else:
    import hashlib
    import hmac as _hmac
    import os

    from dedloc_tpu.utils.logging import get_logger

    get_logger(__name__).warning(
        "the 'cryptography' package is unavailable — DHT record signing is "
        "running on an INSECURE structural stub (signatures reveal the "
        "signing seed). Fine for offline tests; install 'cryptography' for "
        "any real deployment."
    )

    _STUB_MAGIC = b"DEDLOC-STUB-KEY:"

    class RSAPrivateKey:  # type: ignore[no-redef]
        """Structural stand-in: a 32-byte seed is the private key, its
        sha256 is the public identity, a signature is (seed, mac) so
        verification needs only the public bytes. Preserves the semantics
        tests rely on (wrong key / tampered payload => verify fails), NOT
        unforgeability."""

        def __init__(self, key: bytes | None = None):
            self._seed = key if key is not None else os.urandom(32)

        def sign(self, data: bytes) -> bytes:
            mac = hashlib.sha256(self._seed + data).digest()
            return _STUB_MAGIC + self._seed + mac

        def public_bytes(self) -> bytes:
            return _STUB_MAGIC + hashlib.sha256(self._seed).digest()

        def to_bytes(self) -> bytes:
            return self._seed

        @classmethod
        def from_bytes(cls, data: bytes) -> "RSAPrivateKey":
            return cls(data)

    def verify_signature(  # type: ignore[no-redef]
        public_key_bytes: bytes, data: bytes, signature: bytes
    ) -> bool:
        if not (
            isinstance(signature, bytes)
            and isinstance(public_key_bytes, bytes)
            and signature.startswith(_STUB_MAGIC)
            and public_key_bytes.startswith(_STUB_MAGIC)
        ):
            return False
        body = signature[len(_STUB_MAGIC):]
        seed, mac = body[:32], body[32:]
        if hashlib.sha256(seed).digest() != public_key_bytes[len(_STUB_MAGIC):]:
            return False  # signed by a different key than claimed
        return _hmac.compare_digest(
            hashlib.sha256(seed + data).digest(), mac
        )
