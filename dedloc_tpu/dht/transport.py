"""The transport seam: what RPCServer/RPCClient actually need from a wire.

``dht/protocol.py`` used to call ``asyncio.start_server`` /
``asyncio.open_connection`` directly, which welded every subsystem above it
(DHT routing, matchmaking, averaging, checkpoint fetching, relay/NAT paths)
to real TCP sockets — and therefore welded every scaling claim to however
many real processes a test box can run. This module names the five-capability
surface the RPC layer really uses:

- **connect** to an endpoint -> a (reader, writer) stream pair
- **accept**: listen on (host, port) and invoke a callback per inbound pair
- **framed send/recv**: ``StreamReader.readexactly`` + ``writer.write/drain``
  (the framing itself — length prefix + msgpack — lives in ``protocol.py``
  and is shared by every transport, so frames are byte-identical on all of
  them BY CONSTRUCTION; ``tests/test_simulator.py`` asserts it anyway with a
  ``RecordingTransport``)
- **close**: writer close / listener close
- **peer endpoint identity**: ``writer.get_extra_info("peername")``

``TcpTransport`` is the production implementation — the exact asyncio calls
``protocol.py`` made before the seam existed, so the real wire path is
unchanged. ``simulator/network.py`` provides the in-process simulated
implementation (latency/bandwidth/loss models on a virtual clock). Anything
above the seam runs unmodified on either.
"""
from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Callable, List, Optional, Tuple

Endpoint = Tuple[str, int]
ConnectionCallback = Callable[
    [asyncio.StreamReader, Any], Awaitable[None]
]


class Listener:
    """A bound, accepting endpoint. ``port`` is the REAL bound port (the
    requested one, or the OS/network-assigned one when 0 was requested)."""

    port: int

    def close(self) -> None:  # pragma: no cover — interface
        raise NotImplementedError

    async def wait_closed(self) -> None:  # pragma: no cover — interface
        raise NotImplementedError


class Transport:
    """Factory for connections and listeners. One instance may serve many
    RPCServer/RPCClient objects (TCP does); simulated transports are
    per-peer so the network knows who is sending (uplink contention,
    peername identity)."""

    async def start_server(
        self, host: str, port: int, on_connection: ConnectionCallback
    ) -> Listener:  # pragma: no cover — interface
        raise NotImplementedError

    async def open_connection(
        self, endpoint: Endpoint, timeout: float
    ) -> Tuple[asyncio.StreamReader, Any]:  # pragma: no cover — interface
        raise NotImplementedError


class _TcpListener(Listener):
    def __init__(self, server: asyncio.AbstractServer):
        self._server = server
        self.port = server.sockets[0].getsockname()[1]

    def close(self) -> None:
        self._server.close()

    async def wait_closed(self) -> None:
        await self._server.wait_closed()


class TcpTransport(Transport):
    """Real asyncio TCP — byte-for-byte the pre-seam behavior."""

    async def start_server(
        self, host: str, port: int, on_connection: ConnectionCallback
    ) -> Listener:
        server = await asyncio.start_server(on_connection, host, port)
        return _TcpListener(server)

    async def open_connection(
        self, endpoint: Endpoint, timeout: float
    ) -> Tuple[asyncio.StreamReader, Any]:
        return await asyncio.wait_for(
            asyncio.open_connection(*endpoint), timeout=timeout
        )


# the process default: production code that never mentions transports keeps
# getting real TCP (one stateless instance is safe to share — it holds no
# connection state; RPCServer/RPCClient own their sockets)
TCP = TcpTransport()


def resolve(transport: Optional[Transport]) -> Transport:
    return transport if transport is not None else TCP


class _RecordingWriter:
    """Write-through proxy that mirrors every byte into a capture list.
    Proxies the handful of writer attributes the RPC layer touches."""

    def __init__(self, inner: Any, sink: List[bytes]):
        self._inner = inner
        self.sent = sink

    def write(self, data: bytes) -> None:
        self.sent.append(bytes(data))
        self._inner.write(data)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


class RecordingTransport(Transport):
    """Wrap any transport and capture the exact bytes written on every
    connection it opens or accepts — the framing-parity harness
    (docs/simulator.md): run the same RPC exchange over real TCP and over
    the simulated network and assert the captured frames are identical,
    byte for byte, including the trace-context field and the
    telemetry-disabled framing."""

    def __init__(self, inner: Transport):
        self.inner = inner
        self.client_frames: List[bytes] = []  # bytes written by connectors
        self.server_frames: List[bytes] = []  # bytes written by acceptors

    async def start_server(
        self, host: str, port: int, on_connection: ConnectionCallback
    ) -> Listener:
        async def wrapped(reader, writer):
            await on_connection(
                reader, _RecordingWriter(writer, self.server_frames)
            )

        return await self.inner.start_server(host, port, wrapped)

    async def open_connection(
        self, endpoint: Endpoint, timeout: float
    ) -> Tuple[asyncio.StreamReader, Any]:
        reader, writer = await self.inner.open_connection(endpoint, timeout)
        return reader, _RecordingWriter(writer, self.client_frames)
