"""Local DHT record store: expiration times + dictionary subkeys.

Reproduces the record semantics the reference depends on (SURVEY.md §2.6,
§5 failure-detection): every record carries an absolute ``expiration_time``
(liveness via expiration, not heartbeats); a key may hold either a plain
value or a dictionary of subkeys (per-peer metrics under
``{prefix}_metrics``/public-key subkeys, albert/run_trainer.py:160-166);
newer expiration wins on conflict.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple, Union

from dedloc_tpu.core.timeutils import DHTExpiration, ValueWithExpiration, get_dht_time

Subkey = Union[str, bytes]
BinaryValue = bytes

_NO_SUBKEY = object()


class DictionaryDHTValue:
    """A value made of independently-expiring subkey entries."""

    def __init__(self):
        self.data: Dict[Subkey, ValueWithExpiration[BinaryValue]] = {}

    @property
    def latest_expiration_time(self) -> DHTExpiration:
        return max(
            (e.expiration_time for e in self.data.values()), default=-float("inf")
        )

    def store(
        self, subkey: Subkey, value: BinaryValue, expiration_time: DHTExpiration
    ) -> bool:
        prev = self.data.get(subkey)
        if prev is not None and prev.expiration_time >= expiration_time:
            return False
        self.data[subkey] = ValueWithExpiration(value, expiration_time)
        return True

    def items(self) -> Iterator[Tuple[Subkey, ValueWithExpiration[BinaryValue]]]:
        return iter(self.data.items())

    def __len__(self):
        return len(self.data)


StoredValue = Union[BinaryValue, DictionaryDHTValue]


class DHTLocalStorage:
    def __init__(self, maxsize: int = 10000):
        self.maxsize = maxsize
        self._data: Dict[bytes, ValueWithExpiration[StoredValue]] = {}

    def store(
        self,
        key: bytes,
        value: BinaryValue,
        expiration_time: DHTExpiration,
        subkey=_NO_SUBKEY,
    ) -> bool:
        """Store a record; newer expiration wins. Returns True if stored."""
        if expiration_time <= get_dht_time():
            return False
        self._evict_expired()
        existing = self._data.get(key)
        if subkey is not _NO_SUBKEY:
            if existing is None or not isinstance(existing.value, DictionaryDHTValue):
                # an existing plain value is superseded only by a newer record
                if existing is not None and existing.expiration_time >= expiration_time:
                    return False
                self._data[key] = ValueWithExpiration(
                    DictionaryDHTValue(), expiration_time
                )
                existing = self._data[key]
            dictval = existing.value
            ok = dictval.store(subkey, value, expiration_time)
            if ok:
                self._data[key] = ValueWithExpiration(
                    dictval, dictval.latest_expiration_time
                )
            return ok
        if existing is not None and existing.expiration_time >= expiration_time:
            return False
        self._data[key] = ValueWithExpiration(value, expiration_time)
        return True

    def get(self, key: bytes) -> Optional[ValueWithExpiration[StoredValue]]:
        entry = self._data.get(key)
        if entry is None:
            return None
        if entry.expired():
            if isinstance(entry.value, DictionaryDHTValue):
                # drop only expired subkeys; the dict may still be alive
                entry.value.data = {
                    sk: v for sk, v in entry.value.data.items() if not v.expired()
                }
                if entry.value.data:
                    return ValueWithExpiration(
                        entry.value, entry.value.latest_expiration_time
                    )
            del self._data[key]
            return None
        if isinstance(entry.value, DictionaryDHTValue):
            entry.value.data = {
                sk: v for sk, v in entry.value.data.items() if not v.expired()
            }
        return entry

    def _evict_expired(self) -> None:
        if len(self._data) < self.maxsize:
            return
        now = get_dht_time()
        self._data = {
            k: v for k, v in self._data.items() if v.expiration_time > now
        }
        while len(self._data) >= self.maxsize:
            # drop the soonest-to-expire record
            victim = min(self._data, key=lambda k: self._data[k].expiration_time)
            del self._data[victim]

    def items(self):
        return self._data.items()

    def keys(self):
        return list(self._data.keys())

    def __len__(self):
        return len(self._data)
