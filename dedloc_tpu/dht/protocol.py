"""asyncio TCP transport + msgpack RPC framing for the DHT and averager.

This is the in-tree replacement for the reference's transport dependencies
(libp2p daemon + gRPC, SURVEY.md §2.7): length-prefixed msgpack frames over
TCP with a small request/response RPC layer. NAT traversal and relays are
descoped for datacenter TPU fleets, but the seam is this module — a future
transport only needs to provide ``call`` and ``serve``.
"""
from __future__ import annotations

import asyncio
import struct
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from dedloc_tpu.core.serialization import pack_obj, unpack_obj
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

Endpoint = Tuple[str, int]
MAX_FRAME = 512 * 1024 * 1024  # tensors ride this transport too
_LEN = struct.Struct("!I")


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    payload = await reader.readexactly(length)
    return unpack_obj(payload)


def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    payload = pack_obj(obj)
    writer.write(_LEN.pack(len(payload)))
    writer.write(payload)


Handler = Callable[[Endpoint, Dict[str, Any]], Awaitable[Any]]


class RPCServer:
    """Serves named RPC methods; one task per connection, many requests per
    connection (pipelined)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self.host, self.requested_port = host, port
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()
        self.port: Optional[int] = None

    def register(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # force-close live connections: in py3.12 wait_closed() waits for
            # all handlers, which would otherwise hang on idle peers
            for writer in list(self._writers):
                writer.close()
            await self._server.wait_closed()

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername") or ("?", 0)
        self._writers.add(writer)
        try:
            while True:
                try:
                    msg = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
                    return
                asyncio.ensure_future(self._dispatch(peer, msg, writer))
        finally:
            self._writers.discard(writer)
            writer.close()

    async def _dispatch(self, peer, msg, writer) -> None:
        req_id = msg.get("id")
        method = msg.get("method")
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise KeyError(f"unknown method {method!r}")
            result = await handler(tuple(peer[:2]), msg.get("args") or {})
            reply = {"id": req_id, "ok": True, "result": result}
        except Exception as e:  # noqa: BLE001 — RPC boundary
            logger.debug(f"rpc {method} failed: {e!r}")
            reply = {"id": req_id, "ok": False, "error": repr(e)}
        try:
            write_frame(writer, reply)
            await writer.drain()
        except (ConnectionResetError, RuntimeError, BrokenPipeError):
            pass


class RPCClient:
    """Pooled msgpack-RPC client: one persistent connection per endpoint."""

    def __init__(self, request_timeout: float = 5.0):
        self.request_timeout = request_timeout
        self._conns: Dict[Endpoint, Tuple[asyncio.StreamReader, asyncio.StreamWriter]] = {}
        self._pending: Dict[Endpoint, Dict[int, asyncio.Future]] = {}
        self._readers: Dict[Endpoint, asyncio.Task] = {}
        self._next_id = 0
        self._conn_locks: Dict[Endpoint, asyncio.Lock] = {}

    async def _connect(self, endpoint: Endpoint):
        lock = self._conn_locks.setdefault(endpoint, asyncio.Lock())
        async with lock:
            if endpoint in self._conns:
                return self._conns[endpoint]
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(*endpoint), timeout=self.request_timeout
            )
            self._conns[endpoint] = (reader, writer)
            self._pending[endpoint] = {}
            self._readers[endpoint] = asyncio.ensure_future(
                self._read_loop(endpoint, reader)
            )
            return reader, writer

    async def _read_loop(self, endpoint: Endpoint, reader: asyncio.StreamReader):
        try:
            while True:
                msg = await read_frame(reader)
                fut = self._pending.get(endpoint, {}).pop(msg.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._drop(endpoint, ConnectionResetError("connection lost"))

    def _drop(self, endpoint: Endpoint, exc: Exception) -> None:
        conn = self._conns.pop(endpoint, None)
        if conn is not None:
            conn[1].close()
        task = self._readers.pop(endpoint, None)
        if task is not None:
            task.cancel()
        for fut in self._pending.pop(endpoint, {}).values():
            if not fut.done():
                fut.set_exception(exc)

    async def call(
        self,
        endpoint: Endpoint,
        method: str,
        args: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
    ) -> Any:
        """Invoke a remote method; raises on transport error / remote error."""
        endpoint = (endpoint[0], int(endpoint[1]))
        _, writer = await self._connect(endpoint)
        self._next_id += 1
        req_id = self._next_id
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[endpoint][req_id] = fut
        write_frame(writer, {"id": req_id, "method": method, "args": args or {}})
        try:
            await writer.drain()
            reply = await asyncio.wait_for(
                fut, timeout=timeout or self.request_timeout
            )
        except (asyncio.TimeoutError, ConnectionError, OSError):
            self._pending.get(endpoint, {}).pop(req_id, None)
            raise
        if not reply.get("ok"):
            raise RPCError(reply.get("error", "unknown remote error"))
        return reply.get("result")

    async def close(self) -> None:
        for endpoint in list(self._conns):
            self._drop(endpoint, ConnectionResetError("client closed"))


class RPCError(Exception):
    pass
