"""asyncio TCP transport + msgpack RPC framing for the DHT and averager.

This is the in-tree replacement for the reference's transport dependencies
(libp2p daemon + gRPC, SURVEY.md §2.7): length-prefixed msgpack frames over
TCP with a small request/response RPC layer.

Circuit relay (the libp2p relay capability, p2p/circuit-relay.md:15-68): a
peer that cannot listen publicly opens an OUTBOUND connection to a public
peer's ``RelayService`` and registers; the connection then becomes
bidirectional — relayed requests arrive on it as frames with a ``method``
field and are dispatched against the client's ``reverse_handlers``. Anyone
can then reach the private peer at the virtual endpoint
``("relay:<host>:<port>:<peer_hex>", 0)``: ``RPCClient.call`` resolves the
form by preferring a DIRECT path — an adopted hole-punched connection or a
reversal route (dht/nat.py NatTraversal) — and only falls back to wrapping
the call in ``relay.call`` to the public peer, which pipes it down the
registered connection and relays the reply back. At steady state the relay
carries handshakes, not tensor bytes.
"""
from __future__ import annotations

import asyncio
import contextlib
import socket
import struct
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from dedloc_tpu.core.serialization import pack_obj, unpack_obj
from dedloc_tpu.dht import transport as transport_mod
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.testing import faults
from dedloc_tpu.utils.aio import keep_task
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

Endpoint = Tuple[str, int]
MAX_FRAME = 512 * 1024 * 1024  # tensors ride this transport too
_LEN = struct.Struct("!I")


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    payload = await reader.readexactly(length)
    if telemetry._active is not None:  # process-wide wire accounting
        telemetry._active.counter("net.bytes_in").inc(_LEN.size + length)
    return unpack_obj(payload)


def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    payload = pack_obj(obj)
    writer.write(_LEN.pack(len(payload)))
    writer.write(payload)
    if telemetry._active is not None:  # process-wide wire accounting
        telemetry._active.counter("net.bytes_out").inc(
            _LEN.size + len(payload)
        )


def _expire_response(fut: "asyncio.Future") -> None:
    """Deadline callback for an in-flight RPC's response future."""
    if not fut.done():
        fut.set_exception(asyncio.TimeoutError("rpc response timed out"))


Handler = Callable[[Endpoint, Dict[str, Any]], Awaitable[Any]]


def trace_field(tele) -> Optional[list]:
    """The compact trace context a request frame carries: ``[trace_id,
    parent_span_id, caller_peer]``, or None when it must carry NOTHING.

    None — and therefore zero extra bytes on the wire framing — whenever
    telemetry is disabled (``tele is None``) or no trace is live on this
    task. The receiving ``_dispatch`` adopts the context around the handler
    so server-side spans record their remote parent; a peer with telemetry
    off simply ignores the field."""
    if tele is None:
        return None
    tc = telemetry.current_trace()
    if tc is None:
        return None
    return [tc[0], tc[1], tele.peer]


# shared no-op: nullcontext is stateless and re-entrant, so the disabled
# path allocates nothing per dispatch
_NULL_CM = contextlib.nullcontext()


def _adopt_cm(tele, msg):
    """Context manager adopting a request frame's trace context (no-op when
    telemetry is off or the frame carries none)."""
    tc = msg.get("tc")
    if tele is None or tc is None:
        return _NULL_CM
    return telemetry.adopt_trace(tc)


def _set_nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on an RPC connection. The averaging wire path streams
    many mid-sized chunk frames in a request/reply pattern; with Nagle on,
    each frame can sit in the kernel waiting for the previous frame's ACK
    (up to a delayed-ACK period), which serializes the pipelined all-reduce
    on exactly the latency it exists to hide."""
    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover — non-TCP transports
            pass


def relay_endpoint(relay: Endpoint, peer_id: bytes) -> Endpoint:
    """Virtual endpoint for a peer reachable only via ``relay``."""
    return (f"relay:{relay[0]}:{relay[1]}:{peer_id.hex()}", 0)


def parse_relay_endpoint(endpoint) -> Optional[Tuple[Endpoint, str]]:
    """((relay_host, relay_port), peer_hex) if ``endpoint`` is relayed."""
    host = endpoint[0]
    if not (isinstance(host, str) and host.startswith("relay:")):
        return None
    _, rh, rp, peer_hex = host.split(":", 3)
    return (rh, int(rp)), peer_hex


class RPCServer:
    """Serves named RPC methods; one task per connection, many requests per
    connection (pipelined)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 telemetry_registry=None, transport=None):
        self.host, self.requested_port = host, port
        # per-peer scope for in-process multi-peer tests; None falls back to
        # the process-global registry (production: one peer per process)
        self.telemetry = telemetry_registry
        # the transport seam (dht/transport.py): None = real asyncio TCP,
        # exactly the pre-seam wire; the simulator injects its in-process
        # network here and everything above this line runs unmodified
        self.transport = transport_mod.resolve(transport)
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[transport_mod.Listener] = None
        self._writers: set = set()
        self.port: Optional[int] = None
        # server-initiated calls piped DOWN an inbound connection (circuit
        # relay forwarding, NAT reverse-connection routes): reply frames (no
        # "method") are matched by id and VALIDATED against the writer the
        # request went down — a reply arriving on any other connection
        # (i.e. from a different peer) is discarded, so a stranger cannot
        # forge results into someone else's call
        self._pending_calls: Dict[
            int, Tuple[asyncio.Future, asyncio.StreamWriter]
        ] = {}
        self._next_call_id = 0

    def register(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    async def call_over(
        self,
        writer: asyncio.StreamWriter,
        method: str,
        args: Optional[Dict[str, Any]] = None,
        timeout: float = 60.0,
    ) -> Any:
        """Invoke a method on the peer at the OTHER end of an inbound
        connection (the peer serves it via ``RPCClient.reverse_handlers``).
        This is how otherwise-unreachable peers are called back over the
        connections they parked with us."""
        self._next_call_id += 1
        rid = self._next_call_id
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending_calls[rid] = (fut, writer)
        request = {"id": rid, "method": method, "args": args or {}}
        # trace propagation survives the relay: the relay's _rpc_call runs
        # inside the ORIGINAL caller's adopted context, so the piped frame
        # re-carries it (absent — zero bytes — when telemetry is off)
        tc = trace_field(telemetry.resolve(self.telemetry))
        if tc is not None:
            request["tc"] = tc
        try:
            write_frame(writer, request)
            await writer.drain()
            reply = await asyncio.wait_for(fut, timeout=timeout)
        finally:
            self._pending_calls.pop(rid, None)
        if not reply.get("ok"):
            raise RPCError(reply.get("error", "unknown remote error"))
        return reply.get("result")

    def _route_reply(self, msg, writer) -> None:
        entry = self._pending_calls.get(msg.get("id"))
        if entry is None:
            return
        fut, expected_writer = entry
        if writer is not expected_writer:
            logger.warning(
                "discarding reply arriving on the wrong connection"
            )
            return
        self._pending_calls.pop(msg.get("id"), None)
        if not fut.done():
            fut.set_result(msg)

    async def start(self) -> None:
        self._server = await self.transport.start_server(
            self.host, self.requested_port, self._on_connection
        )
        self.port = self._server.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # force-close live connections: in py3.12 wait_closed() waits for
            # all handlers, which would otherwise hang on idle peers
            for writer in list(self._writers):
                writer.close()
            await self._server.wait_closed()

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername") or ("?", 0)
        _set_nodelay(writer)
        self._writers.add(writer)
        try:
            while True:
                try:
                    msg = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
                    return
                if msg.get("method") is None:
                    # reply to a call_over we piped down this connection
                    self._route_reply(msg, writer)
                    continue
                handler = self._handlers.get(msg.get("method"))
                if (
                    handler is not None
                    and getattr(handler, "rpc_inline", False)
                    and faults._active is None
                ):
                    # non-blocking handlers (marked ``rpc_inline``: they
                    # never await I/O) run inline — task-per-request costs
                    # a Task allocation and two context switches per RPC,
                    # which dominates a lookup-heavy simulation. With a
                    # fault schedule installed every request takes the
                    # task path so ``delay`` faults cannot head-of-line
                    # block an entire connection.
                    await self._dispatch(peer, msg, writer)
                    continue
                # retained + exception-logged (utils/aio): a handler
                # task dying silently would swallow the request forever
                keep_task(self._dispatch(peer, msg, writer),
                          name="rpc dispatch", log=logger)
        finally:
            self._writers.discard(writer)
            writer.close()

    async def _dispatch(self, peer, msg, writer) -> None:
        req_id = msg.get("id")
        method = msg.get("method")
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("rpc.server.requests").inc()
        if faults._active is not None:  # fault injection (testing/faults.py)
            fault = faults.fire(
                "rpc.server.dispatch", method=method, peer=peer, server=self,
                port=self.port,
            )
            if fault is not None:
                if tele is not None:
                    # attribute the APPLIED fault to this peer's registry
                    # (faults.fire also logs a process-global trace event)
                    tele.counter("faults.applied").inc()
                    tele.event(
                        "fault.applied", point="rpc.server.dispatch",
                        action=fault.action, method=method,
                    )
                try:
                    await faults.apply_transport_fault(fault, f"rpc {method}")
                except (ConnectionResetError, OSError):
                    # process-death semantics: reset the connection, no reply
                    writer.close()
                    return
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise KeyError(f"unknown method {method!r}")
            # adopt the caller's trace context (frame field "tc") around the
            # handler: spans opened inside record their REMOTE parent, which
            # is what lets the coordinator stitch per-peer event logs into
            # one causal cross-peer round trace
            with _adopt_cm(tele, msg):
                if getattr(handler, "rpc_wants_writer", False):
                    result = await handler(
                        tuple(peer[:2]), msg.get("args") or {}, writer
                    )
                else:
                    result = await handler(
                        tuple(peer[:2]), msg.get("args") or {}
                    )
            reply = {"id": req_id, "ok": True, "result": result}
        except Exception as e:  # noqa: BLE001 — RPC boundary
            logger.debug(f"rpc {method} failed: {e!r}")
            if tele is not None:
                tele.counter("rpc.server.errors").inc()
            reply = {"id": req_id, "ok": False, "error": repr(e)}
        try:
            write_frame(writer, reply)
            await writer.drain()
        except (OSError, RuntimeError):
            # best-effort reply: any transport-level failure (reset, broken
            # pipe, a simulated-link 'error' fault from drain) means the
            # caller is unreachable — drop the reply, never kill the task
            pass


class RPCClient:
    """Pooled msgpack-RPC client: one persistent connection per endpoint."""

    def __init__(self, request_timeout: float = 5.0, telemetry_registry=None,
                 transport=None):
        self.request_timeout = request_timeout
        # per-peer scope for in-process multi-peer tests; None falls back to
        # the process-global registry (production: one peer per process)
        self.telemetry = telemetry_registry
        # the transport seam (dht/transport.py): None = real asyncio TCP
        self.transport = transport_mod.resolve(transport)
        self._conns: Dict[Endpoint, Tuple[asyncio.StreamReader, asyncio.StreamWriter]] = {}
        self._pending: Dict[Endpoint, Dict[int, asyncio.Future]] = {}
        self._readers: Dict[Endpoint, asyncio.Task] = {}
        self._next_id = 0
        self._conn_locks: Dict[Endpoint, asyncio.Lock] = {}
        # circuit relay: requests relayed to THIS (otherwise unreachable)
        # peer arrive on its outbound relay connection and dispatch here —
        # point this at an RPCServer's handler dict to expose its methods
        self.reverse_handlers: Dict[str, Handler] = {}
        # NAT traversal policy (dht/nat.py NatTraversal attaches itself):
        # consulted before falling back to the relay for relay: endpoints
        self.nat = None

    async def _connect(self, endpoint: Endpoint):
        # fast path first: a pooled connection needs no lock (entries are
        # installed fully-formed), and ``setdefault`` with an eagerly-built
        # Lock() would allocate one per CALL, not one per endpoint
        conn = self._conns.get(endpoint)
        if conn is not None:
            return conn
        lock = self._conn_locks.get(endpoint)
        if lock is None:
            lock = self._conn_locks.setdefault(endpoint, asyncio.Lock())
        async with lock:
            if endpoint in self._conns:
                return self._conns[endpoint]
            # the LOOP's clock, not perf_counter: under the simulator
            # engine loop.time() IS the virtual clock, so the sampled RTT
            # reflects the MODELED link latency exactly — with none of the
            # event-loop scheduling churn a real-clock read would add on a
            # busy loop (noise that a twin fitted from this estimate would
            # then pay a second time on replay). In production loop.time()
            # is the ordinary monotonic clock.
            loop = asyncio.get_event_loop()
            t0 = loop.time()
            reader, writer = await self.transport.open_connection(
                endpoint, timeout=self.request_timeout
            )
            tele = telemetry.resolve(self.telemetry)
            if tele is not None:
                # the TCP handshake is a free SYN/SYN-ACK round trip: the
                # per-link RTT estimate's "piggybacked ping" (one sample per
                # pooled connection, zero traffic added to the hot path)
                tele.links().observe_rtt(
                    endpoint, max(0.0, loop.time() - t0)
                )
            _set_nodelay(writer)
            self._conns[endpoint] = (reader, writer)
            self._pending[endpoint] = {}
            self._readers[endpoint] = asyncio.ensure_future(
                self._read_loop(endpoint, reader)
            )
            return reader, writer

    async def _read_loop(self, endpoint: Endpoint, reader: asyncio.StreamReader):
        try:
            while True:
                msg = await read_frame(reader)
                if msg.get("method") is not None:
                    # relayed request piped to us down our own outbound
                    # connection (circuit relay): serve it and reply in-band
                    keep_task(self._dispatch_reverse(endpoint, msg),
                              name="reverse dispatch", log=logger)
                    continue
                fut = self._pending.get(endpoint, {}).pop(msg.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._drop(endpoint, ConnectionResetError("connection lost"))

    async def _dispatch_reverse(self, endpoint: Endpoint, msg) -> None:
        handler = self.reverse_handlers.get(msg.get("method"))
        try:
            if handler is None:
                raise KeyError(f"unknown relayed method {msg.get('method')!r}")
            with _adopt_cm(telemetry.resolve(self.telemetry), msg):
                result = await handler(endpoint, msg.get("args") or {})
            reply = {"id": msg.get("id"), "ok": True, "result": result}
        except Exception as e:  # noqa: BLE001 — RPC boundary
            logger.debug(f"relayed rpc {msg.get('method')} failed: {e!r}")
            reply = {"id": msg.get("id"), "ok": False, "error": repr(e)}
        conn = self._conns.get(endpoint)
        if conn is None:
            return
        try:
            write_frame(conn[1], reply)
            await conn[1].drain()
        except (OSError, RuntimeError):
            # best-effort reply: any transport-level failure (reset, broken
            # pipe, a simulated-link 'error' fault from drain) means the
            # caller is unreachable — drop the reply, never kill the task
            pass

    async def register_with_relay(
        self, relay: Endpoint, peer_id: bytes
    ) -> Endpoint:
        """Park this client's connection at a public peer's RelayService and
        return the virtual endpoint others can reach us at. The pooled
        connection stays open; ``reverse_handlers`` serve what arrives."""

        async def _probe(_peer, _args):
            # answered over the parked connection: proves to the relay that
            # this registration's path is still alive when a newcomer tries
            # to (re-)register the same peer id
            return {"alive": True}

        self.reverse_handlers.setdefault("relay.probe", _probe)
        await self.call(relay, "relay.register", {"peer_id": peer_id.hex()})
        return relay_endpoint(relay, peer_id)

    def adopt_connection(
        self,
        endpoint: Endpoint,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        """Install an externally-established connection (NAT punch) into the
        pool under ``endpoint`` — calls to that endpoint then ride it like
        any dialed connection, and inbound requests on it dispatch via
        ``reverse_handlers``."""
        self._conns[endpoint] = (reader, writer)
        self._pending[endpoint] = {}
        self._readers[endpoint] = asyncio.ensure_future(
            self._read_loop(endpoint, reader)
        )

    def _drop(self, endpoint: Endpoint, exc: Exception) -> None:
        conn = self._conns.pop(endpoint, None)
        if conn is not None:
            conn[1].close()
            tele = telemetry.resolve(self.telemetry)
            if tele is not None:
                tele.counter("rpc.conns_lost").inc()
                tele.event(
                    "rpc.conn_lost", endpoint=endpoint,
                    error=type(exc).__name__,
                )
        task = self._readers.pop(endpoint, None)
        if task is not None:
            task.cancel()
        for fut in self._pending.pop(endpoint, {}).values():
            if not fut.done():
                fut.set_exception(exc)

    async def call(
        self,
        endpoint: Endpoint,
        method: str,
        args: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
    ) -> Any:
        """Invoke a remote method; raises on transport error / remote error.

        A ``relay:`` endpoint is resolved in preference order: an adopted
        direct connection (NAT punch), a NAT upgrade attempt (connection
        reversal / hole punch, dht/nat.py), and finally a ``relay.call``
        wrapped to the public peer hosting the registration (circuit
        relay)."""
        tele = telemetry.resolve(self.telemetry)
        if faults._active is not None:  # fault injection (testing/faults.py)
            fault = faults.fire(
                "rpc.client.call", method=method, endpoint=endpoint,
                client=self,
            )
            if fault is not None:
                if tele is not None:
                    tele.counter("faults.applied").inc()
                    tele.event(
                        "fault.applied", point="rpc.client.call",
                        action=fault.action, method=method,
                        endpoint=endpoint,
                    )
                try:
                    await faults.apply_transport_fault(fault, f"rpc {method}")
                except Exception:
                    if tele is not None:
                        tele.counter("rpc.client.calls").inc()
                        tele.counter("rpc.client.failures").inc()
                    raise
        relayed = parse_relay_endpoint(endpoint)
        if relayed is not None:
            relay, peer_hex = relayed
            vep = (endpoint[0], int(endpoint[1]))
            route = None
            if vep in self._conns:
                route = "conn"  # adopted punched connection: direct path
            elif self.nat is not None and method not in _NAT_CONTROL:
                route = await self.nat.upgrade(relay, peer_hex)
                if route == "writer":
                    writer = self.nat.direct_writer(peer_hex)
                    if writer is not None and self.nat.server is not None:
                        # reversal route: the target dialed us back; call it
                        # over the parked inbound connection. Counted like
                        # the dialed leaf below — a half-open reversal route
                        # timing out must show up in rpc.client.failures or
                        # the swarm-health view misses the stalling peer.
                        if tele is not None:
                            tele.counter("rpc.client.calls").inc()
                        try:
                            return await self.nat.server.call_over(
                                writer, method, args or {},
                                timeout=timeout or self.request_timeout,
                            )
                        except RPCError:
                            if tele is not None:
                                tele.counter("rpc.client.remote_errors").inc()
                            raise  # remote answered — the route is alive
                        except asyncio.TimeoutError:
                            # half-open reversal route (NAT mapping expiry,
                            # silent TCP death — is_closing() never fires):
                            # evict it so the NEXT call rides the relay and
                            # re-solicits a dial-back. The timeout budget is
                            # already spent, so retrying inline would make a
                            # timeout=T call take ~2T — callers' straggler
                            # deadlines must stay honest.
                            if tele is not None:
                                tele.counter("rpc.client.failures").inc()
                                tele.event(
                                    "rpc.client.failure", method=method,
                                    endpoint=endpoint, error="TimeoutError",
                                    route="reversal",
                                )
                            self.nat.drop_route(peer_hex)
                            raise
                        except (ConnectionError, OSError) as e:
                            # instant transport failure (no budget burned):
                            # evict and fall back to the relay inline
                            if tele is not None:
                                tele.counter("rpc.client.failures").inc()
                                tele.event(
                                    "rpc.client.failure", method=method,
                                    endpoint=endpoint,
                                    error=type(e).__name__, route="reversal",
                                )
                            self.nat.drop_route(peer_hex)
                            route = None
                    else:
                        route = None
            if route != "conn":
                inner_timeout = timeout or self.request_timeout
                return await self.call(
                    relay,
                    "relay.call",
                    {
                        "to": peer_hex,
                        "method": method,
                        "args": args or {},
                        "timeout": inner_timeout,
                    },
                    timeout=inner_timeout + 5.0,
                )
        endpoint = (endpoint[0], int(endpoint[1]))
        # counted at the LEAF (after relay/NAT resolution): one count per
        # wire RPC, never double-counted through the relay recursion
        if tele is not None:
            tele.counter("rpc.client.calls").inc()
        try:
            _, writer = await self._connect(endpoint)
            self._next_id += 1
            req_id = self._next_id
            fut: asyncio.Future = asyncio.get_event_loop().create_future()
            self._pending[endpoint][req_id] = fut
        except (asyncio.TimeoutError, ConnectionError, OSError) as e:
            if tele is not None:
                tele.counter("rpc.client.failures").inc()
                tele.event(
                    "rpc.client.failure", method=method, endpoint=endpoint,
                    error=type(e).__name__,
                )
            raise
        request = {"id": req_id, "method": method, "args": args or {}}
        # cross-peer trace context: [trace_id, parent_span_id, caller peer]
        # — attached ONLY when telemetry is enabled AND a span is live on
        # this task, so disabled telemetry adds zero bytes to the framing
        tc = trace_field(tele)
        if tc is not None:
            request["tc"] = tc
        write_frame(writer, request)
        # hand-rolled deadline instead of asyncio.wait_for: the response
        # future is a bare Future (no task wrapping needed), so the whole
        # timeout is one timer that fails the future — wait_for's
        # ensure_future / release-waiter / cancellation-shield machinery
        # is pure overhead on this, and this is the hottest await in a
        # large simulation
        deadline = asyncio.get_event_loop().call_later(
            timeout or self.request_timeout, _expire_response, fut
        )
        try:
            await writer.drain()
            reply = await fut
        except (asyncio.TimeoutError, ConnectionError, OSError) as e:
            self._pending.get(endpoint, {}).pop(req_id, None)
            if tele is not None:
                tele.counter("rpc.client.failures").inc()
                tele.event(
                    "rpc.client.failure", method=method, endpoint=endpoint,
                    error=type(e).__name__,
                )
            raise
        finally:
            deadline.cancel()
        if not reply.get("ok"):
            if tele is not None:
                # the transport worked; the remote handler refused/crashed
                tele.counter("rpc.client.remote_errors").inc()
            raise RPCError(reply.get("error", "unknown remote error"))
        return reply.get("result")

    async def close(self) -> None:
        for endpoint in list(self._conns):
            self._drop(endpoint, ConnectionResetError("client closed"))


class RPCError(Exception):
    pass


async def probe_route_alive(
    server: RPCServer,
    writer: asyncio.StreamWriter,
    method: str,
    timeout: float = 2.0,
) -> bool:
    """End-to-end liveness probe of a parked inbound connection. A half-open
    TCP path (peer power loss, NAT mapping expiry with no FIN — is_closing()
    stays False forever) only reveals itself by not answering; True means
    the peer at the other end actually replied. Shared by the relay's and
    the NAT layer's re-registration checks so their hijack-protection
    semantics cannot drift apart."""
    try:
        await server.call_over(writer, method, {}, timeout=timeout)
        return True
    except Exception:  # noqa: BLE001 — no answer == dead path
        return False


# NAT-coordination methods must not themselves trigger an upgrade attempt
# (dht/nat.py defines them; duplicated here to avoid a circular import)
_NAT_CONTROL = frozenset(
    {"nat.reverse_connect", "nat.register", "nat.punch", "nat.hello"}
)


class RelayService:
    """Attachable circuit-relay for a public RPCServer
    (p2p/circuit-relay.md:15-68 capability: ``relay_enabled`` public node).

    Private peers park an outbound connection via ``relay.register``;
    ``relay.call`` pipes a request down that connection and relays the reply
    back. The relay is transport-only: it never inspects payloads and takes
    no part in the rounds it carries.
    """

    def __init__(self, server: RPCServer, call_timeout: float = 60.0):
        self.server = server
        self.call_timeout = call_timeout
        self._registered: Dict[str, asyncio.StreamWriter] = {}
        # observability + test hook: recent methods piped through this relay
        # (bounded — a long-lived relay must not grow without limit)
        from collections import deque

        self.piped_methods: "deque[str]" = deque(maxlen=512)
        self._rpc_register.__func__.rpc_wants_writer = True
        server.register("relay.register", self._rpc_register)
        server.register("relay.call", self._rpc_call)
        server.register("relay.ping", self._rpc_ping)
        server.register("relay.observed", self._rpc_observed)

    async def _rpc_register(self, peer: Endpoint, args, writer) -> dict:
        peer_id = args["peer_id"]
        current = self._registered.get(peer_id)
        if (current is not None and current is not writer
                and not current.is_closing()):
            # Never silently overwrite a registration whose connection still
            # ANSWERS: otherwise any host that can reach the relay could
            # hijack another peer's virtual endpoint and receive its
            # matchmaking/allreduce traffic. A half-open old connection must
            # not block the legitimate re-registration the keepalive
            # performs, so the OLD path is probed: alive => the newcomer is
            # refused; dead/unresponsive => replaced.
            if await probe_route_alive(self.server, current, "relay.probe"):
                raise PermissionError(
                    f"peer {peer_id!r} already has a live registration"
                )
        self._registered[peer_id] = writer
        return {"registered": True}

    async def _rpc_observed(self, peer: Endpoint, args) -> dict:
        """Reflexive-address observation (the STUN-ish primitive real NAT
        traversal needs): the address the relay sees for a registrant."""
        writer = self._registered.get(args["to"])
        if writer is None or writer.is_closing():
            raise KeyError(f"no relayed peer {args['to']!r} registered here")
        peername = writer.get_extra_info("peername") or (None, None)
        return {"host": peername[0], "port": peername[1]}

    async def _rpc_ping(self, peer: Endpoint, args) -> dict:
        """Cheap liveness probe: registrants call this periodically over
        their parked connection — a half-open TCP connection (relay power
        loss, NAT mapping expiry with no FIN) times out here, and the
        registrant reconnects + re-registers."""
        return {"pong": True}

    async def _rpc_call(self, peer: Endpoint, args) -> Any:
        writer = self._registered.get(args["to"])
        if writer is None or writer.is_closing():
            self._registered.pop(args["to"], None)
            raise KeyError(f"no relayed peer {args['to']!r} registered here")
        self.piped_methods.append(args["method"])
        call_args = args.get("args") or {}
        if args["method"] == "nat.punch":
            # inject the caller's relay-observed (reflexive) address: behind
            # a real NAT the self-reported bind host is an RFC1918 address
            # the target could never dial
            call_args = dict(call_args, observed_host=peer[0])
        return await self.server.call_over(
            writer,
            args["method"],
            call_args,
            timeout=float(args.get("timeout") or self.call_timeout),
        )
