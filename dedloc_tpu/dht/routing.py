"""Kademlia-style node IDs and k-bucket routing table.

The reference delegates this to hivemind.DHT (SURVEY.md §2.6). Here it is
re-implemented in-tree: 256-bit IDs (sha256), XOR metric, k-buckets with
least-recently-seen eviction preference for live nodes.
"""
from __future__ import annotations

import hashlib
import os
from bisect import bisect_right, insort
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dedloc_tpu.core import timeutils

ID_BITS = 256


class DHTID(int):
    """256-bit Kademlia identifier with the XOR distance metric."""

    MIN, MAX = 0, 2**ID_BITS - 1

    @classmethod
    def generate(cls, seed: Optional[bytes] = None) -> "DHTID":
        seed = seed if seed is not None else os.urandom(32)
        return cls(int.from_bytes(hashlib.sha256(seed).digest(), "big"))

    @classmethod
    def of_key(cls, key: str | bytes) -> "DHTID":
        if isinstance(key, str):
            key = key.encode()
        return cls(int.from_bytes(hashlib.sha256(key).digest(), "big"))

    def xor_distance(self, other: int) -> int:
        return int(self) ^ int(other)

    def to_bytes(self) -> bytes:  # type: ignore[override]
        return int(self).to_bytes(32, "big")

    # bytes -> DHTID memo: every RPC carries sender/node ids as 32-byte
    # blobs, and a busy simulation decodes the same few thousand identities
    # millions of times. DHTID is an immutable int, so interning is safe.
    _intern: Dict[bytes, "DHTID"] = {}

    @classmethod
    def from_bytes(cls, data: bytes) -> "DHTID":  # type: ignore[override]
        out = cls._intern.get(data)
        if out is None:
            if len(cls._intern) >= 65536:  # bounded: arbitrary wipe is fine
                cls._intern.clear()
            out = cls._intern[data] = cls(int.from_bytes(data, "big"))
        return out


Endpoint = Tuple[str, int]  # (host, port)


@dataclass
class NodeInfo:
    node_id: DHTID
    endpoint: Endpoint
    last_seen: float = field(default_factory=timeutils.monotonic)


class KBucket:
    def __init__(self, lower: int, upper: int, k: int):
        self.lower, self.upper, self.k = lower, upper, k
        self.nodes: Dict[DHTID, NodeInfo] = {}  # insertion-ordered
        self.replacement_cache: Dict[DHTID, NodeInfo] = {}
        # when this bucket's range last saw lookup/refresh activity — the
        # Kademlia bucket-refresh trigger (DHTNode.run_maintenance)
        self.last_refreshed: float = timeutils.monotonic()

    def covers(self, node_id: int) -> bool:
        return self.lower <= node_id < self.upper

    def add_or_update(self, info: NodeInfo) -> bool:
        """Returns False if the bucket is full (candidate goes to cache)."""
        if info.node_id in self.nodes:
            self.nodes.pop(info.node_id)
            self.nodes[info.node_id] = info
            return True
        if len(self.nodes) < self.k:
            self.nodes[info.node_id] = info
            return True
        self.replacement_cache.pop(info.node_id, None)
        self.replacement_cache[info.node_id] = info
        while len(self.replacement_cache) > self.k:  # bounded: drop oldest
            self.replacement_cache.pop(next(iter(self.replacement_cache)))
        return False

    def remove(self, node_id: DHTID) -> None:
        self.nodes.pop(node_id, None)
        if self.replacement_cache:
            rid, rinfo = self.replacement_cache.popitem()
            self.nodes[rid] = rinfo

    def oldest(self) -> Optional[NodeInfo]:
        return next(iter(self.nodes.values()), None)


class RoutingTable:
    def __init__(self, node_id: DHTID, bucket_size: int = 20):
        self.node_id = node_id
        self.bucket_size = bucket_size
        self.buckets: List[KBucket] = [KBucket(0, 2**ID_BITS, bucket_size)]
        # bucket lower bounds, kept sorted in lockstep with ``buckets``:
        # bucket membership is a bisect, not a linear scan (the table is
        # consulted on every RPC send AND receive — at simulator scale the
        # scan was a top-ten profile line)
        self._lowers: List[int] = [0]

    def _bucket_for(self, node_id: int) -> KBucket:
        return self.buckets[bisect_right(self._lowers, node_id) - 1]

    def add_or_update_node(self, info: NodeInfo) -> None:
        if info.node_id == self.node_id:
            return
        bucket = self._bucket_for(info.node_id)
        if bucket.add_or_update(info):
            return
        # split only the bucket containing our own ID (standard Kademlia)
        if bucket.covers(self.node_id):
            self._split(bucket)
            self.add_or_update_node(info)

    def _split(self, bucket: KBucket) -> None:
        mid = (bucket.lower + bucket.upper) // 2
        left = KBucket(bucket.lower, mid, self.bucket_size)
        right = KBucket(mid, bucket.upper, self.bucket_size)
        left.last_refreshed = right.last_refreshed = bucket.last_refreshed
        for info in bucket.nodes.values():
            (left if left.covers(info.node_id) else right).add_or_update(info)
        idx = self.buckets.index(bucket)
        self.buckets[idx : idx + 1] = [left, right]
        insort(self._lowers, mid)

    def random_id_in(self, bucket: KBucket) -> DHTID:
        """A uniform ID inside the bucket's range (bucket-refresh target)."""
        import random

        return DHTID(random.randrange(bucket.lower, bucket.upper))

    def mark_range_refreshed(self, target: int) -> None:
        """Record lookup activity for the bucket covering ``target``."""
        self._bucket_for(target).last_refreshed = timeutils.monotonic()

    def remove_node(self, node_id: DHTID) -> None:
        self._bucket_for(node_id).remove(node_id)

    def nearest_neighbors(
        self, target: int, k: Optional[int] = None
    ) -> List[NodeInfo]:
        k = k or self.bucket_size
        target = int(target)
        # (distance, info) rows sorted WITHOUT a key function: XOR with a
        # fixed target is a bijection, so distances are unique and the sort
        # never compares the (unorderable) NodeInfo second element. At
        # 256-bit int compares this is several times cheaper than a
        # per-element lambda, and this is the hottest DHT code path.
        ranked = [
            (node_id ^ target, info)
            for b in self.buckets
            for node_id, info in b.nodes.items()
        ]
        ranked.sort()
        return [info for _dist, info in ranked[:k]]

    def __len__(self) -> int:
        return sum(len(b.nodes) for b in self.buckets)
