"""Asyncio Kademlia DHT node: iterative routing, replicated records,
validator-gated stores.

In-tree replacement for hivemind.DHT's node (SURVEY.md §2.6). One node = one
asyncio endpoint; N nodes can share one process and event loop, which is how
multi-peer behavior is tested without a cluster (closing the reference's
biggest test gap, SURVEY.md §4).
"""
from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Sequence, Tuple

from dedloc_tpu.core import timeutils
from dedloc_tpu.core.timeutils import DHTExpiration, ValueWithExpiration, get_dht_time
from dedloc_tpu.dht.protocol import Endpoint, RPCClient, RPCServer
from dedloc_tpu.dht.routing import DHTID, NodeInfo, RoutingTable
from dedloc_tpu.dht.storage import DHTLocalStorage, DictionaryDHTValue
from dedloc_tpu.dht.validation import CompositeValidator, DHTRecord, RecordValidatorBase
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _pack_nodes(nodes: Sequence[NodeInfo]) -> List[List[Any]]:
    return [[n.node_id.to_bytes(), n.endpoint[0], n.endpoint[1]] for n in nodes]


def _unpack_nodes(raw: Sequence[Sequence[Any]]) -> List[NodeInfo]:
    # one clock read for the whole batch: the per-NodeInfo default_factory
    # is a visible cost when a lookup-heavy simulation unpacks millions
    now = timeutils.monotonic()
    return [
        NodeInfo(DHTID.from_bytes(r[0]), (r[1], int(r[2])), now) for r in raw
    ]


class DHTNode:
    """A single DHT peer. Use ``await DHTNode.create(...)``."""

    def __init__(self):
        raise RuntimeError("use DHTNode.create(...)")

    @classmethod
    async def create(
        cls,
        listen_host: str = "0.0.0.0",
        listen_port: int = 0,
        initial_peers: Sequence[Endpoint] = (),
        node_id: Optional[DHTID] = None,
        bucket_size: int = 20,
        num_replicas: int = 5,
        parallel_rpc: int = 3,
        request_timeout: float = 5.0,
        record_validators: Sequence[RecordValidatorBase] = (),
        client_mode: bool = False,
        advertised_host: Optional[str] = None,
        maintenance_interval: float = 30.0,  # 0 disables the background loop
        stale_peer_timeout: float = 75.0,
        bucket_refresh_interval: float = 120.0,
        lookup_cache_ttl: float = 15.0,  # nearest-set cache: 0 disables
        replication_interval: float = 600.0,  # Kademlia-style, much slower
        # than eviction/refresh: a full lookup+store fan-out per held record
        # every 30s would be orders of magnitude more traffic than needed
        transport=None,  # dht/transport.py seam: None = real TCP; the
        # simulator passes its in-process network so 1000 nodes share a loop
        telemetry_registry=None,  # per-peer scope for in-process multi-peer
        # runs (telemetry/registry.py); None falls back to the global
        store_admission=None,  # serving/admission.Admission: per-sender
        # token bucket on the store RPC (public-run rate control); None =
        # open (the default — store volume is already bounded by validators)
    ) -> "DHTNode":
        self = object.__new__(cls)
        self.node_id = node_id or DHTID.generate()
        self.store_admission = store_admission
        self.telemetry = telemetry_registry
        self.bucket_size = bucket_size
        self.num_replicas = num_replicas
        self.parallel_rpc = parallel_rpc
        self.request_timeout = request_timeout
        self.client_mode = client_mode
        self.stale_peer_timeout = stale_peer_timeout
        self.bucket_refresh_interval = bucket_refresh_interval
        self.replication_interval = replication_interval
        # None => replicate on the first pass (a 0.0 monotonic sentinel
        # would silently delay it on recently-booted hosts, where
        # monotonic() < replication_interval)
        self._last_replication: Optional[float] = None
        # nearest-set lookup cache (classic Kademlia lookup caching): an
        # iterative lookup's converged result for a target is stable for
        # as long as the keyspace neighborhood is — repeated gets/stores
        # on a hot key (matchmaking leader boards, catalog records) pay
        # the iterative fan-out once per TTL instead of once per call.
        # Entries are dropped early whenever a query against the cached
        # set errors (a holder died — re-converge), so churn degrades to
        # exactly the old behavior instead of serving a stale set.
        self.lookup_cache_ttl = lookup_cache_ttl
        self._nearest_cache: Dict[Tuple[int, int], Tuple[float, List[NodeInfo]]] = {}
        self._sender_args_cache: Optional[Dict[str, Any]] = None
        self.routing_table = RoutingTable(self.node_id, bucket_size)
        self.storage = DHTLocalStorage()
        self.cache = DHTLocalStorage(maxsize=2000)
        self.validator = CompositeValidator(record_validators)
        self.client = RPCClient(
            request_timeout=request_timeout, transport=transport,
            telemetry_registry=telemetry_registry,
        )
        self.server: Optional[RPCServer] = None
        self.port: Optional[int] = None
        self.advertised_host = advertised_host or "127.0.0.1"
        self._maintenance_task: Optional[asyncio.Task] = None
        if not client_mode:
            self.server = RPCServer(listen_host, listen_port,
                                    transport=transport,
                                    telemetry_registry=telemetry_registry)
            for method in ("dht.ping", "dht.find", "dht.store"):
                self.server.register(method, getattr(self, "_rpc_" + method.split(".")[1]))
            await self.server.start()
            self.port = self.server.port
        if initial_peers:
            await self.bootstrap(initial_peers)
        if maintenance_interval > 0:
            self._maintenance_task = asyncio.ensure_future(
                self._maintenance_loop(maintenance_interval)
            )
        return self

    @property
    def endpoint(self) -> Endpoint:
        return (self.advertised_host, self.port or 0)

    # ------------------------------------------------------------------ RPCs

    def _sender_args(self) -> Dict[str, Any]:
        # node_id and port are fixed after create(); one RPC is issued per
        # dict, so build it once (callers copy via ``{**...}`` or hand it
        # straight to msgpack — nobody mutates it)
        cached = self._sender_args_cache
        if cached is None:
            cached = self._sender_args_cache = {
                "sender_id": self.node_id.to_bytes(),
                "sender_port": self.port,  # None in client mode
            }
        return cached

    def _register_sender(self, peer: Endpoint, args: Dict[str, Any]) -> None:
        port = args.get("sender_port")
        sid = args.get("sender_id")
        if port and sid:
            self.routing_table.add_or_update_node(
                NodeInfo(DHTID.from_bytes(sid), (peer[0], int(port)))
            )

    async def _rpc_ping(self, peer: Endpoint, args: Dict[str, Any]) -> Dict[str, Any]:
        self._register_sender(peer, args)
        return {"node_id": self.node_id.to_bytes(), "dht_time": get_dht_time()}

    async def _rpc_find(self, peer: Endpoint, args: Dict[str, Any]) -> Dict[str, Any]:
        """find_node + find_value in one RPC (hivemind-style)."""
        self._register_sender(peer, args)
        target = DHTID.from_bytes(args["target"])
        nearest = _pack_nodes(
            self.routing_table.nearest_neighbors(target, self.bucket_size)
        )
        result: Dict[str, Any] = {"nodes": nearest}
        if args.get("return_value"):
            key = args["key"]
            entry = self.storage.get(key) or self.cache.get(key)
            if entry is not None:
                value, expiration = entry
                if isinstance(value, DictionaryDHTValue):
                    result["dict_value"] = [
                        [sk, v.value, v.expiration_time] for sk, v in value.items()
                    ]
                else:
                    result["value"] = value
                result["expiration"] = expiration
        return result

    _rpc_ping.rpc_inline = True
    _rpc_find.rpc_inline = True

    async def _rpc_store(self, peer: Endpoint, args: Dict[str, Any]) -> Dict[str, Any]:
        self._register_sender(peer, args)
        if self.store_admission is not None:
            # rate admission BEFORE validation: the point is bounding how
            # much validator work one sender can demand. Identity = the
            # claimed sender node id (self-chosen in open swarms, but the
            # bucket table is LRU-bounded so identity churn buys rate, not
            # memory), else the source host.
            sid = args.get("sender_id")
            identity = sid.hex() if isinstance(sid, bytes) else str(peer[0])
            reason = self.store_admission.check(
                identity, cost=float(len(args["records"]))
            )
            if reason is not None:
                tele = telemetry.resolve(self.telemetry)
                if tele is not None:
                    tele.counter("serve.rejected").inc()
                    tele.event(
                        "serve.reject", reason=reason, rpc="dht.store",
                        sender=identity[:32],
                    )
                return {
                    "stored": [False] * len(args["records"]),
                    "refused": reason,
                }
        outcomes = []
        for rec in args["records"]:
            key, subkey, value, expiration = rec
            record = DHTRecord(key, subkey, value, expiration)
            if not self.validator.validate(record):
                outcomes.append(False)
                continue
            if subkey is not None:
                outcomes.append(self.storage.store(key, value, expiration, subkey=subkey))
            else:
                outcomes.append(self.storage.store(key, value, expiration))
        return {"stored": outcomes}

    # the core DHT handlers never await I/O (validation and storage are
    # synchronous): the RPC server may run them inline instead of paying a
    # Task per request (protocol.py ``rpc_inline``)
    _rpc_store.rpc_inline = True

    # ----------------------------------------------------------- client side

    async def bootstrap(self, initial_peers: Sequence[Endpoint]) -> None:
        pings = await asyncio.gather(
            *(self._ping(tuple(p)) for p in initial_peers), return_exceptions=True
        )
        if not any(p is True for p in pings):
            logger.warning(f"bootstrap: no initial peer of {len(list(initial_peers))} responded")
        await self.find_nearest_nodes(self.node_id)

    async def _ping(self, endpoint: Endpoint) -> bool:
        try:
            result = await self.client.call(
                endpoint, "dht.ping", self._sender_args()
            )
            self.routing_table.add_or_update_node(
                NodeInfo(DHTID.from_bytes(result["node_id"]), tuple(endpoint))
            )
            return True
        except Exception:  # noqa: BLE001 — peer unreachable
            return False

    async def find_nearest_nodes(
        self, target: DHTID, k: Optional[int] = None
    ) -> List[NodeInfo]:
        """Iterative Kademlia lookup over the `dht.find` RPC. Results are
        cached per (target, k) for ``lookup_cache_ttl`` virtual seconds;
        callers that then observe a dead holder must ``_uncache_nearest``
        so the next call re-converges."""
        k = k or self.bucket_size
        cache_key = (int(target), k)
        if self.lookup_cache_ttl > 0:
            hit = self._nearest_cache.get(cache_key)
            if hit is not None:
                if hit[0] > get_dht_time():
                    return list(hit[1])
                del self._nearest_cache[cache_key]
        # a lookup IS refresh activity for the target's bucket
        self.routing_table.mark_range_refreshed(target)
        candidates: Dict[int, NodeInfo] = {
            n.node_id: n for n in self.routing_table.nearest_neighbors(target, k)
        }
        queried: set = set()
        # nodes that failed a probe THIS lookup: a later reply must not
        # re-admit one via setdefault — it is already in ``queried``, so the
        # termination check would accept it into the final top-k and every
        # subsequent get/store against the cached set would fail on it,
        # evicting the cache and re-learning the same dead peer forever
        failed: set = set()
        while True:
            frontier = sorted(
                (n for nid, n in candidates.items() if nid not in queried),
                key=lambda n: n.node_id ^ target,
            )[: self.parallel_rpc]
            if not frontier:
                break
            best_known = sorted(candidates, key=lambda nid: nid ^ target)[:k]
            if best_known and all(nid in queried for nid in best_known):
                break
            replies = await asyncio.gather(
                *(
                    self.client.call(
                        n.endpoint,
                        "dht.find",
                        {**self._sender_args(), "target": target.to_bytes()},
                    )
                    for n in frontier
                ),
                return_exceptions=True,
            )
            for node, reply in zip(frontier, replies):
                queried.add(node.node_id)
                if isinstance(reply, Exception):
                    self.routing_table.remove_node(node.node_id)
                    candidates.pop(node.node_id, None)
                    failed.add(node.node_id)
                    continue
                for info in _unpack_nodes(reply["nodes"]):
                    if info.node_id != self.node_id and info.node_id not in failed:
                        candidates.setdefault(info.node_id, info)
                        self.routing_table.add_or_update_node(info)
        out = sorted(candidates.values(), key=lambda n: n.node_id ^ target)
        out = out[:k]
        if self.lookup_cache_ttl > 0:
            while len(self._nearest_cache) >= 256:  # bounded: drop oldest
                self._nearest_cache.pop(next(iter(self._nearest_cache)))
            self._nearest_cache[cache_key] = (
                get_dht_time() + self.lookup_cache_ttl, list(out)
            )
        return out

    def _uncache_nearest(self, target: DHTID, k: Optional[int] = None) -> None:
        """Drop the cached nearest set for ``target`` — called when a query
        against it failed, i.e. the cached neighborhood no longer matches
        the live network."""
        self._nearest_cache.pop((int(target), k or self.bucket_size), None)

    async def store(
        self,
        key: bytes,
        value: bytes,
        expiration_time: DHTExpiration,
        subkey: Optional[bytes] = None,
    ) -> bool:
        """Sign, validate locally, then replicate onto the nearest peers."""
        key_id = DHTID.of_key(key)
        record = DHTRecord(key, subkey, value, expiration_time)
        signed = self.validator.sign_value(record)
        record = DHTRecord(key, subkey, signed, expiration_time)
        if not self.validator.validate(record):
            # e.g. relaying a record owned (signed) by a key we don't hold
            logger.debug(f"record for key {key!r} failed local validation")
            return False

        # look up WIDER than the replica set (classic Kademlia: k-wide
        # lookup, then pick the replicas): with small buckets the iterative
        # search needs the extra frontier to converge on the true global
        # nearest set — a k=num_replicas lookup from a sparse table can
        # settle on a locally-nearest set that misses the real one, and
        # store/get would then disagree about where the record lives
        k_wide = max(self.bucket_size, self.num_replicas)
        nearest = (await self.find_nearest_nodes(key_id, k=k_wide))[
            : self.num_replicas
        ]
        stored_anywhere = False
        # self-store if we are closer than the furthest replica (or low pop.)
        if not self.client_mode and (
            len(nearest) < self.num_replicas
            or (self.node_id ^ key_id) < (nearest[-1].node_id ^ key_id)
        ):
            if subkey is not None:
                stored_anywhere |= self.storage.store(
                    key, signed, expiration_time, subkey=subkey
                )
            else:
                stored_anywhere |= self.storage.store(key, signed, expiration_time)
        wire_record = [key, subkey, signed, expiration_time]
        replies = await asyncio.gather(
            *(
                self.client.call(
                    n.endpoint,
                    "dht.store",
                    {**self._sender_args(), "records": [wire_record]},
                )
                for n in nearest
            ),
            return_exceptions=True,
        )
        for node, reply in zip(nearest, replies):
            if isinstance(reply, Exception):
                # a replica died since the set was (possibly) cached —
                # evict it everywhere so the next lookup re-converges
                self.routing_table.remove_node(node.node_id)
                self._uncache_nearest(key_id, k_wide)
            elif any(reply.get("stored", [])):
                stored_anywhere = True
        return stored_anywhere

    async def get(
        self, key: bytes, latest: bool = False
    ) -> Optional[ValueWithExpiration]:
        """Fetch a record; ``latest=True`` always queries the network and
        merges dictionary subkeys across replicas."""
        key_id = DHTID.of_key(key)
        local = (None if self.client_mode else self.storage.get(key)) or self.cache.get(key)
        if local is not None and not latest:
            return self._strip(key, local)

        merged_dict = DictionaryDHTValue()
        best_value: Optional[ValueWithExpiration] = None
        if local is not None:
            if isinstance(local.value, DictionaryDHTValue):
                for sk, v in local.value.items():
                    merged_dict.store(sk, v.value, v.expiration_time)
            else:
                best_value = local

        # wide lookup for the same reason as in store(); query a couple of
        # nodes beyond the replica count so one stale/missed replica does
        # not turn into a lost record
        k_wide = max(self.bucket_size, self.num_replicas)
        nearest = (await self.find_nearest_nodes(key_id, k=k_wide))[
            : self.num_replicas + 2
        ]
        replies = await asyncio.gather(
            *(
                self.client.call(
                    n.endpoint,
                    "dht.find",
                    {
                        **self._sender_args(),
                        "target": key_id.to_bytes(),
                        "key": key,
                        "return_value": True,
                    },
                )
                for n in nearest
            ),
            return_exceptions=True,
        )
        for node, reply in zip(nearest, replies):
            if isinstance(reply, Exception):
                self.routing_table.remove_node(node.node_id)
                self._uncache_nearest(key_id, k_wide)
                continue
            # validate on the READ path too: a malicious replica could serve
            # forged records it never accepted through _rpc_store
            if "dict_value" in reply:
                for sk, v, exp in reply["dict_value"]:
                    if self.validator.validate(DHTRecord(key, sk, v, exp)):
                        merged_dict.store(sk, v, exp)
            elif "value" in reply:
                candidate = ValueWithExpiration(reply["value"], reply["expiration"])
                if not self.validator.validate(
                    DHTRecord(key, None, candidate.value, candidate.expiration_time)
                ):
                    continue
                if best_value is None or candidate.expiration_time > best_value.expiration_time:
                    best_value = candidate

        now = get_dht_time()
        if len(merged_dict):
            result = ValueWithExpiration(
                merged_dict, merged_dict.latest_expiration_time
            )
            if result.expiration_time > now:
                for sk, v in merged_dict.items():
                    self.cache.store(key, v.value, v.expiration_time, subkey=sk)
                return self._strip(key, result)
        if best_value is not None and best_value.expiration_time > now:
            self.cache.store(key, best_value.value, best_value.expiration_time)
            return self._strip(key, best_value)
        return None

    def _strip(self, key: bytes, entry: ValueWithExpiration) -> ValueWithExpiration:
        """Remove signature wrapping for the reader."""
        if isinstance(entry.value, DictionaryDHTValue):
            out = DictionaryDHTValue()
            for sk, v in entry.value.items():
                stripped = self.validator.strip_value(
                    DHTRecord(key, sk, v.value, v.expiration_time)
                )
                out.store(sk, stripped, v.expiration_time)
            return ValueWithExpiration(out, entry.expiration_time)
        stripped = self.validator.strip_value(
            DHTRecord(key, None, entry.value, entry.expiration_time)
        )
        return ValueWithExpiration(stripped, entry.expiration_time)

    # ----------------------------------------------------------- maintenance

    async def _maintenance_loop(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            try:
                await self.run_maintenance()
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                logger.debug(f"dht maintenance pass failed: {e!r}")

    async def run_maintenance(self) -> Dict[str, int]:
        """One self-maintenance pass — the Kademlia housekeeping a
        multi-hour churning run depends on (the capability hivemind's DHT
        provides under albert/run_trainer.py:236-243):

        1. **stale-peer eviction** — ping routing-table entries not heard
           from within ``stale_peer_timeout``; unresponsive nodes are
           evicted (replacement-cache candidates promote), so lookups stop
           spraying RPCs at long-dead peers.
        2. **bucket refresh** — a random-target lookup in every bucket
           whose range saw no activity for ``bucket_refresh_interval``,
           (re)discovering live peers for sparse regions of the ID space.
        3. **record re-replication** — every unexpired locally-held record
           is re-offered to the CURRENT ``num_replicas`` nearest nodes;
           as membership churns, replicas migrate onto newer nodes, so a
           record outlives every node that originally stored it (receivers
           keep the newest expiration — idempotent).

        Returns counters (tests and soak harnesses call this directly with
        a fake clock instead of waiting out ``maintenance_interval``).
        """
        stats = {"evicted": 0, "refreshed_buckets": 0, "republished": 0}
        now = timeutils.monotonic()
        # 1. stale-peer eviction, pings in parallel (a mass disconnect must
        # not serialize N x request_timeout inside one pass); ping success
        # re-registers with a fresh last_seen via _ping's add_or_update
        stale = [
            info
            for bucket in list(self.routing_table.buckets)
            for info in list(bucket.nodes.values())
            if now - info.last_seen >= self.stale_peer_timeout
        ]
        if stale:
            alive = await asyncio.gather(
                *(self._ping(info.endpoint) for info in stale)
            )
            for info, ok in zip(stale, alive):
                if not ok:
                    self.routing_table.remove_node(info.node_id)
                    stats["evicted"] += 1
        # 2. bucket refresh
        for bucket in list(self.routing_table.buckets):
            if (timeutils.monotonic() - bucket.last_refreshed
                    < self.bucket_refresh_interval):
                continue
            target = self.routing_table.random_id_in(bucket)
            await self.find_nearest_nodes(target)
            bucket.last_refreshed = timeutils.monotonic()
            stats["refreshed_buckets"] += 1
        # 3. record re-replication — on its own (much longer) cadence
        due = (
            self._last_replication is None
            or timeutils.monotonic() - self._last_replication
            >= self.replication_interval
        )
        if not self.client_mode and due:
            self._last_replication = timeutils.monotonic()
            dht_now = get_dht_time()
            for key in self.storage.keys():
                entry = self.storage.get(key)  # prunes expired subkeys
                if entry is None or entry.expiration_time <= dht_now:
                    continue
                if isinstance(entry.value, DictionaryDHTValue):
                    records = [
                        [key, sk, v.value, v.expiration_time]
                        for sk, v in entry.value.items()
                        if v.expiration_time > dht_now
                    ]
                else:
                    records = [[key, None, entry.value, entry.expiration_time]]
                if not records:
                    continue
                key_id = DHTID.of_key(key)
                nearest = await self.find_nearest_nodes(
                    key_id, k=self.num_replicas
                )
                targets = [n for n in nearest if n.node_id != self.node_id]
                if not targets:
                    continue
                await asyncio.gather(
                    *(
                        self.client.call(
                            n.endpoint,
                            "dht.store",
                            {**self._sender_args(), "records": records},
                        )
                        for n in targets
                    ),
                    return_exceptions=True,
                )
                stats["republished"] += 1
        return stats

    async def shutdown(self) -> None:
        if self._maintenance_task is not None:
            self._maintenance_task.cancel()
        await self.client.close()
        if self.server is not None:
            await self.server.stop()
