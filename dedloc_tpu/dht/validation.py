"""DHT record validators: RSA signatures + schema validation.

Capability parity with the reference's spoof-resistant metrics bus
(albert/metrics_utils.py:21-24: make_validators returns
[SchemaValidator(MetricSchema, prefix), RSASignatureValidator()] and the
signed public-key subkeys of BytesWithPublicKey). A validator chain runs at
every storing node; records failing any validator are rejected.

Ownership scheme: a record whose SUBKEY is an owner tag
``b"rsa:" + DER(public_key)`` must carry a signature by exactly that key over
the canonical (key, subkey, value, expiration) tuple. This gives per-peer
write isolation inside shared dictionary keys like ``{prefix}_metrics``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Type

from dedloc_tpu.core.serialization import pack_obj, unpack_obj
from dedloc_tpu.dht.crypto import RSAPrivateKey, verify_signature

OWNER_PREFIX = b"rsa:"


@dataclass(frozen=True)
class DHTRecord:
    key: bytes
    subkey: Optional[bytes]
    value: bytes
    expiration_time: float

    def canonical(self) -> bytes:
        return pack_obj(
            [self.key, self.subkey, self.value, round(self.expiration_time, 3)]
        )


class RecordValidatorBase:
    def validate(self, record: DHTRecord) -> bool:
        raise NotImplementedError

    def sign_value(self, record: DHTRecord) -> bytes:
        """Transform the outgoing value (e.g. append a signature)."""
        return record.value

    def strip_value(self, record: DHTRecord) -> bytes:
        """Inverse of sign_value for readers."""
        return record.value

    def merge_with(self, other: "RecordValidatorBase") -> "CompositeValidator":
        return CompositeValidator([self, other])


class RSASignatureValidator(RecordValidatorBase):
    def __init__(self, private_key: Optional[RSAPrivateKey] = None):
        self.private_key = private_key or RSAPrivateKey()
        self.local_public_key: bytes = OWNER_PREFIX + self.private_key.public_bytes()

    def _wrap(self, value: bytes, signature: bytes) -> bytes:
        return pack_obj({"_v": value, "_sig": signature})

    @staticmethod
    def _unwrap(value: bytes):
        try:
            obj = unpack_obj(value)
            if isinstance(obj, dict) and "_v" in obj and "_sig" in obj:
                return obj["_v"], obj["_sig"]
        except Exception:  # noqa: BLE001 — not a wrapped value
            pass
        return None

    def sign_value(self, record: DHTRecord) -> bytes:
        if record.subkey is None or not record.subkey.startswith(OWNER_PREFIX):
            return record.value
        if record.subkey != self.local_public_key:
            return record.value  # not ours to sign; will fail remote validation
        base = DHTRecord(record.key, record.subkey, record.value,
                         record.expiration_time)
        return self._wrap(record.value, self.private_key.sign(base.canonical()))

    def strip_value(self, record: DHTRecord) -> bytes:
        unwrapped = self._unwrap(record.value)
        return unwrapped[0] if unwrapped is not None else record.value

    def validate(self, record: DHTRecord) -> bool:
        if record.subkey is None or not record.subkey.startswith(OWNER_PREFIX):
            return True  # unowned record: nothing to verify
        unwrapped = self._unwrap(record.value)
        if unwrapped is None:
            return False
        value, signature = unwrapped
        base = DHTRecord(record.key, record.subkey, value, record.expiration_time)
        return verify_signature(
            record.subkey[len(OWNER_PREFIX):], base.canonical(), signature
        )


class SchemaValidator(RecordValidatorBase):
    """Validates (stripped) values for configured keys against pydantic models.

    ``schema`` maps a DHT key (str) -> pydantic model class; the record's
    unpacked value must validate against the model. Unknown keys pass when
    ``allow_extra_keys`` (hivemind-compatible default).
    """

    def __init__(
        self,
        schema: Dict[str, Type],
        prefix: Optional[str] = None,
        allow_extra_keys: bool = True,
        inner_validators: Sequence[RecordValidatorBase] = (),
    ):
        self.schema = {
            (f"{prefix}_{k}" if prefix else k): model for k, model in schema.items()
        }
        self.allow_extra_keys = allow_extra_keys
        self.inner = list(inner_validators)

    def validate(self, record: DHTRecord) -> bool:
        key = record.key.decode(errors="replace")
        model = self.schema.get(key)
        if model is None:
            return self.allow_extra_keys
        value = record.value
        for v in self.inner:
            value = v.strip_value(
                DHTRecord(record.key, record.subkey, value, record.expiration_time)
            )
        try:
            payload = unpack_obj(value)
            model.model_validate(payload)
            return True
        except Exception:  # noqa: BLE001 — validation boundary
            return False


class CompositeValidator(RecordValidatorBase):
    def __init__(self, validators: Sequence[RecordValidatorBase] = ()):
        # Schema validators need signature validators to strip wrapping:
        # run signature validators LAST on write (sign) and make them
        # available as inner strip for schema checks.
        self.validators: List[RecordValidatorBase] = []
        for v in validators:
            self.extend([v])

    def extend(self, validators: Sequence[RecordValidatorBase]) -> None:
        for v in validators:
            if isinstance(v, CompositeValidator):
                self.extend(v.validators)
            else:
                self.validators.append(v)
        sig = [v for v in self.validators if isinstance(v, RSASignatureValidator)]
        for v in self.validators:
            if isinstance(v, SchemaValidator):
                # make signature validators available for unwrapping, keeping
                # any user-supplied inner validators
                for s in sig:
                    if s not in v.inner:
                        v.inner.append(s)

    def validate(self, record: DHTRecord) -> bool:
        return all(v.validate(record) for v in self.validators)

    def sign_value(self, record: DHTRecord) -> bytes:
        value = record.value
        for v in self.validators:
            value = v.sign_value(
                DHTRecord(record.key, record.subkey, value, record.expiration_time)
            )
        return value

    def strip_value(self, record: DHTRecord) -> bytes:
        value = record.value
        for v in reversed(self.validators):
            value = v.strip_value(
                DHTRecord(record.key, record.subkey, value, record.expiration_time)
            )
        return value
