from dedloc_tpu.dht.node import DHTNode
from dedloc_tpu.dht.dht import DHT
from dedloc_tpu.dht.crypto import RSAPrivateKey
from dedloc_tpu.dht.validation import (
    RecordValidatorBase,
    RSASignatureValidator,
    SchemaValidator,
    CompositeValidator,
    DHTRecord,
)
