"""NAT traversal beyond the circuit relay: connection reversal and
hole-punched direct connect (dcutr-style), with the relay as fallback.

Reference capability: p2p/NAT-traversal.md:86-94 — private nodes obtain
punched addresses and call each other DIRECTLY; the coordination rides the
public node. Without this, every private↔private byte rides the relay
(dht/protocol.py RelayService), making relay hosts bandwidth bottlenecks at
volunteer scale. With it, the relay carries only the few hundred bytes of
handshake per peer pair.

Two upgrade paths, tried transparently by ``RPCClient.call`` on first use of
a ``relay:`` virtual endpoint and cached afterwards:

reversal (we are public, target is private)
    One small relayed control message (``nat.reverse_connect``) asks the
    target to dial our real endpoint and park that connection
    (``nat.register``); subsequent calls ride it directly via
    ``RPCServer.call_over``. Registrations are only accepted for peers we
    solicited, and a live registration is never overwritten — a stranger
    cannot claim someone else's route.

punch (both private)
    A relayed rendezvous (``nat.punch``) exchanges each side's
    (host, bound-port); both sides then connect simultaneously from/to
    those ports (TCP simultaneous open — the crossing SYNs are what punch
    real NAT mappings). Because crossing SYNs cannot be timed reliably on
    loopback/datacenter networks, each side also accepts on its punched
    port for the duration of the handshake: the accept stands in for the
    mapping a real NAT would hold open, and the protocol layer (rendezvous,
    simultaneous dial, tie-break, verification, adoption) is identical.
    Double-establishes are tie-broken deterministically (the connection
    initiated by the smaller peer id wins) and the surviving connection is
    verified end-to-end with ``nat.hello`` before adoption.

Failures fall back to the relay and are cached for ``failure_ttl`` so a dead
path does not re-handshake on every call.
"""
from __future__ import annotations

import asyncio
import socket
from typing import Dict, Optional, Tuple

from dedloc_tpu.core import timeutils
from dedloc_tpu.utils.aio import keep_task

from dedloc_tpu.dht.protocol import (
    Endpoint,
    RPCClient,
    RPCServer,
    probe_route_alive,
    relay_endpoint,
)
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _punch_socket(bind_host: str, port: int = 0) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if hasattr(socket, "SO_REUSEPORT"):
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.setblocking(False)
    s.bind((bind_host, port))
    return s


class NatTraversal:
    """Attach to an (RPCClient, RPCServer) pair; ``RPCClient.call`` consults
    it before falling back to the circuit relay for ``relay:`` endpoints."""

    def __init__(
        self,
        client: RPCClient,
        server: Optional[RPCServer],
        peer_id: bytes,
        advertised: Optional[Endpoint] = None,
        bind_host: str = "127.0.0.1",
        handshake_timeout: float = 4.0,
        failure_ttl: float = 30.0,
    ):
        self.client = client
        self.server = server
        self.peer_id = peer_id
        self.advertised = advertised  # our real endpoint; None => private
        self.bind_host = bind_host
        self.handshake_timeout = handshake_timeout
        self.failure_ttl = failure_ttl
        # reversal routes: peer_hex -> parked inbound connection writer
        self._routes: Dict[str, asyncio.StreamWriter] = {}
        # reversal registrations we solicited (peer_hex -> solicited-at)
        self._expected: Dict[str, float] = {}
        self._failed: Dict[str, float] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        self._register_locks: Dict[str, asyncio.Lock] = {}

        if server is not None and server.port is not None:
            # listening (public) side: accept solicited dial-backs
            self._rpc_register.__func__.rpc_wants_writer = True
            server.register("nat.register", self._rpc_register)
        # private side: serve coordination arriving over our parked relay
        # connection (reverse dispatch) — and over adopted punch connections
        client.reverse_handlers["nat.reverse_connect"] = self._rpc_reverse_connect
        client.reverse_handlers["nat.punch"] = self._rpc_punch
        client.reverse_handlers["nat.hello"] = self._rpc_hello
        client.nat = self

    # ------------------------------------------------------------ public API

    def direct_writer(self, peer_hex: str) -> Optional[asyncio.StreamWriter]:
        w = self._routes.get(peer_hex)
        if w is not None and w.is_closing():
            self._routes.pop(peer_hex, None)
            return None
        return w

    def drop_route(self, peer_hex: str) -> None:
        """Evict a reversal route whose connection failed in use (timeout on
        ``call_over``): is_closing() never fires on a half-open TCP path, so
        the caller's failure signal is the only eviction trigger. The next
        call to this peer rides the relay and re-solicits a dial-back."""
        w = self._routes.pop(peer_hex, None)
        if w is not None:
            try:
                w.close()
            except OSError:
                pass

    async def upgrade(
        self, relay: Endpoint, peer_hex: str
    ) -> Optional[str]:
        """Try to obtain a direct path to ``peer_hex`` (registered at
        ``relay``). Returns "writer" when a reversal route is parked on our
        server, "conn" when a punched connection was adopted into the
        client pool under the virtual endpoint, or None (use the relay)."""
        if self.direct_writer(peer_hex) is not None:
            return "writer"
        vep = relay_endpoint(relay, bytes.fromhex(peer_hex))
        if vep in self.client._conns:
            return "conn"
        now = timeutils.monotonic()
        if now - self._failed.get(peer_hex, -1e9) < self.failure_ttl:
            return None
        lock = self._locks.setdefault(peer_hex, asyncio.Lock())
        async with lock:
            if self.direct_writer(peer_hex) is not None:
                return "writer"
            if vep in self.client._conns:
                return "conn"
            try:
                if self.advertised is not None:
                    return await self._reverse(relay, peer_hex)
                return await self._punch_initiate(relay, peer_hex)
            except Exception as e:  # noqa: BLE001 — any failure => relay
                logger.debug(f"nat upgrade to {peer_hex[:12]} failed: {e!r}")
                self._failed[peer_hex] = timeutils.monotonic()
                return None

    # ------------------------------------------------------------- reversal

    async def _reverse(self, relay: Endpoint, peer_hex: str) -> Optional[str]:
        self._expected[peer_hex] = timeutils.monotonic()
        await self.client.call(
            relay,
            "relay.call",
            {
                "to": peer_hex,
                "method": "nat.reverse_connect",
                "args": {
                    "dial": list(self.advertised),
                    "peer_id": self.peer_id.hex(),
                },
                "timeout": self.handshake_timeout,
            },
            timeout=self.handshake_timeout + 2.0,
        )
        # the target dialed us back DURING the call (nat.register completes
        # before reverse_connect returns), so the route is parked now
        if self.direct_writer(peer_hex) is not None:
            logger.info(f"nat: reversal route to {peer_hex[:12]} established")
            return "writer"
        raise ConnectionError("target reported dialing but no route parked")

    async def _rpc_register(self, peer: Endpoint, args, writer) -> dict:
        peer_hex = args["peer_id"]
        solicited_at = self._expected.get(peer_hex)
        if (solicited_at is None
                or timeutils.monotonic() - solicited_at
                > 2 * self.handshake_timeout):
            raise PermissionError(
                f"unsolicited nat registration for {peer_hex[:12]!r}"
            )
        # per-peer REGISTRATION lock (distinct from the upgrade locks:
        # _reverse holds those while awaiting the very dial-back served
        # here, so sharing them would deadlock): the liveness probe below
        # awaits, and two dial-backs from overlapping solicitations must not
        # interleave their check-then-replace (the slower one would clobber
        # the fresh route with an abandoned writer)
        lock = self._register_locks.setdefault(peer_hex, asyncio.Lock())
        async with lock:
            current = self._routes.get(peer_hex)
            if (current is not None and current is not writer
                    and not current.is_closing()):
                # a half-open old route (peer power loss, NAT mapping expiry
                # — no FIN, is_closing() stays False forever) must not block
                # the peer's legitimate re-dial: probe the old path
                # end-to-end and only refuse the newcomer when it still
                # answers (same contract as the relay's check)
                if await probe_route_alive(self.server, current, "nat.hello"):
                    raise PermissionError(
                        f"peer {peer_hex[:12]!r} already has a live route"
                    )
                self.drop_route(peer_hex)
            self._routes[peer_hex] = writer
        return {"registered": True}

    async def _rpc_reverse_connect(self, arrived_on: Endpoint, args) -> dict:
        # dialing back parks OUR pooled connection at the public peer; its
        # calls then arrive on it and dispatch via reverse_handlers
        dial = (args["dial"][0], int(args["dial"][1]))
        reg = {"peer_id": self.peer_id.hex()}
        if dial in self.client._conns:
            # an existing pooled connection to the solicitor may be the
            # dead half of the very path being re-solicited (symmetric
            # half-open death never EOFs) — but it may also be a healthy
            # shared connection (our relay registration, when the solicitor
            # IS our relay), so never evict blindly: try the register over
            # it with a bounded budget, and only on silence evict and dial
            # fresh
            try:
                await self.client.call(
                    dial, "nat.register", reg,
                    timeout=max(1.0, self.handshake_timeout / 2),
                )
                logger.info(
                    f"nat: dialed back to {dial} (connection reversal)"
                )
                return {"dialed": True}
            except (asyncio.TimeoutError, ConnectionError, OSError):
                if dial == arrived_on:
                    # THIS solicitation was just delivered over that very
                    # connection, so the path is alive — merely slow under
                    # load (e.g. queued behind a bulk relay transfer).
                    # Evicting would kill every in-flight RPC on it and
                    # unregister us from our own relay; surface the
                    # timeout instead and let the solicitor retry.
                    raise
                self.client._drop(
                    dial, ConnectionResetError("re-dial solicited")
                )
        await self.client.call(dial, "nat.register", reg)
        logger.info(f"nat: dialed back to {dial} (connection reversal)")
        return {"dialed": True}

    async def _rpc_hello(self, _ep: Endpoint, args) -> dict:
        return {"peer_id": self.peer_id.hex()}

    # ---------------------------------------------------------------- punch

    async def _punch_initiate(
        self, relay: Endpoint, peer_hex: str
    ) -> Optional[str]:
        lsock = _punch_socket(self.bind_host)
        port = lsock.getsockname()[1]
        reply = await self.client.call(
            relay,
            "relay.call",
            {
                "to": peer_hex,
                "method": "nat.punch",
                "args": {
                    "host": self.bind_host,
                    "port": port,
                    "peer_id": self.peer_id.hex(),
                    "relay": list(relay),
                },
                "timeout": self.handshake_timeout,
            },
            timeout=self.handshake_timeout + 2.0,
        )
        # prefer the target's relay-observed (reflexive) host: behind a real
        # NAT the self-reported bind host is an RFC1918 address we could
        # never dial; the bound port rides on the classic port-preserving-
        # NAT assumption of TCP hole punching
        dial_host = reply["host"]
        try:
            observed = await self.client.call(
                relay, "relay.observed", {"to": peer_hex}, timeout=3.0
            )
            if observed.get("host"):
                dial_host = observed["host"]
        except Exception:  # noqa: BLE001 — fall back to self-reported
            pass
        remote = (dial_host, int(reply["port"]))
        vep = relay_endpoint(relay, bytes.fromhex(peer_hex))
        ok = await self._punch_run(lsock, remote, peer_hex, vep)
        if ok:
            return "conn"
        raise ConnectionError("punch failed")

    async def _rpc_punch(self, _ep: Endpoint, args) -> dict:
        their_hex = args["peer_id"]
        relay = (args["relay"][0], int(args["relay"][1]))
        lsock = _punch_socket(self.bind_host)
        port = lsock.getsockname()[1]
        vep = relay_endpoint(relay, bytes.fromhex(their_hex))
        # the relay injects the initiator's reflexive address into the
        # relayed args (RelayService._rpc_call); prefer it over the
        # initiator's self-reported private bind host
        remote = (args.get("observed_host") or args["host"], int(args["port"]))
        # reply first (the initiator needs our port), punch in background
        # (retained + exception-logged: a failed punch must be visible)
        keep_task(
            self._punch_run(lsock, remote, their_hex, vep),
            name="nat punch", log=logger,
        )
        return {"host": self.bind_host, "port": port}

    async def _punch_run(
        self,
        lsock: socket.socket,
        remote: Endpoint,
        their_hex: str,
        vep: Endpoint,
    ) -> bool:
        """Simultaneous dial + accept on the punched port; tie-break, verify
        with nat.hello, adopt into the client pool under ``vep``."""
        loop = asyncio.get_event_loop()
        local = lsock.getsockname()
        deadline = timeutils.monotonic() + self.handshake_timeout
        accepted: Optional[socket.socket] = None
        connected: Optional[socket.socket] = None

        async def _accept():
            nonlocal accepted
            lsock.listen(1)
            while timeutils.monotonic() < deadline and accepted is None:
                try:
                    conn, _ = await asyncio.wait_for(
                        loop.sock_accept(lsock),
                        timeout=max(0.05, deadline - timeutils.monotonic()),
                    )
                    conn.setblocking(False)
                    accepted = conn
                    return
                except asyncio.TimeoutError:
                    return
                except OSError:
                    await asyncio.sleep(0.05)

        async def _dial():
            nonlocal connected
            while timeutils.monotonic() < deadline and connected is None:
                s = _punch_socket(local[0], local[1])
                try:
                    await asyncio.wait_for(
                        loop.sock_connect(s, remote), timeout=0.5
                    )
                    connected = s
                    return
                except (OSError, asyncio.TimeoutError):
                    s.close()
                    await asyncio.sleep(0.08)
                except asyncio.CancelledError:
                    # cancelled mid sock_connect: the in-flight socket is
                    # ours to close — repeated punches on a long-lived peer
                    # must not accumulate leaked FDs
                    s.close()
                    raise

        tasks = [asyncio.ensure_future(_accept()),
                 asyncio.ensure_future(_dial())]
        # wait until SOME path established, then a short grace for the other
        # so both sides can apply the same tie-break
        while (timeutils.monotonic() < deadline and accepted is None
               and connected is None):
            await asyncio.sleep(0.03)
        await asyncio.sleep(0.25)
        for t in tasks:
            t.cancel()
        try:
            my_id = self.peer_id.hex()
            # the connection initiated by the SMALLER peer id wins: that is
            # our dial if we are smaller, else the one we accepted
            prefer_mine = my_id < their_hex
            first = connected if prefer_mine else accepted
            second = accepted if prefer_mine else connected
            for sock_choice, other in ((first, second), (second, first)):
                if sock_choice is None:
                    continue
                if await self._verify_adopt(sock_choice, their_hex, vep):
                    if other is not None:
                        try:
                            other.close()
                        except OSError:
                            pass
                    return True
            return False
        finally:
            lsock.close()

    async def _verify_adopt(
        self, sock: socket.socket, their_hex: str, vep: Endpoint
    ) -> bool:
        try:
            reader, writer = await asyncio.open_connection(sock=sock)
        except OSError:
            sock.close()
            return False
        existing = self.client._conns.get(vep)
        if existing is not None and not existing[1].is_closing():
            writer.close()
            return True  # a concurrent handshake already adopted a conn
        self.client.adopt_connection(vep, reader, writer)
        try:
            hello = await self.client.call(
                vep, "nat.hello", {}, timeout=self.handshake_timeout
            )
            if hello.get("peer_id") != their_hex:
                raise ConnectionError("hello identity mismatch")
            logger.info(
                f"nat: punched direct connection to {their_hex[:12]} "
                f"({vep[0].split(':', 1)[0]} route upgraded)"
            )
            return True
        except Exception:  # noqa: BLE001 — dead/mismatched path
            self.client._drop(vep, ConnectionResetError("punch verify failed"))
            return False
