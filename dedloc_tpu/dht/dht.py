"""Synchronous DHT facade: background event loop + future-based API.

Mirrors the hivemind.DHT surface the reference consumes (SURVEY.md §2.6):
``DHT(start=True, initial_peers=..., record_validators=...)``,
``dht.store(key, value, expiration_time, subkey=..., return_future=...)``,
``dht.get(key, latest=True)``, ``dht.port``, ``dht.shutdown()``.

The reference runs its DHT in a forked *process*; here a daemon *thread*
suffices — the node is pure asyncio IO which releases the GIL, and the
trainer's hot loop is on the TPU anyway.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Any, Dict, Optional, Sequence, Tuple, Union

from dedloc_tpu.core.serialization import pack_obj, unpack_obj
from dedloc_tpu.core.timeutils import DHTExpiration, ValueWithExpiration
from dedloc_tpu.dht.node import DHTNode
from dedloc_tpu.dht.protocol import Endpoint
from dedloc_tpu.dht.storage import DictionaryDHTValue
from dedloc_tpu.dht.validation import RecordValidatorBase
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DHTKey = Union[str, bytes]


def _to_bytes(key: DHTKey) -> bytes:
    return key.encode() if isinstance(key, str) else key


def _parse_endpoint(ep: Union[str, Endpoint]) -> Endpoint:
    if isinstance(ep, str):
        host, _, port = ep.rpartition(":")
        return (host or "127.0.0.1", int(port))
    return (ep[0], int(ep[1]))


class DHT:
    """Thread-backed DHT peer with a blocking/future API."""

    def __init__(
        self,
        initial_peers: Sequence[Union[str, Endpoint]] = (),
        start: bool = False,
        listen_host: str = "0.0.0.0",
        listen_port: int = 0,
        client_mode: bool = False,
        record_validators: Sequence[RecordValidatorBase] = (),
        advertised_host: Optional[str] = None,
        num_replicas: int = 5,
        daemon: bool = True,
        maintenance_interval: float = 30.0,  # 0 disables self-maintenance
    ):
        self._initial_peers = [_parse_endpoint(p) for p in initial_peers]
        self._listen = (listen_host, listen_port)
        self._client_mode = client_mode
        self._validators = list(record_validators)
        self._advertised_host = advertised_host
        self._num_replicas = num_replicas
        self._maintenance_interval = maintenance_interval
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._node: Optional[DHTNode] = None
        self._thread = threading.Thread(
            target=self._run_loop, daemon=daemon, name="dedloc-dht"
        )
        self._ready = threading.Event()
        self._startup_error: Optional[BaseException] = None
        self._shut_down = False
        if start:
            self.run_in_background()

    # ------------------------------------------------------------ lifecycle

    def run_in_background(self, await_ready: bool = True, timeout: float = 15.0):
        self._thread.start()
        if await_ready:
            if not self._ready.wait(timeout):
                raise TimeoutError("DHT failed to start in time")
            if self._startup_error is not None:
                raise RuntimeError("DHT failed to start") from self._startup_error
        return self

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def boot():
            try:
                self._node = await DHTNode.create(
                    listen_host=self._listen[0],
                    listen_port=self._listen[1],
                    initial_peers=self._initial_peers,
                    record_validators=self._validators,
                    client_mode=self._client_mode,
                    advertised_host=self._advertised_host,
                    num_replicas=self._num_replicas,
                    maintenance_interval=self._maintenance_interval,
                )
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                self._startup_error = e
            finally:
                self._ready.set()

        loop.run_until_complete(boot())
        if self._startup_error is None:
            loop.run_forever()
        loop.close()

    def shutdown(self) -> None:
        if self._loop is None or self._node is None or self._shut_down:
            return
        self._shut_down = True
        try:
            fut = asyncio.run_coroutine_threadsafe(self._node.shutdown(), self._loop)
            fut.result(timeout=5)
        except Exception:  # noqa: BLE001 — best-effort shutdown
            pass
        try:
            self._loop.call_soon_threadsafe(self._loop.stop)
        except RuntimeError:
            pass  # loop already closed
        self._thread.join(timeout=5)

    # ------------------------------------------------------------ properties

    @property
    def port(self) -> Optional[int]:
        return self._node.port if self._node else None

    @property
    def endpoint(self) -> Endpoint:
        assert self._node is not None
        return self._node.endpoint

    def get_visible_address(self) -> str:
        host, port = self.endpoint
        return f"{host}:{port}"

    # ------------------------------------------------------------ operations

    def _submit(self, coro) -> concurrent.futures.Future:
        assert self._loop is not None and self._node is not None, "DHT not started"
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def store(
        self,
        key: DHTKey,
        value: Any,
        expiration_time: DHTExpiration,
        subkey: Optional[bytes] = None,
        return_future: bool = False,
    ):
        """Store a msgpack-able value. Blocks unless return_future."""
        assert self._node is not None, "DHT not started"
        coro = self._node.store(
            _to_bytes(key), pack_obj(value), expiration_time, subkey=subkey
        )
        fut = self._submit(coro)
        return fut if return_future else fut.result()

    def get(
        self,
        key: DHTKey,
        latest: bool = False,
        return_future: bool = False,
    ):
        """Returns ValueWithExpiration of (unpacked value | dict of subkey ->
        ValueWithExpiration(unpacked)) or None."""
        assert self._node is not None, "DHT not started"
        inner = self._node.get(_to_bytes(key), latest=latest)

        async def convert():
            entry = await inner
            if entry is None:
                return None
            if isinstance(entry.value, DictionaryDHTValue):
                out: Dict[Any, ValueWithExpiration] = {}
                for sk, v in entry.value.items():
                    try:
                        out[sk] = ValueWithExpiration(
                            unpack_obj(v.value), v.expiration_time
                        )
                    except Exception:  # noqa: BLE001 — skip undecodable entry
                        continue
                return ValueWithExpiration(out, entry.expiration_time)
            try:
                return ValueWithExpiration(
                    unpack_obj(entry.value), entry.expiration_time
                )
            except Exception:  # noqa: BLE001
                return None

        fut = self._submit(convert())
        return fut if return_future else fut.result()

    def run_coroutine(self, coro_fn, return_future: bool = False):
        """Run ``coro_fn(node)`` on the DHT loop (averager integration hook)."""
        fut = self._submit(coro_fn(self._node))
        return fut if return_future else fut.result()
