"""ctypes loader + numpy wrappers for the native wire codec.

The reference keeps its wire hot loops in native dependency code (protobuf/
grpc C++ wheels, NCCL — SURVEY.md §2.7); this package is the TPU build's
in-tree equivalent (native/wirecodec.cpp). The .so is compiled lazily with
g++ on first import (no pybind11 in the image, so plain `extern "C"` +
ctypes); every entry point has a numpy fallback so the framework works on
machines without a toolchain. `AVAILABLE` reports which path is active.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_wirecodec.so")
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")

_lib: Optional[ctypes.CDLL] = None


def _build() -> bool:
    src = os.path.join(_SRC_DIR, "wirecodec.cpp")
    if not os.path.exists(src):
        return False
    # build to a per-pid temp path and rename into place: concurrent
    # importers must never CDLL a half-written .so
    tmp = f"{_SO_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            [
                "g++", "-O3", "-fPIC", "-std=c++17", "-shared",
                src, "-o", tmp,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO_PATH)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _stale() -> bool:
    src = os.path.join(_SRC_DIR, "wirecodec.cpp")
    try:
        return os.path.getmtime(src) > os.path.getmtime(_SO_PATH)
    except OSError:
        return False


def _load() -> Optional[ctypes.CDLL]:
    if (not os.path.exists(_SO_PATH) or _stale()) and not _build():
        if not os.path.exists(_SO_PATH):
            return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    i64, f32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_float)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    lib.f32_to_f16.argtypes = [f32p, u16p, i64]
    lib.f16_to_f32.argtypes = [u16p, f32p, i64]
    lib.quantize_uint8.argtypes = [f32p, u8p, i64, f32p, f32p]
    lib.dequantize_uint8.argtypes = [u8p, f32p, i64, ctypes.c_float, ctypes.c_float]
    lib.axpy_f32.argtypes = [f32p, f32p, ctypes.c_float, i64]
    lib.scale_f32.argtypes = [f32p, ctypes.c_float, i64]
    lib.crc32c.argtypes = [u8p, i64]
    lib.crc32c.restype = ctypes.c_uint32
    return lib


_lib = _load()
AVAILABLE = _lib is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def f32_to_f16(x: np.ndarray) -> np.ndarray:
    """fp32 -> IEEE fp16 bytes-compatible array (round-to-nearest-even)."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    if _lib is None:
        return x.astype(np.float16)
    out = np.empty(x.shape, dtype=np.float16)
    _lib.f32_to_f16(
        _ptr(x, ctypes.c_float),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        x.size,
    )
    return out


def f16_to_f32(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.float16)
    if _lib is None:
        return x.astype(np.float32)
    out = np.empty(x.shape, dtype=np.float32)
    _lib.f16_to_f32(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        _ptr(out, ctypes.c_float),
        x.size,
    )
    return out


def quantize_uint8(x: np.ndarray) -> Tuple[np.ndarray, float, float]:
    """Fused min/max + affine encode. Returns (q, lo, scale)."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    if _lib is None:
        lo = float(x.min()) if x.size else 0.0
        hi = float(x.max()) if x.size else 0.0
        scale = (hi - lo) / 255.0 or 1.0
        q = np.clip(np.rint((x - lo) / scale), 0, 255).astype(np.uint8)
        return q, lo, scale
    q = np.empty(x.shape, dtype=np.uint8)
    lo = ctypes.c_float()
    scale = ctypes.c_float()
    _lib.quantize_uint8(
        _ptr(x, ctypes.c_float),
        _ptr(q, ctypes.c_uint8),
        x.size,
        ctypes.byref(lo),
        ctypes.byref(scale),
    )
    return q, float(lo.value), float(scale.value)


def dequantize_uint8(q: np.ndarray, lo: float, scale: float) -> np.ndarray:
    q = np.ascontiguousarray(q, dtype=np.uint8)
    if _lib is None:
        return q.astype(np.float32) * scale + lo
    out = np.empty(q.shape, dtype=np.float32)
    _lib.dequantize_uint8(
        _ptr(q, ctypes.c_uint8), _ptr(out, ctypes.c_float), q.size, lo, scale
    )
    return out


def axpy(acc: np.ndarray, x: np.ndarray, w: float) -> np.ndarray:
    """acc += w * x in place (acc must be contiguous fp32). Returns acc."""
    assert acc.dtype == np.float32 and acc.flags["C_CONTIGUOUS"]
    x = np.ascontiguousarray(x, dtype=np.float32)
    if x.size != acc.size:
        # peer-controlled shapes must fail loudly, not read out of bounds
        raise ValueError(f"axpy size mismatch: acc {acc.size} vs x {x.size}")
    if _lib is None:
        acc += np.float32(w) * x.reshape(acc.shape)
        return acc
    _lib.axpy_f32(_ptr(acc, ctypes.c_float), _ptr(x, ctypes.c_float), w, acc.size)
    return acc


def scale(x: np.ndarray, s: float) -> np.ndarray:
    """x *= s in place (contiguous fp32). Returns x."""
    assert x.dtype == np.float32 and x.flags["C_CONTIGUOUS"]
    if _lib is None:
        x *= np.float32(s)
        return x
    _lib.scale_f32(_ptr(x, ctypes.c_float), s, x.size)
    return x


_CRC32C_TABLE: Optional[list] = None


def _crc32c_py(data: bytes) -> int:
    # Vectorized pure-python/numpy fallback; same polynomial as the native
    # path so mixed fleets (with/without a toolchain) agree on checksums.
    # Strategy: process in fixed-size blocks — within a block, fold each
    # byte's table value shifted by its position. A simple per-byte loop in
    # Python costs ~1 µs/byte (seconds per multi-MB chunk), so instead use
    # the crc32 "combine by zero-extension" trick via 8 per-position tables.
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        base = [0] * 256
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            base[i] = c
        # slice-by-8 tables: table[k][b] = crc of byte b followed by k zeros
        tables = [base]
        for _ in range(7):
            prev = tables[-1]
            tables.append([base[v & 0xFF] ^ (v >> 8) for v in prev])
        _CRC32C_TABLE = [np.array(t, dtype=np.uint32) for t in tables]
    t = _CRC32C_TABLE
    crc = 0xFFFFFFFF
    buf = np.frombuffer(data, dtype=np.uint8)
    n8 = (len(buf) // 8) * 8
    if n8:
        blocks = buf[:n8].reshape(-1, 8)
        # crc feedback only touches the first 4 bytes of each 8-byte block;
        # the last 4 bytes' contribution is crc-independent — vectorize it
        f4 = (
            t[3][blocks[:, 4]] ^ t[2][blocks[:, 5]]
            ^ t[1][blocks[:, 6]] ^ t[0][blocks[:, 7]]
        ).tolist()
        t7, t6, t5, t4 = t[7].tolist(), t[6].tolist(), t[5].tolist(), t[4].tolist()
        b0, b1, b2, b3 = (blocks[:, k].tolist() for k in range(4))
        for i in range(len(f4)):
            crc = (
                t7[(crc ^ b0[i]) & 0xFF]
                ^ t6[((crc >> 8) ^ b1[i]) & 0xFF]
                ^ t5[((crc >> 16) ^ b2[i]) & 0xFF]
                ^ t4[((crc >> 24) ^ b3[i]) & 0xFF]
                ^ f4[i]
            )
    base = t[0].tolist()
    for b in buf[n8:].tolist():
        crc = base[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes) -> int:
    """CRC32C (Castagnoli) of a byte string — chunk-frame integrity check."""
    if _lib is None:
        return _crc32c_py(data)
    buf = np.frombuffer(data, dtype=np.uint8)
    if buf.size == 0:
        buf = np.zeros(1, dtype=np.uint8)
        return int(_lib.crc32c(_ptr(buf, ctypes.c_uint8), 0))
    return int(_lib.crc32c(_ptr(buf, ctypes.c_uint8), buf.size))
