"""dedloc_tpu — a TPU-native collaborative deep-learning framework.

Re-imagines the capabilities of DeDLOC (arXiv 2106.10207; reference repo
yhn112/DeDLOC + hivemind 0.9.9) for JAX/XLA on TPU pod slices:

- In-slice data parallelism is a single ``pjit`` step whose gradient mean rides
  ICI collectives (replaces NCCL DDP *and* the intra-group butterfly for
  co-located chips).
- Cross-slice collaboration — a pure-Python asyncio DHT (Kademlia-style record
  store with expiration, subkeys and signed/validated records), DHT-driven
  matchmaking into bounded peer groups, fault-tolerant chunked all-reduce over
  TCP/DCN with fp16/uint8 wire compression and bandwidth-weighted partitioning,
  peer-to-peer state catch-up for late joiners, auxiliary bandwidth-donor peers
  and client-mode (firewalled) peers.

Layer map (mirrors SURVEY.md §1 of the reference analysis):

    transport  dedloc_tpu.dht.protocol      (asyncio TCP + msgpack framing)
    DHT        dedloc_tpu.dht               (routing, storage, validation)
    averaging  dedloc_tpu.averaging         (matchmaking, group all-reduce)
    optimizer  dedloc_tpu.collaborative     (CollaborativeOptimizer)
    training   dedloc_tpu.parallel          (pjit step, mesh, grad-accum,
                                             ring attention, ZeRO-1)
    kernels    dedloc_tpu.ops               (Pallas flash attention)
    models     dedloc_tpu.models            (ALBERT, ResNet-50/SwAV)
    data       dedloc_tpu.data              (MLM+SOP, streaming, multicrop,
                                             tokenizer, prepare CLI)
    eval       dedloc_tpu.finetune          (NER/NCC drivers, linear probe)
    roles      dedloc_tpu.roles             (trainer / coordinator / aux /
                                             dht / swav / fleet)
    auth       dedloc_tpu.core.auth         (gated-run tokens + envelopes)
"""

__version__ = "0.1.0"

from dedloc_tpu.core.timeutils import get_dht_time  # noqa: F401
