"""Phase-loop trainer: hook dispatch around a jitted train step.

Capability of vissl's SelfSupervisionTrainer + standard_train_step (reference:
swav/vissl/vissl/trainer/trainer_main.py:138-204,
train_steps/standard_train_step.py:87-229): a phase (epoch) loop that pulls
batches, runs the train step, and dispatches cross-cutting hooks at defined
points, with per-phase perf timers around read_sample / step / hooks.

TPU-native shape: the reference's per-event torch phases (forward, loss,
backward, optimizer) are ONE fused XLA program here, so ``step_fn`` is an
opaque jitted callable ``(state, batch) -> (state, metrics)`` and the in-step
events (on_forward/on_loss/on_backward/on_update) fire back-to-back after it
returns — they exist so reference-shaped hooks keep working. The host reads
one scalar (the loss) per step; everything else stays on device.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Tuple

import jax

from dedloc_tpu.core.hooks import HookList, LoopContext, default_hooks
from dedloc_tpu.telemetry import steps
from dedloc_tpu.telemetry.steps import StepRecorder
from dedloc_tpu.utils.logging import get_logger
from dedloc_tpu.utils.perf import PerfStats, profiler_trace

logger = get_logger(__name__)

StepFn = Callable[[Any, Any], Tuple[Any, Dict[str, Any]]]


class Trainer:
    """Generic phase-loop driver.

    ``step_fn(state, batch) -> (new_state, metrics)`` with ``metrics["loss"]``
    a device scalar; optional ``metrics["lr"]`` and ``metrics["global_step"]``
    flow into the hook context (the reference feeds the collaboration-wide
    optimizer step into its loss the same way, standard_train_step.py:153).
    """

    def __init__(
        self,
        step_fn: StepFn,
        hooks: Optional[HookList] = None,
        perf: Optional[PerfStats] = None,
        profiler_dir: Optional[str] = None,
        recorder: Optional[StepRecorder] = None,
    ):
        self.step_fn = step_fn
        self.hooks = hooks if hooks is not None else default_hooks()
        self.perf = perf if perf is not None else PerfStats()
        self.profiler_dir = profiler_dir
        # step-phase flight recorder (telemetry/steps.py): no-op while
        # telemetry is disabled; the default instance keeps call sites
        # unconditional
        self.recorder = recorder if recorder is not None else StepRecorder()

    def train(
        self,
        state: Any,
        batches: Iterator[Any],
        max_steps: int,
        steps_per_phase: Optional[int] = None,
        ctx: Optional[LoopContext] = None,
    ) -> Tuple[Any, LoopContext]:
        """Run up to ``max_steps`` steps, split into phases of
        ``steps_per_phase`` (one phase if None). Returns (state, ctx)."""
        steps_per_phase = steps_per_phase or max_steps
        ctx = ctx or LoopContext()
        ctx.max_steps = max_steps
        ctx.perf = self.perf
        ctx.train_state = state

        with profiler_trace(self.profiler_dir):
            self.hooks.dispatch("on_start", ctx)
            while ctx.local_step < max_steps and not ctx.should_stop:
                self.hooks.dispatch("on_phase_start", ctx)
                phase_end = min(ctx.local_step + steps_per_phase, max_steps)
                while ctx.local_step < phase_end and not ctx.should_stop:
                    state = self._one_step(state, batches, ctx)
                self.hooks.dispatch("on_phase_end", ctx)
                ctx.phase += 1
            self.hooks.dispatch("on_end", ctx)
        return state, ctx

    def _one_step(self, state: Any, batches: Iterator[Any], ctx: LoopContext):
        with self.recorder.step(step=ctx.local_step):
            return self._one_step_inner(state, batches, ctx)

    def _one_step_inner(self, state, batches, ctx):
        self.hooks.dispatch("on_step_begin", ctx)
        with self.perf.timer("read_sample"), steps.phase("data_wait"):
            try:
                batch = next(batches)
            except StopIteration:
                ctx.should_stop = True
                return state
        metrics: Dict[str, Any] = {}
        with self.perf.timer("train_step"), steps.phase("fwd_bwd"):
            state, metrics = self.step_fn(state, batch)
            # block on the loss only — the rest of the state stays async
            loss = metrics.get("loss")
            if loss is not None:
                jax.block_until_ready(loss)
        ctx.local_step += 1
        ctx.train_state = state
        ctx.loss = float(metrics["loss"]) if "loss" in metrics else float("nan")
        if "lr" in metrics:
            ctx.lr = float(metrics["lr"])
        if "global_step" in metrics:
            ctx.global_step = int(metrics["global_step"])
        ctx.metrics = {
            k: float(v)
            for k, v in metrics.items()
            if k not in ("global_step",) and _is_scalar(v)
        }
        with self.perf.timer("hooks"), steps.phase("hooks"):
            # fused-step event fan-out (see module docstring)
            for event in ("on_forward", "on_loss", "on_backward", "on_update",
                          "on_step_end"):
                self.hooks.dispatch(event, ctx)
        return state


def _is_scalar(v: Any) -> bool:
    try:
        return getattr(v, "ndim", 0) == 0 or isinstance(v, (int, float))
    except Exception:
        return False
