"""Tensor wire (de)serialization with lossy compression.

Capability parity with the reference's ``CompressionType.Value("FLOAT16")``
wire format for averaging rounds (albert/arguments.py:75-77) plus a
uint8 per-chunk affine quantizer for lower-bandwidth links. The framing is
msgpack (self-describing, protobuf-free — see SURVEY.md §2.7).

All encoders take/return numpy arrays: device arrays are fetched to host by
the caller at the jit↔asyncio seam (SURVEY.md §7 hard-part b).
"""
from __future__ import annotations

import enum
from typing import Any, Dict

import msgpack
import numpy as np

from dedloc_tpu import native


class CompressionType(enum.Enum):
    NONE = "none"
    FLOAT16 = "float16"
    UINT8 = "uint8"  # per-tensor affine quantization with fp32 scale/zero-point


def serialize_array(
    x: np.ndarray,
    compression: CompressionType = CompressionType.NONE,
    checksum: bool = False,
) -> bytes:
    x = np.asarray(x)
    header: Dict[str, Any] = {
        "shape": list(x.shape),
        "dtype": x.dtype.str,
        "compression": compression.value,
    }
    if compression is CompressionType.NONE:
        payload = np.ascontiguousarray(x).tobytes()
    elif compression is CompressionType.FLOAT16:
        if x.dtype == np.float16:
            payload = np.ascontiguousarray(x).tobytes()
        else:
            payload = native.f32_to_f16(x.astype(np.float32, copy=False)).tobytes()
    elif compression is CompressionType.UINT8:
        q, lo, scale = native.quantize_uint8(x.astype(np.float32, copy=False))
        header["lo"], header["scale"] = lo, scale
        payload = q.tobytes()
    else:  # pragma: no cover
        raise ValueError(f"unknown compression {compression}")
    if checksum:
        header["crc"] = native.crc32c(payload)
    return msgpack.packb({"h": header, "p": payload}, use_bin_type=True)


def deserialize_array(data: bytes) -> np.ndarray:
    obj = msgpack.unpackb(data, raw=False)
    header, payload = obj["h"], obj["p"]
    if "crc" in header and native.crc32c(payload) != header["crc"]:
        raise ValueError("wire chunk checksum mismatch (corrupt frame)")
    shape = tuple(header["shape"])
    dtype = np.dtype(header["dtype"])
    compression = CompressionType(header["compression"])
    if compression is CompressionType.NONE:
        return np.frombuffer(payload, dtype=dtype).reshape(shape).copy()
    if compression is CompressionType.FLOAT16:
        h = np.frombuffer(payload, dtype=np.float16).reshape(shape)
        if dtype == np.float16:
            return h.copy()
        return native.f16_to_f32(h).astype(dtype, copy=False)
    if compression is CompressionType.UINT8:
        q = np.frombuffer(payload, dtype=np.uint8).reshape(shape)
        x = native.dequantize_uint8(q, header["lo"], header["scale"])
        return x.astype(dtype, copy=False)
    raise ValueError(f"unknown compression {compression}")  # pragma: no cover


def wire_roundtrip(
    x: np.ndarray, compression: CompressionType
) -> np.ndarray:
    """What the receiving side of the wire reconstructs for ``x`` — encode
    then decode, skipping the msgpack framing. Used by the optimizer's
    error-feedback residual to measure this round's quantization error
    without touching the network."""
    x = np.asarray(x, dtype=np.float32)
    if compression is CompressionType.NONE:
        return x
    if compression is CompressionType.FLOAT16:
        return native.f16_to_f32(native.f32_to_f16(x))
    if compression is CompressionType.UINT8:
        q, lo, scale = native.quantize_uint8(x)
        return native.dequantize_uint8(q, lo, scale).reshape(x.shape)
    raise ValueError(f"unknown compression {compression}")  # pragma: no cover


def serialize_tree(
    tree: Dict[str, np.ndarray],
    compression: CompressionType = CompressionType.NONE,
) -> bytes:
    """Serialize a flat {name: array} mapping (e.g. flattened params/grads)."""
    return msgpack.packb(
        {k: serialize_array(v, compression) for k, v in tree.items()},
        use_bin_type=True,
    )


def deserialize_tree(data: bytes) -> Dict[str, np.ndarray]:
    obj = msgpack.unpackb(data, raw=False)
    return {k: deserialize_array(v) for k, v in obj.items()}


def pack_obj(obj: Any) -> bytes:
    """msgpack helper for small control-plane objects (DHT values, metadata)."""
    return msgpack.packb(obj, use_bin_type=True)


def unpack_obj(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)
