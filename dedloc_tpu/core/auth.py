"""Token authorization for gated public collaborations.

Capability parity with the reference's auth stack
(sahajbert/huggingface_auth.py:46-171): a peer authenticates to an authority
with its credentials, submits its local RSA public key, and receives a
signed ``AccessToken`` (username + peer public key + expiration, signed by
the authority) plus the coordinator endpoint; the token then rides on every
peer-to-peer request, letting any peer verify that its counterparty was
admitted to the run without talking to the authority again. The client
implements the reference's ``TokenAuthorizerBase`` protocol surface:
``get_token`` / ``is_token_valid`` / ``does_token_need_refreshing``.

TPU-native descope: the reference's authority is an HTTPS service
(collaborative-training-auth.huggingface.co) reached through huggingface_hub
login; here the authority is an in-process/object seam
(``AllowlistAuthServer``) a deployment can put behind any transport. The
cryptography (RSA-PSS over a canonical token encoding) is the load-bearing
part and is identical in capability.
"""
from __future__ import annotations

import asyncio
import dataclasses
import hmac
import os
import random
from collections import deque
from typing import Awaitable, Callable, Deque, Dict, Optional, Tuple, TypeVar

from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.dht.crypto import RSAPrivateKey, verify_signature
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

T = TypeVar("T")


@dataclasses.dataclass
class AccessToken:
    """Signed admission ticket (reference: the AccessToken the auth endpoint
    returns, huggingface_auth.py:46-76 consumption sites)."""

    username: str
    peer_public_key: bytes  # DER SubjectPublicKeyInfo of the admitted peer
    expiration_time: float  # DHT time
    signature: bytes = b""

    def signing_bytes(self) -> bytes:
        """Canonical byte encoding covered by the authority's signature."""
        return b" ".join(
            [
                self.username.encode(),
                self.peer_public_key.hex().encode(),
                repr(float(self.expiration_time)).encode(),
            ]
        )

    def to_wire(self) -> Dict:
        return {
            "username": self.username,
            "peer_public_key": self.peer_public_key,
            "expiration_time": self.expiration_time,
            "signature": self.signature,
        }

    @classmethod
    def from_wire(cls, raw: Dict) -> "AccessToken":
        return cls(
            username=str(raw["username"]),
            peer_public_key=bytes(raw["peer_public_key"]),
            expiration_time=float(raw["expiration_time"]),
            signature=bytes(raw["signature"]),
        )


class AuthorizationError(Exception):
    """Raised when the authority rejects a peer or a token fails checks."""


def peer_id_from_public_key(public_key: bytes) -> bytes:
    """Canonical peer identity for gated runs: a digest of the token-bound
    RSA public key. Binding peer ids to keys is what lets receivers check
    that a signed envelope's token actually belongs to the peer identity
    claimed in the payload (no impersonation of other members/leaders)."""
    import hashlib

    return hashlib.sha256(public_key).digest()[:20]


class AllowlistAuthServer:
    """In-process authority: allowlist + credential check -> signed tokens.

    Stand-in for the reference's moderated auth service (the sahajbert run
    gated contributors through an HF-account allowlist). Holds the authority
    keypair; deployments expose ``issue_token`` over any transport.
    """

    def __init__(
        self,
        allowlist: Dict[str, str],  # username -> credential (password/API key)
        token_lifetime: float = 600.0,
        coordinator_endpoint: Optional[str] = None,
        authority_key: Optional[RSAPrivateKey] = None,
    ):
        self._allowlist = dict(allowlist)
        self.token_lifetime = token_lifetime
        self.coordinator_endpoint = coordinator_endpoint
        self._key = authority_key or RSAPrivateKey()

    @property
    def authority_public_key(self) -> bytes:
        return self._key.public_bytes()

    def add_user(self, username: str, credential: str) -> None:
        self._allowlist[username] = credential

    def revoke_user(self, username: str) -> None:
        self._allowlist.pop(username, None)

    def issue_token(
        self, username: str, credential: str, peer_public_key: bytes
    ) -> Dict:
        """Returns {"token": wire-token, "coordinator_endpoint": ...} or
        raises AuthorizationError (non-allowlisted / bad credential)."""
        expected = self._allowlist.get(username)
        if (
            credential is None
            or expected is None
            # bytes comparison: compare_digest on str raises for non-ASCII
            or not hmac.compare_digest(
                expected.encode("utf-8"), credential.encode("utf-8")
            )
        ):
            raise AuthorizationError(f"user {username!r} is not authorized")
        token = AccessToken(
            username=username,
            peer_public_key=peer_public_key,
            expiration_time=get_dht_time() + self.token_lifetime,
        )
        token.signature = self._key.sign(token.signing_bytes())
        return {
            "token": token.to_wire(),
            "coordinator_endpoint": self.coordinator_endpoint,
        }


class TokenAuthorizerBase:
    """The reference's authorizer protocol (hivemind TokenAuthorizerBase as
    implemented by HuggingFaceAuthorizer, huggingface_auth.py:46-143):
    subclasses fetch tokens; this base owns validity/refresh logic and the
    local keypair."""

    def __init__(self, local_key: Optional[RSAPrivateKey] = None):
        self.local_private_key = local_key or RSAPrivateKey()
        self.local_public_key = self.local_private_key.public_bytes()
        self._token: Optional[AccessToken] = None

    async def get_token(self) -> AccessToken:
        raise NotImplementedError

    def is_token_valid(self, token: AccessToken) -> bool:
        raise NotImplementedError

    def does_token_need_refreshing(
        self, token: AccessToken, refresh_margin: float = 30.0
    ) -> bool:
        return get_dht_time() + refresh_margin >= token.expiration_time

    async def refresh_token_if_needed(self) -> AccessToken:
        if self._token is None or self.does_token_need_refreshing(self._token):
            self._token = await self.get_token()
            if not self.is_token_valid(self._token):
                raise AuthorizationError("authority returned an invalid token")
        return self._token


class AllowlistAuthorizer(TokenAuthorizerBase):
    """Client against an ``AllowlistAuthServer``-shaped authority.

    ``issue_fn(username, credential, peer_public_key)`` is the transport
    seam: the in-process server's ``issue_token`` in tests, an HTTPS call in
    a deployment.
    """

    def __init__(
        self,
        username: str,
        credential: str,
        issue_fn: Callable[[str, str, bytes], Dict],
        authority_public_key: bytes,
        local_key: Optional[RSAPrivateKey] = None,
    ):
        super().__init__(local_key)
        self.username = username
        self._credential = credential
        self._issue_fn = issue_fn
        self.authority_public_key = authority_public_key
        self.coordinator_endpoint: Optional[str] = None

    async def get_token(self) -> AccessToken:
        response = await call_with_retries(
            lambda: _maybe_async(
                self._issue_fn, self.username, self._credential,
                self.local_public_key,
            ),
            retryable=(OSError, TimeoutError),
        )
        self.coordinator_endpoint = response.get("coordinator_endpoint")
        return AccessToken.from_wire(response["token"])

    def is_token_valid(self, token: AccessToken) -> bool:
        if token.expiration_time < get_dht_time():
            return False
        # the token must be bound to THIS peer — a validly-signed token for
        # another peer's key would pass signature checks but every envelope
        # we sign would then be rejected by counterparties
        if token.username != self.username:
            return False
        if token.peer_public_key != self.local_public_key:
            return False
        if not verify_signature(
            self.authority_public_key, token.signing_bytes(), token.signature
        ):
            return False
        return True


# ------------------------------------------------------- request envelopes


def _envelope_signing_bytes(
    payload: bytes, nonce: bytes, timestamp: float, context: bytes = b""
) -> bytes:
    # Length-prefix every variable-length field so the signed encoding is
    # unambiguous: payload and nonce are unconstrained bytes, and a
    # delimiter-joined encoding would let an attacker shift the
    # payload/nonce boundary whenever the nonce happened to contain the
    # delimiter, defeating the replay guard's nonce memory.
    ts_bytes = repr(float(timestamp)).encode()
    return b"".join(
        len(field).to_bytes(8, "big") + field
        for field in (context, payload, nonce, ts_bytes)
    )


def wrap_request(
    token: AccessToken,
    payload: bytes,
    sender_key: RSAPrivateKey,
    context: bytes = b"",
) -> Dict:
    """Signed request envelope: the token proves admission (authority
    signature); the sender signature covers payload + a fresh nonce + a
    timestamp + the caller-chosen ``context`` (e.g. round id + recipient
    identity), so a captured envelope can be replayed neither later NOR at a
    different recipient/round (hivemind's AuthRPCWrapper includes
    per-request nonces for the same reason)."""
    nonce = os.urandom(16)
    timestamp = get_dht_time()
    return {
        "token": token.to_wire(),
        "payload": payload,
        "nonce": nonce,
        "timestamp": timestamp,
        "payload_signature": sender_key.sign(
            _envelope_signing_bytes(payload, nonce, timestamp, context)
        ),
    }


class ReplayGuard:
    """Remembers recently-seen nonces within the freshness window.

    Nonces are kept both in a set (O(1) membership) and in an
    insertion-ordered deque of ``(first_seen, nonce)``; because ``now`` is
    monotone across calls the deque stays time-sorted, so each call only
    pops the aged prefix — O(1) amortized instead of a full-dict rebuild
    per request on the leader's admission path."""

    def __init__(self, max_age: float = 60.0):
        self.max_age = max_age
        self._seen: set = set()
        self._order: Deque[Tuple[float, bytes]] = deque()

    def check_and_remember(self, nonce: bytes, now: float) -> bool:
        """False if the nonce was already seen (replay). Expires old ones."""
        while self._order and now - self._order[0][0] > self.max_age:
            _, old = self._order.popleft()
            self._seen.discard(old)
        if nonce in self._seen:
            return False
        self._seen.add(nonce)
        self._order.append((now, nonce))
        return True


def unwrap_request(
    envelope: Dict,
    authority_public_key: bytes,
    now: Optional[float] = None,
    replay_guard: Optional[ReplayGuard] = None,
    max_age: float = 60.0,
    context: bytes = b"",
    return_token: bool = False,
):
    """Validate an envelope and return its payload (or ``(payload, token)``
    with ``return_token`` — callers use the token to bind the sender's key
    to the identity claimed in the payload), or raise AuthorizationError.
    Checks: token signature (authority), token expiry, sender signature over
    context+payload+nonce+timestamp (``context`` must match what the sender
    bound), freshness (``max_age``), and — when a ``replay_guard`` is
    supplied — nonce uniqueness."""
    token = AccessToken.from_wire(envelope["token"])
    if not verify_signature(
        authority_public_key, token.signing_bytes(), token.signature
    ):
        raise AuthorizationError("token signature invalid")
    t_now = now if now is not None else get_dht_time()
    if token.expiration_time < t_now:
        raise AuthorizationError("token expired")
    payload = bytes(envelope["payload"])
    nonce = bytes(envelope["nonce"])
    timestamp = float(envelope["timestamp"])
    if abs(t_now - timestamp) > max_age:
        raise AuthorizationError("request envelope is stale")
    if not verify_signature(
        token.peer_public_key,
        _envelope_signing_bytes(payload, nonce, timestamp, context),
        bytes(envelope["payload_signature"]),
    ):
        raise AuthorizationError("payload signature invalid")
    if replay_guard is not None and not replay_guard.check_and_remember(
        nonce, t_now
    ):
        raise AuthorizationError("replayed request envelope")
    return (payload, token) if return_token else payload


# ---------------------------------------------------------------- retries


async def call_with_retries(
    fn: Callable[[], Awaitable[T]],
    n_retries: int = 3,
    base_delay: float = 0.5,
    retryable: tuple = (Exception,),
) -> T:
    """Exponential backoff with jitter (the reference's retry helper around
    the auth endpoint, huggingface_auth.py:23-35)."""
    for attempt in range(n_retries + 1):
        try:
            return await fn()
        except retryable as e:
            if attempt == n_retries:
                raise
            delay = base_delay * (2 ** attempt) * (0.5 + random.random())
            logger.warning(
                f"auth call failed ({e!r}); retry {attempt + 1}/{n_retries} "
                f"in {delay:.1f}s"
            )
            await asyncio.sleep(delay)
    raise AssertionError("unreachable")


async def _maybe_async(fn, *args):
    result = fn(*args)
    if asyncio.iscoroutine(result):
        return await result
    return result


# ------------------------------------------------- network token service


class AuthService:
    """Network face of ``AllowlistAuthServer``: attaches ``auth.issue`` to
    an ``RPCServer`` (typically the coordinator's DHT node server) so
    volunteers obtain signed access tokens with one RPC — the capability of
    the reference's hosted auth endpoint (sahajbert/huggingface_auth.py:
    46-143 PUTs the peer's public key and receives a signed AccessToken +
    coordinator address)."""

    def __init__(self, server, auth_server: AllowlistAuthServer):
        self.auth = auth_server
        server.register("auth.issue", self._rpc_issue)

    async def _rpc_issue(self, peer, args) -> Dict:
        response = self.auth.issue_token(
            args["username"],
            args["credential"],
            bytes.fromhex(args["public_key"]),
        )
        response["authority_public_key"] = (
            self.auth.authority_public_key.hex()
        )
        return response


def remote_token_issuer(endpoint) -> Callable:
    """``issue_fn`` for ``AllowlistAuthorizer`` that calls a remote
    ``AuthService`` (async — runs inside the DHT event loop on refresh)."""

    async def issue(username: str, credential: str, public_key: bytes) -> Dict:
        from dedloc_tpu.dht.protocol import RPCClient

        client = RPCClient(request_timeout=10.0)
        try:
            return await client.call(
                endpoint,
                "auth.issue",
                {
                    "username": username,
                    "credential": credential,
                    "public_key": public_key.hex(),
                },
            )
        finally:
            await client.close()

    return issue


def remote_auth_handshake(
    endpoint, username: str, credential: str,
    local_key: Optional[RSAPrivateKey] = None,
) -> "AllowlistAuthorizer":
    """Join-time auth (contributor notebook cell 2 capability): fetch the
    first token synchronously — failing fast on bad credentials — and build
    an authorizer that refreshes over the same endpoint. The authority
    public key is taken from the endpoint's reply (trust-on-first-use;
    organizers can distribute it out of band and compare)."""
    import asyncio

    key = local_key or RSAPrivateKey()
    issue = remote_token_issuer(endpoint)

    async def first():
        return await issue(username, credential, key.public_bytes())

    response = asyncio.run(first())
    authority = bytes.fromhex(response["authority_public_key"])
    authorizer = AllowlistAuthorizer(
        username, credential, issue, authority, local_key=key
    )
    # seed the freshly-issued token so the first round needs no second RPC
    token = AccessToken.from_wire(response["token"])
    authorizer._token = token  # noqa: SLF001 — warm the cache
    authorizer.coordinator_endpoint = response.get("coordinator_endpoint")
    return authorizer
