"""Training-loop hook pipeline.

Capability of the vissl/ClassyVision hook system (reference:
swav/vissl/vissl/hooks/__init__.py:54-..., hooks/state_update_hooks.py,
hooks/log_hooks.py): cross-cutting behavior attached to well-defined points of
the train loop, dispatched over an ordered hook list.

TPU-native shape: the reference dispatches on_forward/on_backward/on_update
separately because torch executes them eagerly; under jit the forward,
backward, and optimizer update are ONE fused XLA program, so the in-step
events fire back-to-back at the jit boundary with the same context. Work that
must happen *inside* the compiled step (prototype renormalization,
freeze-by-zeroing-grads, sinkhorn) lives in the jitted step functions
(models/swav.py) — hooks are the host-side seam.
"""
from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable, Dict, List, Optional

from dedloc_tpu.utils.logging import get_logger
from dedloc_tpu.utils.perf import PerfStats

logger = get_logger(__name__)

EVENTS = (
    "on_start",
    "on_phase_start",
    "on_step_begin",
    "on_forward",
    "on_loss",
    "on_backward",
    "on_update",
    "on_step_end",
    "on_phase_end",
    "on_end",
)


@dataclasses.dataclass
class LoopContext:
    """Mutable state threaded through every hook call.

    The hook-visible analogue of vissl's ``task`` object: current progress,
    last step's host-side metrics, and an extras dict for hook-to-hook
    communication (e.g. the trainer deposits the jitted step's outputs here).
    """

    phase: int = 0
    local_step: int = 0
    global_step: int = 0
    loss: float = math.nan
    lr: float = math.nan
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
    train_state: Any = None
    max_steps: Optional[int] = None
    perf: PerfStats = dataclasses.field(default_factory=PerfStats)
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)
    should_stop: bool = False


class Hook:
    """Base hook: every event is a no-op. Subclass and override.

    Mirrors ClassyHook's function set (SSLClassyHookFunctions,
    vissl/hooks/__init__.py) with snake_case TPU-loop semantics.
    """

    def on_start(self, ctx: LoopContext) -> None: ...
    def on_phase_start(self, ctx: LoopContext) -> None: ...
    def on_step_begin(self, ctx: LoopContext) -> None: ...
    def on_forward(self, ctx: LoopContext) -> None: ...
    def on_loss(self, ctx: LoopContext) -> None: ...
    def on_backward(self, ctx: LoopContext) -> None: ...
    def on_update(self, ctx: LoopContext) -> None: ...
    def on_step_end(self, ctx: LoopContext) -> None: ...
    def on_phase_end(self, ctx: LoopContext) -> None: ...
    def on_end(self, ctx: LoopContext) -> None: ...


class HookList:
    """Ordered hook dispatch (vissl runs hooks in registration order)."""

    def __init__(self, hooks: Optional[List[Hook]] = None):
        self.hooks: List[Hook] = list(hooks or [])

    def add(self, hook: Hook) -> None:
        self.hooks.append(hook)

    def dispatch(self, event: str, ctx: LoopContext) -> None:
        if event not in EVENTS:
            raise ValueError(f"unknown hook event {event!r}; known: {EVENTS}")
        for hook in self.hooks:
            getattr(hook, event)(ctx)


class CheckNanLossHook(Hook):
    """Raise FloatingPointError on non-finite loss.

    Capability of vissl's CheckNanLossHook (state_update_hooks.py:207-233).
    The collaborative trainer additionally has state *rollback* on non-finite
    params (collaborative/optimizer.py NaN guard, run_trainer.py:134-137
    capability) — this hook is the fail-fast variant for the phase-loop
    trainer, where a NaN loss means the run is broken, not the averaging.
    """

    def on_loss(self, ctx: LoopContext) -> None:
        if not math.isfinite(ctx.loss):
            raise FloatingPointError(
                f"non-finite loss {ctx.loss} at local step {ctx.local_step}"
            )


class LogLossLrEtaHook(Hook):
    """Periodic progress log: loss, lr, steps/sec, ETA.

    Capability of vissl's LogLossLrEtaHook (log_hooks.py:114-209).
    """

    def __init__(self, log_every: int = 10):
        self.log_every = max(1, log_every)
        self._t0: Optional[float] = None
        self._step0 = 0

    def on_phase_start(self, ctx: LoopContext) -> None:
        self._t0 = time.perf_counter()
        self._step0 = ctx.local_step

    def on_step_end(self, ctx: LoopContext) -> None:
        if ctx.local_step % self.log_every:
            return
        rate = eta = float("nan")
        if self._t0 is not None:
            elapsed = time.perf_counter() - self._t0
            steps = max(ctx.local_step - self._step0, 1)
            rate = steps / max(elapsed, 1e-9)
            if ctx.max_steps:
                eta = (ctx.max_steps - ctx.local_step) / max(rate, 1e-9)
        logger.info(
            f"step {ctx.local_step}"
            + (f"/{ctx.max_steps}" if ctx.max_steps else "")
            + f" (global {ctx.global_step}): loss {ctx.loss:.4f}"
            + ("" if math.isnan(ctx.lr) else f" lr {ctx.lr:.3e}")
            + f" | {rate:.2f} steps/s"
            + ("" if math.isnan(eta) else f" eta {eta:.0f}s")
        )


class LogPerfMetricsHook(Hook):
    """Emit the PerfStats table every N steps and at phase end.

    Capability of vissl's LogPerfTimeMetricsHook (log_hooks.py:420-...).
    """

    def __init__(self, log_every: int = 100):
        self.log_every = max(1, log_every)

    def on_step_end(self, ctx: LoopContext) -> None:
        if ctx.perf.enabled and ctx.local_step % self.log_every == 0:
            logger.info("perf stats @ step %d\n%s", ctx.local_step, ctx.perf.report_str())

    def on_phase_end(self, ctx: LoopContext) -> None:
        if ctx.perf.enabled and ctx.perf.metrics:
            logger.info("perf stats @ phase %d end\n%s", ctx.phase, ctx.perf.report_str())


class DeviceStatsHook(Hook):
    """Periodic accelerator memory stats (vissl LogGpuStatsHook /
    LogGpuMemoryHook capability, log_hooks.py:26-113) via PJRT
    ``memory_stats()`` — HBM in use / peak per local device. Backends that
    expose no stats (CPU) log nothing."""

    def __init__(self, log_every: int = 100):
        self.log_every = max(1, log_every)

    def on_step_end(self, ctx: LoopContext) -> None:
        if ctx.local_step % self.log_every:
            return
        import jax

        lines = []
        for dev in jax.local_devices():
            stats = getattr(dev, "memory_stats", lambda: None)()
            if not stats:
                continue
            in_use = stats.get("bytes_in_use", 0) / 2**30
            peak = stats.get("peak_bytes_in_use", 0) / 2**30
            limit = stats.get("bytes_limit", 0) / 2**30
            lines.append(
                f"{dev.platform}:{dev.id} {in_use:.2f}GiB in use, "
                f"peak {peak:.2f}GiB"
                + (f" / {limit:.2f}GiB" if limit else "")
            )
        if lines:
            logger.info(
                "device memory @ step %d: %s", ctx.local_step,
                " | ".join(lines),
            )


class CheckpointHook(Hook):
    """Periodic + phase-end checkpointing through a caller-provided save_fn.

    Capability of vissl's LogLossMetricsCheckpointHook (log_hooks.py:268-330):
    mid-phase saves every ``every`` steps (CHECKPOINT_ITER_FREQUENCY) and a
    save at every phase end. ``save_fn(ctx)`` owns layout/retention
    (utils/checkpoint.py provides both).
    """

    def __init__(self, save_fn: Callable[[LoopContext], None], every: int = 0):
        self.save_fn = save_fn
        self.every = every

    def on_step_end(self, ctx: LoopContext) -> None:
        if self.every and ctx.local_step and ctx.local_step % self.every == 0:
            self.save_fn(ctx)

    def on_phase_end(self, ctx: LoopContext) -> None:
        self.save_fn(ctx)


class MetricsPublisherHook(Hook):
    """Publish per-step metrics through a callback (DHT metrics bus seam).

    The phase-loop analogue of CollaborativeCallback.on_step_end publishing
    LocalMetrics to the DHT (albert/run_trainer.py:146-166): the trainer owns
    *what* to publish; this hook owns *when* (every global-step advance).
    """

    def __init__(self, publish_fn: Callable[[LoopContext], None]):
        self.publish_fn = publish_fn
        self._last_global = -1

    def on_step_end(self, ctx: LoopContext) -> None:
        if ctx.global_step != self._last_global:
            self._last_global = ctx.global_step
            self.publish_fn(ctx)


def default_hooks(
    log_every: int = 10,
    perf_log_every: int = 100,
    save_fn: Optional[Callable[[LoopContext], None]] = None,
    save_every: int = 0,
    device_stats_every: int = 0,
) -> HookList:
    """The standard pipeline (vissl default_hook_generator capability):
    NaN check → progress log → perf log → optional device-memory log →
    optional checkpointing."""
    hooks = HookList([CheckNanLossHook(), LogLossLrEtaHook(log_every),
                      LogPerfMetricsHook(perf_log_every)])
    if device_stats_every:
        hooks.add(DeviceStatsHook(device_stats_every))
    if save_fn is not None:
        hooks.add(CheckpointHook(save_fn, save_every))
    return hooks
