"""Typed configuration tree + component registry.

The reference uses two config idioms — layered HfArgumentParser dataclasses
(albert/arguments.py:7-128) and Hydra AttrDict composition (vissl) with
string-keyed registries (register_optimizer / register_loss / ...). Per
SURVEY.md §5 the TPU build unifies both into ONE idiom: plain dataclass trees
(parseable from CLI) + a generic Registry.
"""
from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field, fields, is_dataclass
from typing import Any, Callable, Dict, List, Optional, Type, TypeVar, get_args, get_origin

T = TypeVar("T")


class Registry:
    """String-keyed component registry (models, optimizers, losses, datasets).

    Replaces vissl/ClassyVision's per-kind ``register_*`` decorators
    (reference: classy_vision/optim/__init__.py:114-124 et al.).
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, Any] = {}

    def register(self, name: str) -> Callable[[T], T]:
        def deco(obj: T) -> T:
            if name in self._entries:
                raise KeyError(f"{self.kind} {name!r} already registered")
            self._entries[name] = obj
            return obj

        return deco

    def get(self, name: str) -> Any:
        if name not in self._entries:
            raise KeyError(
                f"unknown {self.kind} {name!r}; known: {sorted(self._entries)}"
            )
        return self._entries[name]

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def names(self) -> List[str]:
        return sorted(self._entries)


MODELS = Registry("model")
OPTIMIZERS = Registry("optimizer")
LOSSES = Registry("loss")
DATASETS = Registry("dataset")
SCHEDULES = Registry("schedule")


def _add_dataclass_args(
    parser: argparse.ArgumentParser, cls: Type, prefix: str = "", defaults: Any = None
):
    import typing

    # Defaults come from an INSTANCE of cls so that a parent's
    # default_factory override (e.g. SwAVCollaborationArguments setting
    # target_batch_size=32768 on its optimizer field) survives into the CLI
    # defaults instead of being shadowed by the nested class's own field
    # defaults.
    if defaults is None:
        defaults = cls()
    hints = typing.get_type_hints(cls)
    for f in fields(cls):
        ftype = hints.get(f.name, f.type)
        if is_dataclass(ftype):
            _add_dataclass_args(
                parser,
                ftype,
                prefix=f"{prefix}{f.name}.",
                defaults=getattr(defaults, f.name),
            )
            continue
        name = f"--{prefix}{f.name}"
        origin = get_origin(ftype)
        if origin is Optional or (origin is type(None)):
            ftype = get_args(ftype)[0]
        elif origin is not None and type(None) in get_args(ftype):
            ftype = next(a for a in get_args(ftype) if a is not type(None))
        default = getattr(defaults, f.name)
        if ftype is bool:
            parser.add_argument(name, type=lambda s: s.lower() in ("1", "true", "yes"),
                                default=default)
        elif get_origin(ftype) in (list, List):
            parser.add_argument(name, nargs="*", type=get_args(ftype)[0] if get_args(ftype) else str,
                                default=default)
        elif ftype in (int, float, str):
            parser.add_argument(name, type=ftype, default=default)
        else:
            parser.add_argument(name, type=str, default=default)


def parse_config(cls: Type[T], argv: Optional[List[str]] = None) -> T:
    """Parse a (possibly nested) dataclass config from CLI flags.

    Nested fields use dotted flags: ``--dht.initial_peers host:port``.
    Replaces the reference's HfArgumentParser multi-dataclass pattern
    (albert/run_trainer.py:211-212).
    """
    parser = argparse.ArgumentParser()
    _add_dataclass_args(parser, cls)
    ns = vars(parser.parse_args(argv))

    import typing

    def build(c: Type, prefix: str = ""):
        hints = typing.get_type_hints(c)
        kwargs = {}
        for f in fields(c):
            ftype = hints.get(f.name, f.type)
            if is_dataclass(ftype):
                kwargs[f.name] = build(ftype, prefix=f"{prefix}{f.name}.")
            else:
                kwargs[f.name] = ns[f"{prefix}{f.name}"]
        return c(**kwargs)

    return build(cls)


# ---------------------------------------------------------------------------
# The canonical argument tree, mirroring the reference's 3-layer flag system
# (albert/arguments.py:7-101) with TPU-native additions.
# ---------------------------------------------------------------------------


@dataclass
class DHTArguments:
    """Reference: BaseTrainingArguments (albert/arguments.py:7-20)."""

    experiment_prefix: str = "dedloc_tpu"
    initial_peers: List[str] = field(default_factory=list)  # "host:port" strings
    listen_host: str = "0.0.0.0"
    listen_port: int = 0  # 0 = ephemeral
    # public address other peers should dial (the reference coordinator
    # resolves its public IP the same way, run_first_peer.py:153-155);
    # empty = loopback (single-host runs)
    advertised_host: str = ""
    client_mode: bool = False  # outbound-only peer (albert/arguments.py:63-65)
    # "host:port[,host2:port2,…]" of public peers: a client-mode peer
    # registers with every listed circuit relay (k-redundant, like the
    # reference's several bootstrap nodes) and becomes able to lead groups
    # / host spans through them; if the advertised relay dies, the peer
    # fails over to a live backup automatically
    relay: str = ""


@dataclass
class AveragerArguments:
    """Reference: AveragerArguments (albert/arguments.py:22-54)."""

    averaging_expiration: float = 5.0  # wait-for-stragglers window
    averaging_timeout: float = 30.0  # hard abort for a round
    min_refresh_period: float = 0.5
    max_refresh_period: float = 30.0
    default_refresh_period: float = 3.0
    expected_drift_peers: float = 3.0
    expected_drift_rate: float = 0.2
    performance_ema_alpha: float = 0.1
    target_group_size: int = 256
    metadata_expiration: float = 30.0
    compression: str = "float16"  # none | float16 | uint8 — wire format for
    # averaging rounds (core/serialization.py; the native F16C codec when
    # loaded). Lossy formats pair with the optimizer's error feedback so the
    # quantization residual never biases the trunk (docs/fleet.md).
    # elements per wire chunk in the pipelined all-reduce: spans are split
    # into fixed-size chunks so hosts reduce (and the all-gather streams
    # back) each chunk as it arrives instead of stalling on monolithic
    # spans. <= 0 restores the monolithic-span wire format. Default 128Ki
    # fp32 elements = 512 KiB raw per message.
    chunk_size: int = 131072
    bandwidth: float = 1000.0  # advertised Mbps, for weighted partitioning
    # fixed port for the averager's own RPC server (0 = ephemeral). A
    # listening averager doubles as a circuit relay, so give PUBLIC peers a
    # fixed port here and point client-mode volunteers' --dht.relay at it.
    listen_port: int = 0
    # retrying state sync (peer-lifecycle robustness): a state download is
    # retried up to state_sync_retries times with exponential backoff
    # starting at state_sync_backoff seconds; each attempt refreshes the
    # provider list and prefers providers that have not failed yet, and
    # every snapshot is checksum-validated — so a dead or corrupt provider
    # costs one backoff instead of a failed join
    state_sync_retries: int = 2
    state_sync_backoff: float = 0.5
    # hierarchical (two-level) adaptive averaging (averaging/topology.py;
    # docs/fleet.md "when to enable hierarchical averaging"): path to a
    # TopologyPlan JSON partitioning the swarm into low-RTT cliques with
    # one delegate each — clique members reduce over cheap local links,
    # delegates carry the weight-summed contribution into the WAN round.
    # Generate with ``runlog_summary --topology`` (plan section) from a
    # run's link telemetry. Empty = today's flat butterfly; a plan whose
    # mode is "flat" is also a no-op, and any mid-round failure falls
    # back to a flat retry of the same round automatically.
    topology_plan: str = ""
    # live re-planning (averaging/planwire.py): follow the coordinator's
    # epoch-versioned plan record on the DHT and adopt the newest valid
    # plan between rounds — the closed adaptation loop (docs/fleet.md
    # "closed-loop operations"). Pinning --averager.topology_plan above
    # DISABLES following (the manual opt-out); plan_follow=false disables
    # it outright even without a pin.
    plan_follow: bool = True
    plan_refresh_period: float = 30.0  # seconds between plan-record polls
    # contribution-ledger receipts (telemetry/ledger.py): countersign each
    # averaging round's group envelope into a signed RoundReceipt DHT
    # record, making group-mates' cumulative claims checkable by the
    # coordinator fold (docs/observability.md "signed contribution ledger")
    ledger_receipts: bool = True


@dataclass
class CollaborativeOptimizerArguments:
    """Reference: CollaborativeOptimizerArguments (albert/arguments.py:56-77)."""

    target_batch_size: int = 4096
    batch_size_lead: int = 0
    statistics_expiration: float = 600.0
    # serve model+opt state to late joiners (p2p state transfer); turn off on
    # solo/benchmark runs to keep the device↔host link free for dispatch
    allow_state_sharing: bool = True
    # cap each peer's CONTRIBUTED per-micro-batch mean gradient at
    # clip * (samples per micro-batch) before averaging (0 = off) — the
    # contributed tree is grad_acc / n_acc where n_acc counts MICRO-batches,
    # so with gradient accumulation the cap pairs with the micro-batch
    # sample count, not the boundary total. Sample-
    # weighted averaging assumes equal per-sample gradient quality; a
    # tiny-batch peer violates that hard (measured on SwAV ResNet-50 at
    # init: a B=2 boundary mean has global norm 56.7 = 28.4/sample vs a
    # B=16 one at 23.6 = 1.47/sample — 19x the per-sample energy, nearly
    # all sinkhorn noise) and its noise steers the group's averaged
    # direction. The cap is linear in the peer's own samples, so it
    # self-calibrates across batch sizes: at 2.0/sample it never binds a
    # healthy B=16 peer (1.47 at init, 0.31 trained) and suppresses the
    # B=2 outlier 14x. SwAV runs default it on (roles/swav.py).
    contrib_clip_per_sample: float = 0.0
    # contribution ramp (0 = off): a joining peer's averaging weight scales
    # linearly from 1/(ramp_rounds+1) of its sample count to its full
    # sample count over its first ramp_rounds completed global steps. The
    # joiner RECEIVES the group's averaged direction from round one but
    # barely perturbs it while its params settle into the group's basin —
    # the enforced form of "onboard volunteers onto a formed trunk"
    # (docs/fleet.md; measured: unramped from-scratch SwAV fleets probe
    # 13.0% vs the 22.4% solo bar). SwAV runs default it on.
    ramp_rounds: int = 0
    # trunk-health gate (0 = off): while this peer's advertised loss
    # exceeds ratio x the median advertised loss of the OTHER trainers, it
    # defers mixing entirely — contributing zero weight but still adopting
    # the group average — until its loss rejoins the pack. Engages only
    # for peers that report a loss (roles do, once per global step), and
    # only while the swarm median is POSITIVE (a multiplicative ratio
    # inverts on zero/negative losses). A gated peer never applies its
    # suspect gradients locally either: with no group average received it
    # drops them and resyncs state.
    health_gate_loss_ratio: float = 0.0
    # residual error feedback for lossy wire compression (on by default;
    # no-op under --averager.compression none): each round's quantization
    # error is added back into the next round's contribution, keeping the
    # averaged trunk unbiased under float16/uint8 wire formats
    # (collaborative/error_feedback.py, docs/fleet.md)
    error_feedback: bool = True
    # opt-in background averaging: launch the averaging round at the
    # boundary and keep accumulating the next microbatches; the averaged
    # update applies when the round lands — ONE boundary late (bounded
    # staleness). Auto-disables during the contribution ramp, while
    # health-gated, and around state sync; a failed overlapped round falls
    # back to synchronous averaging (docs/fleet.md staleness contract).
    overlap_averaging: bool = False
    # contribution-ledger claims (telemetry/ledger.py): periodically
    # publish this peer's signed cumulative ContributionClaim DHT record
    # (samples, rounds, wall-seconds, bytes served) so the coordinator can
    # fold it against group-mates' receipts into the volunteer leaderboard
    # (docs/observability.md "signed contribution ledger")
    ledger_claims: bool = True
    claim_period: float = 30.0  # dht-time seconds between claim refreshes
    # device-resident flat gradient pipeline (averaging/device_flat.py):
    # the boundary's mean/clip/error-feedback/quantize run in ONE fused jit
    # on the accelerator, and the (compressed, under fp16/uint8 wire
    # formats) flat buffer streams to the host in async chunks overlapped
    # with matchmaking / accumulation. Off restores the legacy per-leaf
    # device_get + host flatten + host codec path.
    device_flat: bool = True
    # fused flat optimizer apply (optim/flat.py + make_flat_apply_step):
    # the averaged result crosses host->device as ONE buffer and the whole
    # LAMB update runs as segment reductions over it, with the NaN guard
    # fused in. Per-leaf guarded apply otherwise. Fleet-wide choice, like
    # --averager.compression: peers should agree so replica params evolve
    # identically (the flat math agrees with the per-leaf chain to float32
    # reduction-order, ~1e-7 relative — see docs/perf.md round 6).
    flat_apply: bool = True


@dataclass
class TrainingArguments:
    """Local-step recipe, mirroring AlbertTrainingArguments
    (albert/arguments.py:104-128)."""

    model_size: str = "large"  # tiny (CI fixture) | large
    # override model remat: nothing|dots|dots_no_batch|dots_no_batch_attn|
    # fused_ln|fused_ln_gelu (fused_ln — saved Pallas outputs + named
    # matmuls, pairs the fused add+LN kernel on automatically — is the
    # fastest measured policy for the seq-512 recipe on v5e; the policy
    # table lives in models/albert.py, measurements in docs/perf.md)
    remat_policy: str = ""
    attention_impl: str = ""  # override: dense|blockwise|flash|ring
    vocab_size: int = 0  # override model vocab (0 = size default); must cover
    # the dataset tokenizer's vocab (checked against the shard dir's meta.json)
    dataset_path: str = ""  # tokenized dataset dir; empty = synthetic fixture
    # streaming mode (sahajbert capability): one-document-per-line text
    # files mixed by weight, tokenized on the fly (needs tokenizer_path)
    streaming_files: List[str] = field(default_factory=list)
    streaming_weights: List[float] = field(default_factory=list)
    streaming_buffer_size: int = 10_000
    tokenizer_path: str = ""  # trained tokenizer.json for streaming mode
    max_local_steps: int = 0  # stop after N accumulation boundaries (0 = run forever)
    seq_length: int = 512
    per_device_batch_size: int = 4
    # >1: this peer is a whole slice — a data-parallel mesh over N local
    # devices; the per-micro-batch grad mean rides ICI psums and the slice
    # acts as ONE collaboration member (SURVEY.md §2.6 TPU-native mapping)
    mesh_devices: int = 1
    mesh_device_offset: int = 0  # carve disjoint device ranges (tests)
    # sequence parallelism: factor of mesh_devices assigned to a "seq" mesh
    # axis; with attention_impl="ring" the attention KV shards rotate around
    # that axis (ring attention) so no device ever holds the full S×S scores
    mesh_seq_devices: int = 1
    # tensor parallelism: factor of mesh_devices assigned to a "model" mesh
    # axis — params/grads/moments shard by the Megatron-style ALBERT rules
    # (parallel/sharding.py) and XLA inserts the ICI collectives. Composes
    # with data/seq axes and zero_sharding (ZeRO then shards only the
    # moments TP left replicated).
    mesh_model_devices: int = 1
    # pipeline parallelism: factor of mesh_devices assigned to a "pipe" mesh
    # axis — ALBERT's shared block staged across it (GPipe microbatch
    # schedule under shard_map, parallel/pipeline.py). Composes with the
    # data axis; "seq"/"model" axes need collectives inside the stage and
    # are rejected. Checkpoints/grad schemas match the non-pipelined model.
    mesh_pipe_devices: int = 1
    # microbatches per boundary on the pipe (0 = 2 x stages); bubble
    # fraction = (stages-1)/(microbatches+stages-1)
    pipe_microbatches: int = 0
    # expert parallelism: factor of mesh_devices assigned to an "expert"
    # mesh axis — the MoE FFN's experts shard over it (requires
    # moe_experts % mesh_expert_devices == 0); the Switch dispatch einsums
    # lower to XLA all-to-alls (parallel/moe.py)
    mesh_expert_devices: int = 1
    # >0: replace the dense FFN with a Switch-routed mixture of this many
    # experts (shared across ALBERT's layer iterations). The load-balancing
    # aux loss is added at moe_aux_weight.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # ZeRO-1: shard optimizer moments over the slice mesh's data axis
    # (state memory / n_devices; params+grads stay replicated for the
    # cross-slice averager). Requires mesh_devices > 1.
    zero_sharding: bool = False
    gradient_accumulation_steps: int = 2
    learning_rate: float = 0.00176
    warmup_steps: int = 5000
    total_steps: int = 125_000
    weight_decay: float = 0.01
    max_grad_norm: float = 1.0
    clamp_value: float = 10000.0
    seed: int = 0
    output_dir: str = "outputs"
    save_steps: int = 500
    save_total_limit: int = 2
    # telemetry (vissl PerfStats capability on the flagship path):
    train_log_path: str = ""  # per-global-step JSONL: wall/step/loss/phases
    log_perf_steps: int = 0  # log a PerfStats phase report every N global steps


@dataclass
class CheckpointArguments:
    """Swarm checkpointing (dedloc_tpu/checkpointing, docs/fleet.md restart
    runbook): the shared state is also served as a signed manifest + fixed-
    size content-addressed shards announced on the DHT catalog, and a
    joiner/restarted swarm restores by pulling distinct shards from
    distinct providers in parallel (full-blob download stays the
    fallback)."""

    # fp32 elements per shard of the flattened state (4 bytes each; the
    # default 1Mi elements = 4 MiB per shard). <= 0 disables the sharded
    # path entirely — serving, catalog announcements and sharded restore
    # all degrade to the single-provider full blob.
    shard_size: int = 1 << 20
    # concurrent shard downloads during a restore
    fetch_parallelism: int = 4
    # cap on distinct providers one restore spreads across (0 = all
    # announcing providers)
    providers: int = 0
    # local shard cache dir ("" = <output_dir>/shard_cache): fetched shards
    # persist here so a restore killed mid-flight RESUMES instead of
    # refetching; "none" disables the cache
    cache_dir: str = ""


@dataclass
class TelemetryArguments:
    """Swarm telemetry (dedloc_tpu/telemetry, docs/observability.md): a
    process-local registry of counters/histograms + span tracing across the
    DHT/averaging/optimizer seams. One flag: disabled (the default) costs
    one attribute load per instrumented site and emits nothing."""

    enabled: bool = False
    # per-peer JSONL event log ("" = in-memory trace only); rendered by
    # ``python tools/runlog_summary.py --health <events.jsonl> ...``
    event_log_path: str = ""
    # seconds between snapshots of this peer's counters onto the signed DHT
    # metrics bus (LocalMetrics.telemetry) — the coordinator aggregates them
    # into its swarm-health JSONL record
    snapshot_period: float = 30.0
    # how many per-link estimates (telemetry/links.py: RTT + goodput EWMAs
    # per destination, busiest first) ride each metrics-bus snapshot and
    # each link.stats event-log flush — bounds the signed record's size on
    # large swarms; the coordinator folds these into the swarm topology
    # record rendered by ``runlog_summary --topology``
    link_top_k: int = 8


@dataclass
class ServingArguments:
    """Swarm-sharded MoE serving (dedloc_tpu/serving, docs/serving.md):
    expert shards hosted across peers, discovered via the signed
    ``{prefix}_experts`` DHT namespace, routed latency/load-aware by the
    gateway with deadline/retry/hedge and a residual fall-through."""

    enabled: bool = False
    # gateway routing policy (serving/router.py RouterPolicy)
    refresh_period: float = 5.0  # expert-directory staleness bound, s
    request_deadline: float = 2.0  # total per-request budget, s
    attempt_timeout: float = 0.6  # per-attempt RPC timeout, s
    retries: int = 2  # extra attempts after the first
    backoff: float = 0.05  # base transport-failure backoff, doubled
    hedge_after: float = 0.3  # fire the runner-up after this wait, s
    # expert-host knobs (serving/host.py)
    capacity: int = 4096  # max tokens admitted per dispatch window
    announce_period: float = 10.0  # expert-record refresh cadence, s
    # per-peer token-bucket admission on the dispatch RPC (0 rate = open)
    admission_rate: float = 50.0
    admission_burst: float = 100.0
    # per-peer token-bucket admission on the DHT store RPC (0 = open; the
    # public-run hardening knob — over-rate stores are refused with a
    # named reason and counted under serve.rejected)
    store_rate: float = 0.0
    store_burst: float = 0.0


@dataclass
class AuthArguments:
    """Gated-run credentials (sahajbert/huggingface_auth.py capability):
    when ``username`` is set, the role fetches a signed access token from
    ``endpoint`` (default: the first initial peer, where the coordinator
    hosts the AuthService) and every matchmaking message rides signed
    envelopes."""

    username: str = ""
    credential: str = ""
    endpoint: str = ""  # "host:port"; empty = first initial peer


@dataclass
class CollaborationArguments:
    dht: DHTArguments = field(default_factory=DHTArguments)
    averager: AveragerArguments = field(default_factory=AveragerArguments)
    optimizer: CollaborativeOptimizerArguments = field(
        default_factory=CollaborativeOptimizerArguments
    )
    training: TrainingArguments = field(default_factory=TrainingArguments)
    auth: AuthArguments = field(default_factory=AuthArguments)
    telemetry: TelemetryArguments = field(default_factory=TelemetryArguments)
    checkpoint: CheckpointArguments = field(default_factory=CheckpointArguments)
    serving: ServingArguments = field(default_factory=ServingArguments)
    wandb_project: Optional[str] = None
    bandwidth: float = 1000.0


@dataclass
class SwAVTrainingArguments:
    """SwAV local-step recipe, mirroring swav_1node_resnet_submit.yaml
    (:33-37,68,93-104) + sgd_collaborative.py:145-157."""

    model_size: str = "resnet50"  # tiny (CI fixture) | resnet50
    image_folder: str = ""  # real images (flat or class-subdir layout);
    # empty = synthetic fixture. Decoded+augmented via the SwAV SimCLR stack.
    max_local_steps: int = 0  # accumulation boundaries to run (0 = forever)
    per_device_batch_size: int = 8
    gradient_accumulation_steps: int = 1
    learning_rate: float = 0.3  # LARC-SGD base lr (defaults.yaml SwAV recipe)
    momentum: float = 0.9
    weight_decay: float = 1e-6
    trust_coefficient: float = 0.001
    warmup_steps: int = 500
    total_steps: int = 100_000
    queue_length: int = 0  # per-peer embedding queue (0 = off)
    queue_start_step: int = 0  # global step gating use_queue (yaml :95)
    mesh_devices: int = 1  # >1: this peer is a whole slice (see trainer)
    mesh_device_offset: int = 0
    seed: int = 0
    output_dir: str = "outputs_swav"
    save_steps: int = 0
    save_total_limit: int = 2
    log_every: int = 10
    device_stats_every: int = 100  # HBM stats cadence (0 = off)


@dataclass
class SwAVCollaborationArguments:
    """Argument tree for the SwAV collaborative driver (the fork's
    SGDCollaborative defaults: target_batch_size 32768,
    sgd_collaborative.py:153)."""

    dht: DHTArguments = field(default_factory=DHTArguments)
    averager: AveragerArguments = field(default_factory=AveragerArguments)
    optimizer: CollaborativeOptimizerArguments = field(
        default_factory=lambda: CollaborativeOptimizerArguments(
            target_batch_size=32768,
            # sinkhorn gradients from tiny-batch volunteers are high-energy
            # noise (see contrib_clip_per_sample) — SwAV defaults the
            # contribution clip ON; ALBERT keeps it off (LAMB's apply-side
            # max_grad_norm already bounds that path and the converged
            # recipe predates the knob)
            contrib_clip_per_sample=2.0,
            # SwAV also defaults the contribution ramp ON: basin formation
            # is exactly where multi-peer gradient noise cost ~40% of the
            # probe (13.0% vs 22.4% solo, BASELINE.md round 5) — a fresh
            # joiner spends its first 10 rounds adopting the trunk's
            # direction before mixing at full weight
            ramp_rounds=10,
        )
    )
    training: SwAVTrainingArguments = field(
        default_factory=SwAVTrainingArguments
    )
    telemetry: TelemetryArguments = field(default_factory=TelemetryArguments)
    checkpoint: CheckpointArguments = field(default_factory=CheckpointArguments)
