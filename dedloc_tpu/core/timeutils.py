"""Time, expiration and throughput-EMA utilities.

Capability parity with the reference's use of ``hivemind.get_dht_time()``
(DHT-synchronized wall clock) and ``performance_ema.samples_per_second``
(reference: albert/run_trainer.py:145, albert/arguments.py:48-50).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Generic, Optional, TypeVar

DHTExpiration = float  # absolute unix timestamp after which a record is dead
MAX_DHT_TIME_DISCREPANCY = 3.0

_dht_time_offset = 0.0
# optional full override of the clock (None = wall clock + offset). The
# discrete-event simulator installs one so scenario time is EXACTLY the
# engine's virtual time: with only an offset, real seconds spent executing
# Python between fake-clock advances would leak into get_dht_time() and make
# two same-seed runs diverge wherever a deadline comparison is close.
_dht_time_source = None


def get_dht_time() -> DHTExpiration:
    """Wall-clock time shared across the collaboration.

    Peers are assumed NTP-synchronized (same assumption as the reference
    stack); ``set_dht_time_offset`` exists for tests that need a fake clock,
    and ``set_dht_time_source`` for the simulator's fully-virtual clock.
    """
    if _dht_time_source is not None:
        return _dht_time_source()
    return time.time() + _dht_time_offset


def set_dht_time_offset(offset: float) -> None:
    global _dht_time_offset
    _dht_time_offset = offset


def set_dht_time_source(source) -> None:
    """Install (or with None, remove) a zero-argument callable that REPLACES
    the wall clock entirely. Scenario code under the simulator engine sees a
    bit-reproducible timeline regardless of how long the host takes to
    execute it."""
    global _dht_time_source
    _dht_time_source = source


def monotonic() -> float:
    """Monotonic duration/deadline clock for simulator-reachable code.

    Production (no fake source, offset 0) is plain ``time.monotonic()``.
    Under ``FakeClock`` the offset advances it exactly with scenario time;
    under the discrete-event simulator the installed source replaces it
    entirely, so deadlines computed from it expire on the VIRTUAL timeline
    instead of counting real host-execution seconds. This is the approved
    clock the dedlint ``clock-monotonic`` rule points at — raw
    ``time.monotonic()``/``time.perf_counter()`` in sim-reachable modules
    is blind to both mechanisms (docs/contributor.md).

    Like ``get_dht_time()``, the value is DISCONTINUOUS across a frozen-
    source install/uninstall (`set_dht_time_source`): a timestamp taken on
    one side compared on the other yields nonsense ages. The standing
    contract (same one every FakeClock/simulator consumer already lives
    by) is that objects are created and driven on the SAME side — the sim
    engine spawns its peers inside the engine context, and FakeClock test
    scenarios construct their components inside the clock's scope."""
    if _dht_time_source is not None:
        return _dht_time_source()
    return time.monotonic() + _dht_time_offset


T = TypeVar("T")


@dataclass
class ValueWithExpiration(Generic[T]):
    value: T
    expiration_time: DHTExpiration

    def expired(self, now: Optional[DHTExpiration] = None) -> bool:
        return (now if now is not None else get_dht_time()) > self.expiration_time

    def __iter__(self):
        return iter((self.value, self.expiration_time))


class PerformanceEMA:
    """Exponential moving average of samples-per-second throughput.

    Matches the semantics consumed by the reference trainers via
    ``collaborative_optimizer.performance_ema.samples_per_second``
    (albert/run_trainer.py:145): updated once per local accumulation step with
    the number of samples processed; pausable while the peer is inside an
    averaging round so network time does not pollute compute throughput.
    """

    def __init__(self, alpha: float = 0.1, eps: float = 1e-20):
        self.alpha = alpha
        self.eps = eps
        self.ema_seconds_per_sample = 0.0
        self.samples_per_second = eps
        self.timestamp = time.perf_counter()
        self.paused = False
        self.num_updates = 0

    def update(self, num_processed: int) -> float:
        assert num_processed > 0, "must process at least one sample"
        now = time.perf_counter()
        elapsed = max(now - self.timestamp, 1e-9)
        self.timestamp = now
        if self.paused:
            return self.samples_per_second
        seconds_per_sample = elapsed / num_processed
        if self.num_updates == 0:
            self.ema_seconds_per_sample = seconds_per_sample
        else:
            self.ema_seconds_per_sample = (
                self.alpha * seconds_per_sample
                + (1 - self.alpha) * self.ema_seconds_per_sample
            )
        self.num_updates += 1
        self.samples_per_second = 1.0 / max(self.ema_seconds_per_sample, self.eps)
        return self.samples_per_second

    def pause(self) -> None:
        """Stop counting elapsed time (e.g. during an averaging round)."""
        self.paused = True

    def resume(self) -> None:
        self.paused = False
        self.timestamp = time.perf_counter()

    def __repr__(self):
        return f"PerformanceEMA({self.samples_per_second:.3f} samples/s)"


@dataclass
class TimedStorageEntry(Generic[T]):
    value: T
    expiration_time: DHTExpiration = field(default=0.0)
