from dedloc_tpu.core.timeutils import get_dht_time, DHTExpiration, PerformanceEMA
from dedloc_tpu.core.serialization import (
    CompressionType,
    serialize_array,
    deserialize_array,
    serialize_tree,
    deserialize_tree,
)
from dedloc_tpu.core.config import Registry
