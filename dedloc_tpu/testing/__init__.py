"""Deterministic testing utilities: fault injection + fake clock."""
from dedloc_tpu.testing.faults import (  # noqa: F401
    FakeClock,
    Fault,
    FaultSchedule,
)
