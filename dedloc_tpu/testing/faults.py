"""Deterministic fault injection + fake clock for peer-lifecycle tests.

Multi-peer failure paths (leader death mid-matchmaking, truncated state
downloads, stragglers past SLA, join-during-round) used to be exercised only
by real-time churn harnesses that flake on a loaded host. This module makes
fault behavior a first-class, deterministically-testable mechanism:

- ``FaultSchedule``: a seeded schedule of named fault points. Tests program
  faults (``inject``); instrumented code consults the schedule (``fire``)
  at well-known points and applies the returned action. The schedule logs
  every observation and firing so tests can assert exactly what happened.
- ``FakeClock``: scenario time. All matchmaking windows, straggler SLAs and
  DHT record expirations are deadlines on ``get_dht_time()``, so advancing
  the shared offset (``set_dht_time_offset``) expires them instantly —
  scenarios that used to be wall-clock soaks become reproducible unit tests
  that never idle out a real window.

Fault points currently wired:

| point                  | where                                   | context keys |
|------------------------|-----------------------------------------|--------------|
| ``rpc.client.call``    | ``RPCClient.call`` before the frame     | method, endpoint, client |
| ``rpc.server.dispatch``| ``RPCServer._dispatch`` before handler  | method, peer, server, port |
| ``averager.state_get`` | state-snapshot reply (blob mutation)    | size |
| ``checkpoint.shard_get`` | sharded-checkpoint shard reply (bytes mutation) | index, size |
| ``fleet.preempt``      | ``LocalFleet`` victim selection         | alive |
| ``averager.hier_wan``  | delegate's WAN leg of a hierarchical round | round_id, delegate |
| ``topology.plan_record`` | plan publish/fetch (``averaging/planwire.py``; ``drop`` = record lost in flight, others raise) | op, epoch (publish only) |

Actions: ``drop`` (reset the connection / raise ConnectionResetError —
process-death semantics: a killed peer's OS resets its sockets), ``delay``
(hold the RPC for ``delay`` seconds), ``error`` (raise an OSError),
``truncate`` (cut a state blob to ``fraction`` of its bytes, leaving the
checksum stale), ``kill`` (run ``callback`` — e.g. stop a server — then
reset the connection).

The hooks are zero-cost when no schedule is installed: instrumented code
checks the module-level ``_active`` attribute and returns immediately.
Production never installs a schedule.
"""
from __future__ import annotations

import asyncio
import inspect
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import heapq

from dedloc_tpu.core.timeutils import set_dht_time_offset, set_dht_time_source


@dataclass
class Fault:
    """One programmed fault. ``times`` bounds how often it fires (-1 =
    unlimited); ``match`` filters on the fire-site context dict; ``target``
    names a specific victim (fleet preemption); ``callback`` runs for
    ``kill`` actions (sync or async)."""

    point: str
    action: str  # drop | delay | error | truncate | kill
    times: int = 1
    match: Optional[Callable[[Dict[str, Any]], bool]] = None
    delay: float = 0.0
    fraction: float = 0.5
    target: Optional[str] = None
    callback: Optional[Callable[..., Any]] = None


class FaultSchedule:
    """Seeded schedule of named fault points.

    Usage::

        with FaultSchedule(seed=0) as schedule:
            schedule.inject("rpc.server.dispatch", "drop",
                            match=lambda ctx: ctx["method"] == "mm.join")
            ... run the scenario ...
            assert schedule.fired  # the fault actually triggered

    ``rng`` is the schedule's seeded randomness — harnesses that need a
    random choice (e.g. fleet victim selection) draw from it so the whole
    scenario replays from one seed.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.faults: List[Fault] = []
        # (point, context) logs: every consultation, and every actual firing
        self.observed: List[Tuple[str, Dict[str, Any]]] = []
        self.fired: List[Tuple[str, Dict[str, Any]]] = []

    def inject(
        self,
        point: str,
        action: str,
        *,
        times: int = 1,
        match: Optional[Callable[[Dict[str, Any]], bool]] = None,
        delay: float = 0.0,
        fraction: float = 0.5,
        target: Optional[str] = None,
        callback: Optional[Callable[..., Any]] = None,
    ) -> Fault:
        fault = Fault(point, action, times, match, delay, fraction, target,
                      callback)
        self.faults.append(fault)
        return fault

    def fire(self, point: str, **context: Any) -> Optional[Fault]:
        """Called by instrumented code at a fault point; returns the fault
        to apply (consuming one of its ``times``), or None."""
        self.observed.append((point, context))
        for fault in self.faults:
            if fault.point != point or fault.times == 0:
                continue
            if fault.match is not None and not fault.match(context):
                continue
            if fault.target is not None:
                # a targeted fault only fires when its victim is actually in
                # the offered candidate set — otherwise it stays ARMED (not
                # consumed) so "kill trainer1" still means trainer1 on a
                # later tick, never a silent random victim
                candidates = context.get("alive")
                if candidates is not None and fault.target not in candidates:
                    continue
            if fault.times > 0:
                fault.times -= 1
            self.fired.append((point, context))
            # every injected fault is a trace event (docs/observability.md):
            # the process-global registry gets the schedule-level view; the
            # instrumented site additionally attributes a "fault.applied"
            # event to its own per-peer registry. Import deferred — the
            # production fast path (no schedule installed) never pays it.
            from dedloc_tpu.telemetry import registry as telemetry

            if telemetry._active is not None:
                telemetry._active.counter("faults.injected").inc()
                telemetry._active.event(
                    "fault.injected", point=point, action=fault.action,
                    **{
                        k: v
                        for k, v in context.items()
                        if isinstance(v, (str, int, float, bool, bytes))
                    },
                )
            return fault
        return None

    # ------------------------------------------------------- install/uninstall

    def install(self) -> "FaultSchedule":
        global _active
        _active = self
        return self

    def uninstall(self) -> None:
        global _active
        if _active is self:
            _active = None

    def __enter__(self) -> "FaultSchedule":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


# the installed schedule; instrumented code checks this attribute directly
# (``faults._active is not None``) so the production fast path is one load
_active: Optional[FaultSchedule] = None


def active() -> Optional[FaultSchedule]:
    return _active


def fire(point: str, **context: Any) -> Optional[Fault]:
    """Consult the installed schedule (None when fault injection is off)."""
    return _active.fire(point, **context) if _active is not None else None


async def apply_transport_fault(fault: Fault, what: str) -> None:
    """Apply a client/server transport fault inside the event loop. ``drop``
    and ``kill`` raise (the caller sees a dead peer); ``delay`` returns
    after sleeping; ``error`` raises an OSError."""
    if fault.action == "delay":
        await asyncio.sleep(fault.delay)
        return
    if fault.action == "kill" and fault.callback is not None:
        result = fault.callback()
        if inspect.isawaitable(result):
            await result
    if fault.action in ("drop", "kill"):
        raise ConnectionResetError(f"fault injected: dropped {what}")
    if fault.action == "error":
        raise OSError(f"fault injected: error on {what}")


class ClockHandle:
    """Cancellation handle for a ``FakeClock.wake_at`` sleeper.

    ``cancel`` notifies the owning clock so cancelled sleepers are counted
    (and compacted) EAGERLY instead of lingering until they surface at the
    head of the schedule — a churn wave that cancels thousands of pending
    wakes must not leave the clock walking dead entries for the rest of
    the run."""

    __slots__ = ("cancelled", "_clock")

    def __init__(self, clock: Optional["FakeClock"] = None) -> None:
        self.cancelled = False
        self._clock = clock

    def cancel(self) -> None:
        if not self.cancelled:
            self.cancelled = True
            if self._clock is not None:
                self._clock._note_cancelled()


class FakeClock:
    """Deterministic scenario clock over ``set_dht_time_offset``.

    All DHT expirations, matchmaking windows and straggler deadlines are
    absolute timestamps on ``get_dht_time()``; with a FakeClock installed
    they only expire when the test calls ``advance`` — a loaded host can
    never spuriously time a scenario out, and a test never sleeps real
    time to wait a window out.

    The offset is process-global (every in-process peer shares the DHT
    clock, as NTP-synchronized real peers would), and restored to zero on
    exit.

    **Sleepers and the seeded tie-break.** ``wake_at(when, callback)``
    registers a callback fired by ``advance`` when scenario time reaches
    ``when``. Two sleepers registered for the IDENTICAL fake timestamp used
    to resolve in heap insertion order — an implementation detail of
    ``heapq`` that is not promised across Python versions, so simulator
    runs were not bit-reproducible. The documented ordering rule is now:
    same-deadline sleepers fire in the order of a per-sleeper draw from the
    clock's seeded RNG (``seed`` constructor arg), taken at REGISTRATION
    time. Given the same seed and the same registration sequence, the wake
    order is a pure function of the schedule on every Python version; a
    different seed may legally produce a different (but equally
    deterministic) order. The discrete-event engine
    (``simulator/engine.py``) draws the same stream via
    ``tiebreak_epsilon`` for its event-loop timers, so one seed governs
    every same-timestamp decision in a simulated swarm.

    **Frozen mode.** ``frozen=True`` additionally installs a full
    ``get_dht_time`` override returning exactly ``start + advanced``: real
    seconds spent EXECUTING scenario code between advances no longer leak
    into the timeline (with only an offset they would, because the offset
    rides on ``time.time()``). The simulator engine uses this; offset-only
    behavior is unchanged for existing tests.
    """

    # timer-wheel geometry. Sleeper rows are binned by ABSOLUTE slot index
    # (``int(when / slot_width)``) into sparse dict-of-bucket levels, so
    # there is no modulo wrap to disambiguate: bucket index order IS time
    # order. Level 0 holds the dominant near-term band (sub-second waits),
    # level 1 the next ~65 s, and everything farther rides an overflow
    # heap. Scheduling is O(1); firing sorts one bucket at a time (each
    # row is sorted exactly once, amortized O(log bucket)).
    _L0_SLOT_S = 1e-3
    _L0_SPAN_S = 0.256
    _L1_SLOT_S = 0.256
    _L1_SPAN_S = 65.536

    def __init__(self, start: float = 0.0, seed: int = 0,
                 frozen: bool = False):
        self.offset = float(start)
        self.frozen = bool(frozen)
        self.rng = random.Random(seed)
        # rows: (when, tiebreak, seq, callback, handle) — ``tiebreak`` is
        # the seeded draw that defines same-deadline order; ``seq`` only
        # breaks the astronomically-unlikely equal-draw case. Rows live in
        # the wheel buckets / overflow heap; during an ``advance_to`` drain
        # the due ones move to the ``_due`` heap, which replays them in
        # exact (when, tiebreak, seq) order.
        self._l0: Dict[int, List[tuple]] = {}
        self._l1: Dict[int, List[tuple]] = {}
        self._l0_idx: List[int] = []  # heaps of occupied bucket indices
        self._l1_idx: List[int] = []
        self._overflow: List[tuple] = []
        self._due: List[tuple] = []
        self._drain_target: Optional[float] = None
        self._live = 0  # pending, not cancelled
        self._cancelled_resident = 0  # cancelled but still occupying a row
        # merged next-deadline cursor: the engine polls ``next_wake`` every
        # virtual tick, so the earliest pending deadline is cached and
        # updated incrementally on insert — an idle swarm pays O(1) per
        # tick, not a wheel scan. ``None`` = "no sleepers", ``()`` = stale.
        self._next_wake_cache: Any = None
        self._seq = 0

    # ------------------------------------------------------------- sleepers

    def tiebreak_epsilon(self, scale: float = 1e-6) -> float:
        """A strictly-positive seeded jitter in ``(0, ~2*scale]`` for the
        engine's event-loop timer deadlines. Two components, both
        deterministic functions of the schedule: a seeded draw at ``scale``
        (dominates — same-deadline ordering follows the seeded stream, not
        timer-heap internals) plus a strictly-increasing sequence term
        three orders of magnitude smaller, which keeps two epsilons
        distinct even when their draws round to the same float (at
        simulation-epoch magnitudes a float's resolution is ~1e-10 s, so a
        pure nano-scale draw would quantize to a handful of values and
        collide — reintroducing heap-order nondeterminism)."""
        self._seq += 1
        return (
            (1.0 - self.rng.random()) * scale
            + (self._seq % 1000 + 1) * scale * 1e-3
        )

    def wake_at(self, when: float, callback: Callable[[], Any]) -> ClockHandle:
        """Register ``callback`` to fire when scenario time reaches
        ``when`` (fired inside ``advance``, never from real time)."""
        handle = ClockHandle(self)
        # the seeded draw MUST stay one-per-registration, taken here, in
        # registration order — it is the documented same-deadline tie-break
        # stream, and tests cross-check it against an independent
        # ``random.Random(seed)``
        row = (float(when), self.rng.random(), self._seq, callback, handle)
        self._seq += 1
        self._live += 1
        self._place(row)
        cache = self._next_wake_cache
        if cache is None or (cache != () and row[0] < cache):
            self._next_wake_cache = row[0]
        return handle

    def _place(self, row: tuple) -> None:
        """Bin one row into the wheel level (or overflow heap) its distance
        from now selects; rows due within an in-progress drain go straight
        to the drain's replay heap."""
        when = row[0]
        if self._drain_target is not None and when <= self._drain_target:
            heapq.heappush(self._due, row)
            return
        delta = when - self.offset
        if delta < self._L0_SPAN_S:
            buckets, idx_heap = self._l0, self._l0_idx
            idx = int(when // self._L0_SLOT_S)
        elif delta < self._L1_SPAN_S:
            buckets, idx_heap = self._l1, self._l1_idx
            idx = int(when // self._L1_SLOT_S)
        else:
            heapq.heappush(self._overflow, row)
            return
        bucket = buckets.get(idx)
        if bucket is None:
            buckets[idx] = [row]
            heapq.heappush(idx_heap, idx)
        else:
            bucket.append(row)

    def _note_cancelled(self) -> None:
        """Eager cancellation accounting (called by ``ClockHandle.cancel``):
        move one row from live to cancelled-resident and compact the wheel
        once dead rows outnumber live ones — a mass-cancel churn wave must
        not leave the schedule mostly tombstones."""
        self._live -= 1
        self._cancelled_resident += 1
        self._next_wake_cache = ()  # the cancelled row may have been the min
        if self._cancelled_resident > 64 and \
                self._cancelled_resident > self._live:
            self._compact()

    def _compact(self) -> None:
        rows = [
            row
            for bucket_map in (self._l0, self._l1)
            for bucket in bucket_map.values()
            for row in bucket
            if not row[4].cancelled
        ]
        rows += [row for row in self._overflow if not row[4].cancelled]
        due = [row for row in self._due if not row[4].cancelled]
        self._l0.clear()
        self._l1.clear()
        self._l0_idx.clear()
        self._l1_idx.clear()
        self._overflow.clear()
        self._due.clear()
        if due:
            self._due = due
            heapq.heapify(self._due)
        self._cancelled_resident = 0
        for row in rows:
            self._place(row)

    def _level_min(self, buckets: Dict[int, List[tuple]],
                   idx_heap: List[int]) -> Optional[float]:
        """Earliest live deadline in one wheel level: the lowest-indexed
        bucket's min (bucket index order is time order). Cancelled rows
        encountered on the way are dropped for good."""
        while idx_heap:
            idx = idx_heap[0]
            bucket = buckets.get(idx)
            if not bucket:
                heapq.heappop(idx_heap)
                buckets.pop(idx, None)
                continue
            best = None
            live = []
            for row in bucket:
                if row[4].cancelled:
                    self._cancelled_resident -= 1
                    continue
                live.append(row)
                if best is None or row[0] < best:
                    best = row[0]
            if not live:
                heapq.heappop(idx_heap)
                buckets.pop(idx, None)
                continue
            if len(live) != len(bucket):
                buckets[idx] = live
            return best
        return None

    def next_wake(self) -> Optional[float]:
        """Earliest pending sleeper deadline, or None (cached between
        schedule mutations — the engine's merged-cursor read)."""
        cache = self._next_wake_cache
        if cache != ():
            return cache
        best = self._level_min(self._l0, self._l0_idx)
        l1_best = self._level_min(self._l1, self._l1_idx)
        if l1_best is not None and (best is None or l1_best < best):
            best = l1_best
        overflow = self._overflow
        while overflow and overflow[0][4].cancelled:
            heapq.heappop(overflow)
            self._cancelled_resident -= 1
        if overflow and (best is None or overflow[0][0] < best):
            best = overflow[0][0]
        self._next_wake_cache = best
        return best

    def _pull_due(self, target: float) -> None:
        """Move every row with ``when <= target`` from the wheel levels and
        the overflow heap onto the ``_due`` replay heap."""
        due = self._due
        for buckets, idx_heap, slot_s in (
            (self._l0, self._l0_idx, self._L0_SLOT_S),
            (self._l1, self._l1_idx, self._L1_SLOT_S),
        ):
            # compare in INDEX space with the same floor division used by
            # ``_place``: float division is monotone, so ``when <= target``
            # always implies ``row_idx <= target_idx`` and a row can never
            # be stranded in a bucket the pull considers "later"
            target_idx = int(target // slot_s)
            while idx_heap:
                idx = idx_heap[0]
                bucket = buckets.get(idx)
                if not bucket:
                    heapq.heappop(idx_heap)
                    buckets.pop(idx, None)
                    continue
                if idx > target_idx:
                    break  # every later bucket is strictly later still
                if idx < target_idx:  # bucket entirely due
                    heapq.heappop(idx_heap)
                    buckets.pop(idx, None)
                    for row in bucket:
                        heapq.heappush(due, row)
                    continue
                keep = [row for row in bucket if row[0] > target]
                for row in bucket:
                    if row[0] <= target:
                        heapq.heappush(due, row)
                if keep:
                    buckets[idx] = keep
                else:
                    heapq.heappop(idx_heap)
                    buckets.pop(idx, None)
                break  # rows past this partially-due bucket are all later
        overflow = self._overflow
        while overflow and overflow[0][0] <= target:
            heapq.heappush(due, heapq.heappop(overflow))

    def _fire_due(self) -> None:
        """Fire every sleeper already due at the current offset."""
        self.advance_to(self.offset)

    def sleeper_stats(self) -> Dict[str, int]:
        """Schedule occupancy, for diagnostics and regression tests:
        ``live`` pending sleepers, ``resident`` rows actually held (live +
        cancelled tombstones awaiting compaction)."""
        resident = (
            sum(len(b) for b in self._l0.values())
            + sum(len(b) for b in self._l1.values())
            + len(self._overflow)
            + len(self._due)
        )
        return {
            "live": self._live,
            "resident": resident,
            "cancelled_resident": self._cancelled_resident,
            # lifetime registrations (wake_at rows + tie-break draws): the
            # bench's "timer events scheduled" numerator — deterministic for
            # a given seed+scenario, so events/sec isolates wall-time cost
            "scheduled_total": self._seq,
        }

    # ------------------------------------------------------------ lifecycle

    def now(self) -> float:
        return self.offset

    def __enter__(self) -> "FakeClock":
        set_dht_time_offset(self.offset)
        if self.frozen:
            set_dht_time_source(self.now)
        return self

    def advance(self, seconds: float) -> None:
        self.advance_to(self.offset + float(seconds))

    def advance_to(self, target: float) -> None:
        """Move scenario time forward to ``target``, firing due sleepers in
        deadline order (seeded tie-break within one deadline); each sleeper
        observes the clock AT its own deadline."""
        target = float(target)
        if self._live:
            previous_target = self._drain_target
            self._drain_target = target
            self._pull_due(target)
            due = self._due
            consumed = bool(due)
            while due:
                row = heapq.heappop(due)
                if row[4].cancelled:
                    self._cancelled_resident -= 1
                    continue
                self._live -= 1
                when = row[0]
                if when > self.offset:
                    self.offset = when
                    set_dht_time_offset(when)
                row[3]()  # may register new due sleepers: _place routes
                # anything <= target straight onto this replay heap
            self._drain_target = previous_target
            if consumed:
                self._next_wake_cache = ()
        if target > self.offset:
            self.offset = target
        set_dht_time_offset(self.offset)

    def __exit__(self, *exc) -> None:
        set_dht_time_offset(0.0)
        if self.frozen:
            set_dht_time_source(None)
