"""Deterministic fault injection + fake clock for peer-lifecycle tests.

Multi-peer failure paths (leader death mid-matchmaking, truncated state
downloads, stragglers past SLA, join-during-round) used to be exercised only
by real-time churn harnesses that flake on a loaded host. This module makes
fault behavior a first-class, deterministically-testable mechanism:

- ``FaultSchedule``: a seeded schedule of named fault points. Tests program
  faults (``inject``); instrumented code consults the schedule (``fire``)
  at well-known points and applies the returned action. The schedule logs
  every observation and firing so tests can assert exactly what happened.
- ``FakeClock``: scenario time. All matchmaking windows, straggler SLAs and
  DHT record expirations are deadlines on ``get_dht_time()``, so advancing
  the shared offset (``set_dht_time_offset``) expires them instantly —
  scenarios that used to be wall-clock soaks become reproducible unit tests
  that never idle out a real window.

Fault points currently wired:

| point                  | where                                   | context keys |
|------------------------|-----------------------------------------|--------------|
| ``rpc.client.call``    | ``RPCClient.call`` before the frame     | method, endpoint, client |
| ``rpc.server.dispatch``| ``RPCServer._dispatch`` before handler  | method, peer, server, port |
| ``averager.state_get`` | state-snapshot reply (blob mutation)    | size |
| ``checkpoint.shard_get`` | sharded-checkpoint shard reply (bytes mutation) | index, size |
| ``fleet.preempt``      | ``LocalFleet`` victim selection         | alive |

Actions: ``drop`` (reset the connection / raise ConnectionResetError —
process-death semantics: a killed peer's OS resets its sockets), ``delay``
(hold the RPC for ``delay`` seconds), ``error`` (raise an OSError),
``truncate`` (cut a state blob to ``fraction`` of its bytes, leaving the
checksum stale), ``kill`` (run ``callback`` — e.g. stop a server — then
reset the connection).

The hooks are zero-cost when no schedule is installed: instrumented code
checks the module-level ``_active`` attribute and returns immediately.
Production never installs a schedule.
"""
from __future__ import annotations

import asyncio
import inspect
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from dedloc_tpu.core.timeutils import set_dht_time_offset


@dataclass
class Fault:
    """One programmed fault. ``times`` bounds how often it fires (-1 =
    unlimited); ``match`` filters on the fire-site context dict; ``target``
    names a specific victim (fleet preemption); ``callback`` runs for
    ``kill`` actions (sync or async)."""

    point: str
    action: str  # drop | delay | error | truncate | kill
    times: int = 1
    match: Optional[Callable[[Dict[str, Any]], bool]] = None
    delay: float = 0.0
    fraction: float = 0.5
    target: Optional[str] = None
    callback: Optional[Callable[..., Any]] = None


class FaultSchedule:
    """Seeded schedule of named fault points.

    Usage::

        with FaultSchedule(seed=0) as schedule:
            schedule.inject("rpc.server.dispatch", "drop",
                            match=lambda ctx: ctx["method"] == "mm.join")
            ... run the scenario ...
            assert schedule.fired  # the fault actually triggered

    ``rng`` is the schedule's seeded randomness — harnesses that need a
    random choice (e.g. fleet victim selection) draw from it so the whole
    scenario replays from one seed.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.faults: List[Fault] = []
        # (point, context) logs: every consultation, and every actual firing
        self.observed: List[Tuple[str, Dict[str, Any]]] = []
        self.fired: List[Tuple[str, Dict[str, Any]]] = []

    def inject(
        self,
        point: str,
        action: str,
        *,
        times: int = 1,
        match: Optional[Callable[[Dict[str, Any]], bool]] = None,
        delay: float = 0.0,
        fraction: float = 0.5,
        target: Optional[str] = None,
        callback: Optional[Callable[..., Any]] = None,
    ) -> Fault:
        fault = Fault(point, action, times, match, delay, fraction, target,
                      callback)
        self.faults.append(fault)
        return fault

    def fire(self, point: str, **context: Any) -> Optional[Fault]:
        """Called by instrumented code at a fault point; returns the fault
        to apply (consuming one of its ``times``), or None."""
        self.observed.append((point, context))
        for fault in self.faults:
            if fault.point != point or fault.times == 0:
                continue
            if fault.match is not None and not fault.match(context):
                continue
            if fault.target is not None:
                # a targeted fault only fires when its victim is actually in
                # the offered candidate set — otherwise it stays ARMED (not
                # consumed) so "kill trainer1" still means trainer1 on a
                # later tick, never a silent random victim
                candidates = context.get("alive")
                if candidates is not None and fault.target not in candidates:
                    continue
            if fault.times > 0:
                fault.times -= 1
            self.fired.append((point, context))
            # every injected fault is a trace event (docs/observability.md):
            # the process-global registry gets the schedule-level view; the
            # instrumented site additionally attributes a "fault.applied"
            # event to its own per-peer registry. Import deferred — the
            # production fast path (no schedule installed) never pays it.
            from dedloc_tpu.telemetry import registry as telemetry

            if telemetry._active is not None:
                telemetry._active.counter("faults.injected").inc()
                telemetry._active.event(
                    "fault.injected", point=point, action=fault.action,
                    **{
                        k: v
                        for k, v in context.items()
                        if isinstance(v, (str, int, float, bool, bytes))
                    },
                )
            return fault
        return None

    # ------------------------------------------------------- install/uninstall

    def install(self) -> "FaultSchedule":
        global _active
        _active = self
        return self

    def uninstall(self) -> None:
        global _active
        if _active is self:
            _active = None

    def __enter__(self) -> "FaultSchedule":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


# the installed schedule; instrumented code checks this attribute directly
# (``faults._active is not None``) so the production fast path is one load
_active: Optional[FaultSchedule] = None


def active() -> Optional[FaultSchedule]:
    return _active


def fire(point: str, **context: Any) -> Optional[Fault]:
    """Consult the installed schedule (None when fault injection is off)."""
    return _active.fire(point, **context) if _active is not None else None


async def apply_transport_fault(fault: Fault, what: str) -> None:
    """Apply a client/server transport fault inside the event loop. ``drop``
    and ``kill`` raise (the caller sees a dead peer); ``delay`` returns
    after sleeping; ``error`` raises an OSError."""
    if fault.action == "delay":
        await asyncio.sleep(fault.delay)
        return
    if fault.action == "kill" and fault.callback is not None:
        result = fault.callback()
        if inspect.isawaitable(result):
            await result
    if fault.action in ("drop", "kill"):
        raise ConnectionResetError(f"fault injected: dropped {what}")
    if fault.action == "error":
        raise OSError(f"fault injected: error on {what}")


class FakeClock:
    """Deterministic scenario clock over ``set_dht_time_offset``.

    All DHT expirations, matchmaking windows and straggler deadlines are
    absolute timestamps on ``get_dht_time()``; with a FakeClock installed
    they only expire when the test calls ``advance`` — a loaded host can
    never spuriously time a scenario out, and a test never sleeps real
    time to wait a window out.

    The offset is process-global (every in-process peer shares the DHT
    clock, as NTP-synchronized real peers would), and restored to zero on
    exit.
    """

    def __init__(self, start: float = 0.0):
        self.offset = float(start)

    def __enter__(self) -> "FakeClock":
        set_dht_time_offset(self.offset)
        return self

    def advance(self, seconds: float) -> None:
        self.offset += float(seconds)
        set_dht_time_offset(self.offset)

    def __exit__(self, *exc) -> None:
        set_dht_time_offset(0.0)
